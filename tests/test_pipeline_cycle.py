"""Overlapped fleet cycle chaos ring (DESIGN §10).

The pipelined cycle moves commit I/O — journal fsync, BindRequest/evict/
status writes, binder round trips — onto a commit-executor thread so it
overlaps the next cycle's host prep and device work.  This suite proves
the hard part, correctness:

- PLACEMENT BIT-IDENTITY: a randomized churn stream (seeded by
  ``KAI_FAULT_SEED``; ``chaos_matrix --pipeline`` sweeps it) produces
  the exact same {pod -> node} bind decisions serial and pipelined —
  asserted on the full decision history, not the surviving state;
- the SPECULATIVE VIEW makes cycle N's in-flight placements visible to
  cycle N+1's snapshot before any write lands (no double-bind while
  commits are stalled);
- a FENCED DEPOSE mid-overlap rolls the speculative view back and
  poisons the pipeline (the deposed instance never commits);
- CRASH-AFTER-JOURNAL during an overlapped commit replays cleanly
  through the startup reconcile pass;
- BREAKER-OPEN drains the pipeline back to the serial path with no
  lost placements;
- watch-event COALESCING (satellite): a MODIFIED burst collapses to its
  latest resourceVersion before subscriber delivery, lifecycle
  boundaries intact;
- BATCHED EVICTION writes (satellite): the reclaim path's evictions
  route through the async status updater, one flush per gang batch,
  fencing preserved.
"""

from __future__ import annotations

import os
import threading

import pytest

from kai_scheduler_tpu.controllers import System, SystemConfig
from kai_scheduler_tpu.controllers.cache_builder import ClusterCache
from kai_scheduler_tpu.controllers.kubeapi import (FENCE_NAMESPACE,
                                                   Fenced,
                                                   InMemoryKubeAPI,
                                                   make_pod)
from kai_scheduler_tpu.framework.conf import SchedulerConfig
from kai_scheduler_tpu.framework.pipeline import CommitExecutor
from kai_scheduler_tpu.utils.commitlog import CommitLog, SimulatedCrash
from kai_scheduler_tpu.utils.deviceguard import (configure_device_guard,
                                                 reset_device_guard)
from kai_scheduler_tpu.utils.metrics import METRICS

pytestmark = pytest.mark.chaos

SEED = int(os.environ.get("KAI_FAULT_SEED", "0"))


def make_node(api, name, gpu=8):
    api.create({"kind": "Node", "metadata": {"name": name}, "spec": {},
                "status": {"allocatable": {
                    "cpu": "64", "memory": "512Gi",
                    "nvidia.com/gpu": gpu, "pods": 110}}})


def make_queue(api, name="q"):
    api.create({"kind": "Queue", "metadata": {"name": name}, "spec": {}})


def build_system(pipelined: bool, n_nodes=6, n_queues=3,
                 commitlog_path=None) -> System:
    from kai_scheduler_tpu.controllers import ShardSpec
    cfg = SchedulerConfig(actions=["allocate"])
    system = System(SystemConfig(
        shards=[ShardSpec(config=cfg)],
        pipelined_cycles=pipelined,
        commitlog_path=commitlog_path))
    for i in range(n_nodes):
        make_node(system.api, f"n{i}")
    for i in range(n_queues):
        make_queue(system.api, f"q{i}")
    system.drain()
    return system


class BindRecorder:
    """Decision history: every BindRequest the scheduler ever wrote,
    {pod name -> selected node} (last decision wins).  Recorded from the
    watch stream so GC/supersede cannot erase history."""

    def __init__(self, api):
        self.decisions: dict[str, str] = {}
        api.watch("BindRequest", self._on_event)

    def _on_event(self, event_type, obj):
        if event_type in ("ADDED", "MODIFIED"):
            spec = obj.get("spec", {})
            if spec.get("podName") and spec.get("selectedNode"):
                self.decisions[spec["podName"]] = spec["selectedNode"]


# ---------------------------------------------------------------------------
# (1) Placement bit-identity under randomized churn
# ---------------------------------------------------------------------------

class TestPipelinedParity:
    CYCLES = 8

    def _script_and_run_serial(self, rng):
        """Drive the serial system with a seeded churn stream, recording
        the externally-applied operations as a name-based script the
        pipelined run replays verbatim."""
        system = build_system(pipelined=False)
        api = system.api
        recorder = BindRecorder(api)
        script = []
        serial = 0
        for _cycle in range(self.CYCLES):
            ops = []
            n_submit = int(rng.integers(2, 9))
            for _ in range(n_submit):
                name = f"churn-{serial:04d}"
                serial += 1
                queue = f"q{int(rng.integers(0, 3))}"
                gpu = int(rng.integers(0, 2))
                ops.append(("submit", name, queue, gpu))
            bound = sorted(p["metadata"]["name"] for p in api.list("Pod")
                           if p["spec"].get("nodeName")
                           and not p["metadata"].get("deletionTimestamp"))
            rng.shuffle(bound)
            for name in bound[:int(len(bound) * 0.25)]:
                ops.append(("complete", name))
            for name in bound[int(len(bound) * 0.25):
                              int(len(bound) * 0.35)]:
                ops.append(("evict", name))
            script.append(ops)
            self._apply_ops(api, ops)
            system.run_cycle()
            self._finalize_terminations(api)
        system.run_cycle()
        script.append([])
        return script, recorder.decisions, self._final_map(api)

    @staticmethod
    def _apply_ops(api, ops):
        for op in ops:
            if op[0] == "submit":
                _kind, name, queue, gpu = op
                api.create(make_pod(name, queue=queue, gpu=gpu))
            elif op[0] == "complete":
                api.delete("Pod", op[1])
            elif op[0] == "evict":
                pod = api.get_opt("Pod", op[1])
                if pod is not None:
                    pod["metadata"]["deletionTimestamp"] = "evicted"
                    api.update(pod)

    @staticmethod
    def _finalize_terminations(api):
        # Kubelet analog: terminations complete at the cycle boundary.
        for p in api.list("Pod"):
            if p["metadata"].get("deletionTimestamp"):
                api.delete("Pod", p["metadata"]["name"],
                           p["metadata"].get("namespace", "default"))

    @staticmethod
    def _final_map(api):
        return {p["metadata"]["name"]: p["spec"].get("nodeName")
                for p in api.list("Pod")}

    def test_pipelined_matches_serial_randomized_churn(self):
        """The acceptance assert: identical decision history AND
        identical final pod->node state, exactly — no tolerance."""
        import numpy as np
        rng = np.random.default_rng(1000 + SEED)
        script, serial_decisions, serial_final = \
            self._script_and_run_serial(rng)

        system = build_system(pipelined=True)
        api = system.api
        recorder = BindRecorder(api)
        for ops in script[:-1]:
            self._apply_ops(api, ops)
            system.run_cycle()
            # The churn's termination arm runs at the cycle boundary on
            # the driving thread, like the serial run — through the
            # control-locked drain so it cannot race the epilogue.
            system.flush_pipeline()
            self._finalize_terminations(api)
        system.run_cycle()
        system.flush_pipeline()
        system.drain()

        assert recorder.decisions == serial_decisions, \
            "pipelined bind decisions diverged from serial mode"
        assert self._final_map(api) == serial_final
        # And the pipeline actually pipelined: stage C ran on the
        # executor (not silently serialized back into the cycle).
        assert system.commit_executor.stats()["completed"] > 0
        assert len(system.pipeline_stats) == self.CYCLES + 1

    def test_pipelined_overlap_without_boundary_flush(self):
        """Same stream, NO per-cycle flush — commits genuinely overlap
        the next cycles.  Decision history must still match (the
        speculative view keeps every snapshot equivalent); liveness
        invariants: no double-bind, no node oversubscription."""
        import numpy as np
        rng = np.random.default_rng(1000 + SEED)
        script, serial_decisions, _serial_final = \
            self._script_and_run_serial(rng)

        system = build_system(pipelined=True)
        api = system.api
        recorder = BindRecorder(api)
        for ops in script:
            # Only name-based ops that cannot depend on bind timing are
            # replayed without a flush: completes/evicts of pods the
            # serial run saw bound may still be mid-flight here, which
            # is exactly the overlap under test.
            self._apply_ops(api, ops)
            system.run_cycle()
            with system._control_lock:
                self._finalize_terminations(api)
        system.flush_pipeline()
        system.run_cycle()
        system.flush_pipeline()
        system.drain()

        assert recorder.decisions == serial_decisions
        # Zero double-binds: one live BindRequest per pod was the store
        # invariant; here assert no node ever oversubscribed its GPUs.
        per_node: dict[str, int] = {}
        for pod in api.list("Pod"):
            node = pod["spec"].get("nodeName")
            if not node:
                continue
            req = pod["spec"]["containers"][0]["resources"]["requests"]
            per_node[node] = per_node.get(node, 0) + \
                int(req.get("nvidia.com/gpu", 0) or 0)
        assert all(v <= 8 for v in per_node.values()), per_node


# ---------------------------------------------------------------------------
# (2) Speculative view: no double-bind while commits are stalled
# ---------------------------------------------------------------------------

class TestSpeculativeView:
    def test_stalled_commits_do_not_double_schedule(self):
        system = build_system(pipelined=True, n_nodes=1)
        api = system.api
        ex = system.commit_executor
        release = threading.Event()
        ex.submit(release.wait, label="stall")

        for i in range(4):
            api.create(make_pod(f"p{i}", queue="q0", gpu=1))
        system.drain()
        system.run_cycle()
        cache = system.schedulers[0].cache
        specced = cache.speculation_stats()["entries"]
        assert specced == 4, "decisions must be speculatively visible"
        assert api.list("BindRequest") == [], "writes must be in flight"

        # Next cycle BEFORE any write landed: the snapshot sees the
        # speculative placements as BOUND — nothing re-schedules.
        system.run_cycle()
        assert cache.speculation_stats()["entries"] == specced, \
            "second cycle re-scheduled speculatively-placed pods"

        release.set()
        system.flush_pipeline()
        system.drain()
        bound = {p["metadata"]["name"]: p["spec"].get("nodeName")
                 for p in api.list("Pod")}
        assert all(node == "n0" for node in bound.values()), bound
        assert len(bound) == 4
        # The epilogue released the speculative view once echoes landed.
        assert cache.speculation_stats()["entries"] == 0

    def test_snapshot_reports_overlay(self):
        system = build_system(pipelined=True, n_nodes=1)
        api = system.api
        ex = system.commit_executor
        release = threading.Event()
        ex.submit(release.wait, label="stall")
        api.create(make_pod("pov", queue="q0", gpu=1))
        system.drain()
        system.run_cycle()
        system.run_cycle()
        stats = system.schedulers[0].cache.last_snapshot_stats
        assert stats.get("speculative_overlaid", 0) >= 1
        release.set()
        system.flush_pipeline()


# ---------------------------------------------------------------------------
# (3) Fenced depose mid-overlap
# ---------------------------------------------------------------------------

class TestFencedOverlap:
    def test_depose_mid_overlap_rolls_back_speculation(self):
        system = build_system(pipelined=True, n_nodes=2)
        api = system.api
        api.create({"kind": "Lease",
                    "metadata": {"name": "sched",
                                 "namespace": FENCE_NAMESPACE},
                    "spec": {"epoch": 1}})
        system.set_fence("sched", lambda: 1)
        ex = system.commit_executor
        release = threading.Event()
        ex.submit(release.wait, label="stall")

        for i in range(3):
            api.create(make_pod(f"f{i}", queue="q0", gpu=1))
        system.drain()
        rollbacks0 = METRICS.counters.get(
            "pipeline_speculation_rollback_total", 0)
        system.run_cycle()
        cache = system.schedulers[0].cache
        assert cache.speculation_stats()["entries"] == 3

        # A new leader takes over while our commit batch is stalled.
        lease = api.get("Lease", "sched", FENCE_NAMESPACE)
        lease["spec"]["epoch"] = 2
        api.update(lease)
        release.set()
        ex.wait_token(ex.token())

        # The batch hit the fence: no write landed, the speculative view
        # rolled back, the executor poisoned.
        assert api.list("BindRequest") == []
        assert cache.speculation_stats()["entries"] == 0
        assert ex.poisoned is not None and "fenced" in ex.poisoned
        assert METRICS.counters.get(
            "pipeline_speculation_rollback_total", 0) - rollbacks0 == 3
        assert METRICS.counters.get("pipeline_fenced_commits_total", 0) >= 1

        # The next cycle drains the pipeline back to the serial path —
        # where the (still-deposed) instance aborts loudly, exactly like
        # the pre-pipeline fencing behavior.
        drained0 = METRICS.counters.get("pipeline_drained_to_serial_total",
                                        0)
        system.run_cycle()
        assert METRICS.counters.get(
            "pipeline_drained_to_serial_total", 0) == drained0 + 1
        ssn = system.schedulers[0].last_session
        assert ssn.aborted is not None and "epoch" in ssn.aborted
        assert api.list("BindRequest") == []

    def test_partial_batch_keeps_landed_writes(self):
        """Depose BETWEEN two commit batches: the first batch's writes
        stand (they carried a then-valid epoch), only the second rolls
        back — a serial mid-commit depose behaves identically."""
        system = build_system(pipelined=True, n_nodes=2)
        api = system.api
        api.create({"kind": "Lease",
                    "metadata": {"name": "sched",
                                 "namespace": FENCE_NAMESPACE},
                    "spec": {"epoch": 1}})
        system.set_fence("sched", lambda: 1)
        ex = system.commit_executor

        api.create(make_pod("early", queue="q0", gpu=1))
        system.drain()
        system.run_cycle()
        system.flush_pipeline()   # first decision commits + binds cleanly
        assert api.get("Pod", "early")["spec"].get("nodeName")

        release = threading.Event()
        ex.submit(release.wait, label="stall")
        api.create(make_pod("late", queue="q0", gpu=1))
        system.drain()
        system.run_cycle()
        lease = api.get("Lease", "sched", FENCE_NAMESPACE)
        lease["spec"]["epoch"] = 2
        api.update(lease)
        release.set()
        ex.wait_token(ex.token())
        # The first cycle's bind stands (its write carried a then-valid
        # epoch, and its BindRequest was already consumed + GC'd); the
        # deposed second cycle's decision never reached the store.
        assert api.get("Pod", "early")["spec"].get("nodeName")
        assert not api.get("Pod", "late")["spec"].get("nodeName")
        assert not [br for br in api.list("BindRequest")
                    if br["spec"]["podName"] == "late"]
        assert ex.poisoned is not None


# ---------------------------------------------------------------------------
# (4) Crash-after-journal during an overlapped commit
# ---------------------------------------------------------------------------

class TestOverlappedJournalCrash:
    def test_crash_after_journal_replays_cleanly(self, tmp_path,
                                                 monkeypatch):
        log_path = str(tmp_path / "bind.journal")
        system = build_system(pipelined=True, n_nodes=1,
                              commitlog_path=log_path)
        api = system.api
        api.create(make_pod("victim", queue="q0", gpu=1))
        system.drain()
        monkeypatch.setenv("KAI_FAULT_INJECT", "crash-after-journal")
        system.run_cycle()
        with pytest.raises(SimulatedCrash):
            system.flush_pipeline()
        monkeypatch.delenv("KAI_FAULT_INJECT")
        # Intents durable, nothing committed, executor dead (poisoned).
        assert api.list("BindRequest") == []
        assert CommitLog(log_path).pending_intents()
        assert system.commit_executor.poisoned == "crash-after-journal"

        # ---- restart: same store, same journal, fresh process ----
        system2 = System(SystemConfig(commitlog_path=log_path), api=api)
        summary = system2.startup_reconcile()
        assert summary["lost_commits"] == 1
        assert system2.commitlog.pending_intents() == []
        for _ in range(3):
            system2.run_cycle()
        assert api.get("Pod", "victim")["spec"].get("nodeName") == "n0"


# ---------------------------------------------------------------------------
# (5) Breaker-open drains the pipeline to the serial path
# ---------------------------------------------------------------------------

class TestBreakerDrainsToSerial:
    def test_breaker_open_drains_to_serial_no_lost_placements(
            self, monkeypatch):
        system = build_system(pipelined=True, n_nodes=2)
        api = system.api
        api.create(make_pod("ok-pod", queue="q0", gpu=1))
        system.drain()
        system.run_cycle()
        system.flush_pipeline()
        piped_cycles = len(system.pipeline_stats)
        assert piped_cycles >= 1

        # Device path dies: the breaker opens mid-overlap.
        monkeypatch.setenv("KAI_FAULT_INJECT", "error")
        configure_device_guard(fault="error", retries=0,
                               breaker_threshold=1)
        try:
            api.create(make_pod("degraded-pod", queue="q0", gpu=1))
            system.drain()
            system.run_cycle()   # trips the breaker (CPU fallback binds)
            system.flush_pipeline()
            api.create(make_pod("serial-pod", queue="q0", gpu=1))
            system.drain()
            system.run_cycle()   # breaker open -> serial path
            system.run_cycle()
            # Serial cycles do not grow the pipeline stats ring.
            assert len(system.pipeline_stats) <= piped_cycles + 1
            bound = {p["metadata"]["name"] for p in api.list("Pod")
                     if p["spec"].get("nodeName")}
            assert {"ok-pod", "degraded-pod",
                    "serial-pod"} <= bound, bound
            assert system.schedulers[0].cache.speculation_stats()[
                "entries"] == 0
        finally:
            monkeypatch.delenv("KAI_FAULT_INJECT")
            reset_device_guard()


# ---------------------------------------------------------------------------
# (6) Watch-event coalescing (satellite)
# ---------------------------------------------------------------------------

class TestWatchCoalescing:
    def test_modified_burst_collapses_to_latest_rv(self):
        api = InMemoryKubeAPI()
        seen = []
        api.watch("ConfigMap", lambda et, obj: seen.append(
            (et, obj["metadata"]["resourceVersion"])))
        obj = api.create({"kind": "ConfigMap",
                          "metadata": {"name": "cm"}, "spec": {}})
        before = METRICS.counters.get("watch_events_coalesced_total", 0)
        for i in range(30):
            obj["spec"]["v"] = i
            api.update(obj)
        final_rv = obj["metadata"]["resourceVersion"]
        api.drain()
        kinds = [et for et, _rv in seen]
        assert kinds == ["ADDED", "MODIFIED"], kinds
        # The one delivered MODIFIED carries the NEWEST resourceVersion:
        # no subscriber ever observes a stale rv after a newer one.
        assert seen[-1] == ("MODIFIED", final_rv)
        assert METRICS.counters.get(
            "watch_events_coalesced_total", 0) - before == 29

    def test_lifecycle_boundaries_survive_coalescing(self):
        api = InMemoryKubeAPI()
        seen = []
        api.watch("ConfigMap", lambda et, obj: seen.append(et))
        obj = api.create({"kind": "ConfigMap",
                          "metadata": {"name": "cm"}, "spec": {}})
        obj["spec"]["v"] = 1
        api.update(obj)
        api.delete("ConfigMap", "cm")
        obj2 = api.create({"kind": "ConfigMap",
                           "metadata": {"name": "cm"}, "spec": {}})
        obj2["spec"]["v"] = 2
        api.update(obj2)
        api.drain()
        # The pre-delete MODIFIED coalesced into the post-recreate one;
        # ADDED/DELETED boundaries delivered intact, in order.
        assert seen == ["ADDED", "DELETED", "ADDED", "MODIFIED"], seen

    def test_coalesce_keeps_newest_distinct_payload(self):
        """HTTP-substrate shape: each queued MODIFIED is a DISTINCT
        snapshot — coalescing must keep exactly the newest rv."""
        from kai_scheduler_tpu.controllers.kubeapi import coalesce_events
        evs = [("MODIFIED",
                {"kind": "Pod",
                 "metadata": {"name": "p", "namespace": "default",
                              "resourceVersion": str(i)}})
               for i in range(5)]
        out = coalesce_events(list(evs))
        assert out == [evs[-1]]

    def test_unrelated_keys_not_coalesced(self):
        api = InMemoryKubeAPI()
        seen = []
        api.watch("ConfigMap", lambda et, obj: seen.append(
            obj["metadata"]["name"]))
        a = api.create({"kind": "ConfigMap", "metadata": {"name": "a"},
                        "spec": {}})
        b = api.create({"kind": "ConfigMap", "metadata": {"name": "b"},
                        "spec": {}})
        a["spec"]["v"] = 1
        api.update(a)
        b["spec"]["v"] = 1
        api.update(b)
        api.drain()
        assert seen == ["a", "b", "a", "b"]


# ---------------------------------------------------------------------------
# (7) Batched eviction writes (satellite)
# ---------------------------------------------------------------------------

class _Victim:
    def __init__(self, name, uid=None, namespace="default"):
        self.name = name
        self.uid = uid or f"uid-{name}"
        self.namespace = namespace


class TestEvictBatch:
    def _system_with_pods(self, n=5):
        system = build_system(pipelined=False, n_nodes=1)
        api = system.api
        for i in range(n):
            api.create(make_pod(f"v{i}", queue="q0", gpu=1,
                                node_name="n0", phase="Running"))
        system.drain()
        return system

    def test_evict_many_batches_through_async_updater(self):
        system = self._system_with_pods(5)
        cache = system.schedulers[0].cache
        before = METRICS.counters.get("evict_writes_batched_total", 0)
        n = cache.evict_many([_Victim(f"v{i}") for i in range(5)])
        assert n == 5
        assert METRICS.counters.get(
            "evict_writes_batched_total", 0) - before == 5
        # One flush per gang batch: by return, every eviction is applied.
        for i in range(5):
            pod = system.api.get("Pod", f"v{i}")
            assert pod["metadata"].get("deletionTimestamp")
            assert any(c["type"] == "TerminationByKaiScheduler"
                       for c in pod["status"].get("conditions", []))

    def test_evict_many_fenced_depose_raises(self):
        system = self._system_with_pods(2)
        api = system.api
        api.create({"kind": "Lease",
                    "metadata": {"name": "sched",
                                 "namespace": FENCE_NAMESPACE},
                    "spec": {"epoch": 5}})
        system.set_fence("sched", lambda: 4)  # stale incarnation
        cache = system.schedulers[0].cache
        with pytest.raises(Fenced):
            cache.evict_many([_Victim("v0"), _Victim("v1")])
        assert not api.get("Pod", "v0")["metadata"].get(
            "deletionTimestamp")

    def test_evict_many_falls_back_without_updater(self):
        api = InMemoryKubeAPI()
        make_node(api, "n0")
        api.create(make_pod("solo", node_name="n0", phase="Running"))
        cache = ClusterCache(api)   # no status updater attached
        assert cache.evict_many([_Victim("solo")]) == 1
        assert api.get("Pod", "solo")["metadata"].get("deletionTimestamp")


# ---------------------------------------------------------------------------
# (7b) Unschedulable-status dedupe (satellite)
# ---------------------------------------------------------------------------

class TestStatusDedupe:
    def test_identical_unschedulable_condition_not_rewritten(self):
        system = build_system(pipelined=False, n_nodes=1)
        api = system.api
        # Unschedulable forever: demands more GPU than the cluster has.
        api.create(make_pod("giant", queue="q0", gpu=99))
        system.drain()
        system.run_cycle()
        pg = api.list("PodGroup")[0]
        rv_after_first = pg["metadata"]["resourceVersion"]
        cond = [c for c in pg["status"]["conditions"]
                if c["type"] == "Unschedulable"]
        assert cond and cond[0]["status"] == "True"
        before = METRICS.counters.get("status_writes_deduped_total", 0)
        for _ in range(3):
            system.run_cycle()
        pg = api.list("PodGroup")[0]
        # The identical verdict was NOT rewritten: the object's
        # resourceVersion never moved, so the incremental cache never
        # re-parses the backlog group cycle after cycle.
        assert pg["metadata"]["resourceVersion"] == rv_after_first
        assert METRICS.counters.get(
            "status_writes_deduped_total", 0) - before >= 3

    def test_changed_verdict_still_writes(self):
        system = build_system(pipelined=False, n_nodes=1)
        api = system.api
        api.create(make_pod("giant2", queue="q0", gpu=99))
        system.drain()
        system.run_cycle()
        pg = api.list("PodGroup")[0]
        # Force a different recorded message, as if the verdict changed:
        # the next cycle must overwrite it with the live reason.
        for c in pg["status"]["conditions"]:
            if c["type"] == "Unschedulable":
                c["message"] = "stale different reason"
        api.update(pg)
        rv_stale = pg["metadata"]["resourceVersion"]
        system.run_cycle()
        pg = api.list("PodGroup")[0]
        assert pg["metadata"]["resourceVersion"] != rv_stale
        cond = [c for c in pg["status"]["conditions"]
                if c["type"] == "Unschedulable"]
        assert cond[0]["message"] != "stale different reason"


# ---------------------------------------------------------------------------
# (8) Commit executor unit behavior
# ---------------------------------------------------------------------------

class TestCommitExecutor:
    def test_fifo_order_and_flush(self):
        ex = CommitExecutor(name="t-exec")
        out = []
        for i in range(10):
            ex.submit(lambda i=i: out.append(i))
        ex.flush()
        assert out == list(range(10))
        ex.stop()

    def test_errors_surface_at_flush_not_silently(self):
        ex = CommitExecutor(name="t-exec-err")
        ex.submit(lambda: (_ for _ in ()).throw(RuntimeError("boom")))
        done = []
        ex.submit(lambda: done.append(1))
        with pytest.raises(RuntimeError, match="boom"):
            ex.flush()
        assert done == [1], "an error must not wedge later batches"
        ex.stop()

    def test_poison_skips_queued_work_and_rejects_submissions(self):
        ex = CommitExecutor(name="t-exec-poison")
        release = threading.Event()
        ran = []
        ex.submit(release.wait)
        ex.submit(lambda: ran.append(1))
        ex.poison("test poison")
        release.set()
        ex.wait_token(ex.token())
        assert ran == [], "queued work must be skipped once poisoned"
        from kai_scheduler_tpu.framework.pipeline import \
            CommitExecutorPoisoned
        with pytest.raises(CommitExecutorPoisoned):
            ex.submit(lambda: None)
        ex.clear_poison()
        ex.submit(lambda: ran.append(2))
        ex.flush()
        assert ran == [2]
        ex.stop()

    def test_busy_accounting_bounded(self):
        ex = CommitExecutor(name="t-exec-busy")
        import time
        t0 = time.monotonic()
        for _ in range(5):
            ex.submit(lambda: time.sleep(0.002))
        ex.flush()
        busy = ex.busy_seconds(t0, time.monotonic())
        assert 0.005 <= busy <= 5.0
        ex.stop()
