"""Async status updater: worker-pool writes with in-flight dedup
(cache/status_updater concurrency analog)."""

import time

from kai_scheduler_tpu.controllers import InMemoryKubeAPI
from kai_scheduler_tpu.controllers.status_updater import AsyncStatusUpdater


def test_patches_apply_asynchronously():
    api = InMemoryKubeAPI()
    api.create({"kind": "PodGroup", "metadata": {"name": "pg"},
                "spec": {}, "status": {"phase": "Pending"}})
    upd = AsyncStatusUpdater(api, num_workers=2)
    upd.patch_status("PodGroup", "pg", "default", {"phase": "Running"})
    upd.flush()
    assert api.get("PodGroup", "pg")["status"]["phase"] == "Running"
    upd.stop()


def test_inflight_dedup_keeps_latest():
    api = InMemoryKubeAPI()
    api.create({"kind": "PodGroup", "metadata": {"name": "pg"},
                "spec": {}, "status": {}})
    upd = AsyncStatusUpdater(api, num_workers=1)
    # Hold the dedup lock (reentrant) so the worker cannot pop payloads
    # while the three patches queue up.
    with upd._lock:
        for phase in ("A", "B", "C"):
            upd.patch_status("PodGroup", "pg", "default", {"phase": phase})
    upd.flush()
    # Only the latest queued payload lands (no A-then-C interleaving).
    assert api.get("PodGroup", "pg")["status"]["phase"] == "C"
    upd.stop()


def test_events_flow():
    api = InMemoryKubeAPI()
    upd = AsyncStatusUpdater(api)
    upd.record_event("Unschedulable", "no nodes fit")
    upd.flush()
    events = api.list("Event")
    assert len(events) == 1
    assert events[0]["spec"]["reason"] == "Unschedulable"
    upd.stop()
