"""Kernel-parity smoke: diff the fused allocation ladder against its
references in one command.

For each seed, a randomized gang workload runs through:

- the legacy grouped kernel (the committed reference formulation),
- the fused-jnp rung (``fused_mode="jnp"``),
- the Pallas rung in interpreter mode (``fused_mode="pallas"``),
- the exact per-task kernel (``ops/allocate.allocate_jobs_kernel``),

and every pairing must agree bit-for-bit on placements, pipelined flags
and job success.  This is the ci_check.sh gate that catches a fused-path
drift WITHOUT waiting for the full pytest ring; at `--seeds N` it doubles
as a longer offline sweep.

Usage (ci_check.sh runs --smoke):

    JAX_PLATFORMS=cpu python -m kai_scheduler_tpu.tools.kernel_parity \
        [--smoke | --seeds N] [--nodes N]
"""

from __future__ import annotations

import argparse
import sys
import time


def _instance(seed: int, n_nodes: int, n_jobs: int):
    import numpy as np
    rng = np.random.default_rng(seed)
    alloc = np.tile([8000.0, 64e9, 8.0], (n_nodes, 1))
    idle = alloc.copy()
    idle[:, 2] -= rng.integers(0, 6, n_nodes)
    rel = np.zeros((n_nodes, 3))
    rel[:, 2] = rng.integers(0, 3, n_nodes)
    labels = np.full((n_nodes, 1), -1, np.int32)
    labels[: n_nodes // 2, 0] = 0
    taints = np.full((n_nodes, 1), -1, np.int32)
    room = np.full(n_nodes, 110.0)
    reqs, jobs, sels = [], [], []
    for j in range(n_jobs):
        gang = int(rng.integers(1, 6))
        gpu = float(rng.integers(0, 4))
        s = 0 if rng.random() < 0.3 else -1
        for _ in range(gang):
            reqs.append([1000.0, 1e9, gpu])
            jobs.append(j)
            sels.append(s)
    allowed = np.ones(n_jobs, bool)
    if n_jobs > 2:
        allowed[int(rng.integers(n_jobs))] = False
    return (alloc, idle, rel, labels, taints, room, np.array(reqs),
            np.array(jobs, np.int32), np.array(sels, np.int32)[:, None],
            np.full((len(reqs), 1), -1, np.int32), allowed)


def run_seed(seed: int, n_nodes: int, n_jobs: int) -> list[str]:
    """One seed through every rung; returns mismatch descriptions."""
    import jax.numpy as jnp
    import numpy as np

    from ..ops.allocate import allocate_jobs_kernel
    from ..ops.allocate_grouped import allocate_grouped

    (alloc, idle, rel, labels, taints, room, req, job, sel, tol,
     allowed) = _instance(seed, n_nodes, n_jobs)
    nodes = tuple(map(jnp.asarray,
                      (alloc, idle, rel, labels, taints, room)))
    outs = {
        # kailint: disable=KAI004 — offline parity sweep, no Session to dispatch through
        mode: allocate_grouped(nodes, req, job, sel, tol, allowed,
                               fused_mode=mode)
        for mode in ("legacy", "jnp", "pallas")
    }
    # kailint: disable=KAI004 — offline parity sweep, no Session to dispatch through
    exact = allocate_jobs_kernel(*nodes, jnp.asarray(req),
                                 jnp.asarray(job), jnp.asarray(sel),
                                 jnp.asarray(tol), jnp.asarray(allowed))
    problems = []
    ref = outs["legacy"]
    for mode in ("jnp", "pallas"):
        for field in ("placements", "pipelined", "job_success"):
            a = np.asarray(getattr(ref, field))
            b = np.asarray(getattr(outs[mode], field))
            if not (a == b).all():
                problems.append(
                    f"seed {seed}: {mode} != legacy on {field} "
                    f"({int((a != b).sum())} rows)")
    for field in ("placements", "pipelined", "job_success"):
        a = np.asarray(getattr(exact, field))
        b = np.asarray(getattr(ref, field))
        if not (a == b).all():
            problems.append(
                f"seed {seed}: legacy grouped != exact kernel on {field}")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("kai-kernel-parity")
    ap.add_argument("--seeds", type=int, default=6,
                    help="number of randomized workloads to sweep")
    ap.add_argument("--nodes", type=int, default=24)
    ap.add_argument("--jobs", type=int, default=6)
    ap.add_argument("--smoke", action="store_true",
                    help="2-seed quick gate (the ci_check.sh step)")
    args = ap.parse_args(argv)
    seeds = range(2 if args.smoke else args.seeds)

    failures = []
    t0 = time.perf_counter()
    for seed in seeds:
        problems = run_seed(seed, args.nodes, args.jobs)
        status = "ok  " if not problems else "FAIL"
        print(f"{status} seed {seed}  (legacy/jnp/pallas/exact agree)"
              if not problems else f"{status} seed {seed}", flush=True)
        for p in problems:
            print("     " + p, flush=True)
        failures += problems
    dt = time.perf_counter() - t0
    if failures:
        print(f"kernel parity: FAILED ({len(failures)} mismatch(es) "
              f"in {dt:.1f}s)")
        return 1
    print(f"kernel parity: all rungs bit-identical over "
          f"{len(list(seeds))} seed(s) in {dt:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
