"""kairace — whole-program thread-role & lock-contract analyzer.

Built on the kailint engine chassis (3-pass rules, fingerprint
baseline, ``# kairace: disable=`` suppressions, text/JSON CLI, exit
codes 0/1/2) and the shared lock-scope collector
(``tools/kailint/lockscope.py``).  See docs/STATIC_ANALYSIS.md for the
KRC rule catalog, the thread-role table, and the single-writer
annotation how-to; ``utils/locktrace.py`` + ``chaos_matrix --races``
validate the static lock graph against observed runtime orders.
"""

from .cli import build_engine, lock_graph, main, role_table
from .rules import RULE_CLASSES, default_rules

__all__ = ["build_engine", "default_rules", "lock_graph", "main",
           "role_table", "RULE_CLASSES"]
