"""kairace whole-program model: thread roles, lock scopes, access facts.

This is the analysis substrate under the KRC rules (``rules.py``).  One
pass over every module builds a :class:`Program`:

- **Functions** — every def/method/nested def/lambda gets a ``FuncId``
  ``(module path, class name or None, qualified name)`` and a scan of
  its *executed* body (nested function bodies are deferred code and
  belong to their own FuncId).

- **Thread roles** — entry points are discovered statically:
  ``threading.Thread(target=...)``, ``<executor>.submit(fn)``,
  ``watch``/``watch_any``/``watch_sync``/``on_resync``/``on_drain_idle``
  hook registrations, and ``BaseHTTPRequestHandler`` subclasses.  A
  *runs-on* set then propagates over the call graph to a fixpoint;
  functions with no in-tree callers and no entry seed run on ``main``.

- **Lock scopes** — the shared collector (``kailint/lockscope.py``)
  names every synchronization attribute by TYPE, honors
  ``Condition(lock)`` aliasing, and canonical lock names
  (``Class.attr`` / ``module.GLOBAL``) make guard sets comparable
  program-wide.  Guard sets are **interprocedural**: a function called
  only from inside ``with self._control_lock:`` blocks inherits that
  guard (the meet over its call sites), so the operator's
  control-epilogue discipline is visible to the rules without lexical
  locks in every callee.

- **Acquisition order** — every acquisition records edges from each
  already-held lock (lexical + inherited + transitively via callees),
  giving the static lock graph that KRC002 cycles over and the
  ``KAI_LOCKTRACE`` runtime validator (``utils/locktrace.py``) checks
  observed orders against.

Single-writer annotations: ``# kairace: single-writer=<role>[,<role>]``
on (or immediately above) a ``self.attr = ...`` assignment declares the
only roles allowed to mutate that field after ``__init__``; KRC003
enforces the declaration and KRC001/4/5 defer to it.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field

from ..kailint.astutil import dotted_name, resolve_relative_import
from ..kailint.lockscope import (ModuleLocks, collect_module_locks,
                                 lockish_name)

FuncId = tuple  # (module path, class name | None, qualified func name)

SPAWN_THREAD_CTORS = {"threading.Thread", "Thread", "threading.Timer",
                      "Timer"}
HOOK_METHODS = {"watch", "watch_any", "watch_sync", "on_resync",
                "on_drain_idle"}
HTTP_HANDLER_BASES = {"BaseHTTPRequestHandler",
                      "http.server.BaseHTTPRequestHandler",
                      "SimpleHTTPRequestHandler"}

# Method names that mutate the receiver container in place.
MUTATOR_METHODS = {"append", "add", "update", "pop", "popitem", "clear",
                   "extend", "remove", "discard", "insert", "setdefault",
                   "sort", "reverse"}

# Names excluded from unique-method-name call resolution: shadowed by
# builtin container/IO/threading methods, so `self._inflight.get(...)`
# never resolves to some in-tree class's `get`.
CHA_BLOCKLIST = {
    "get", "put", "set", "add", "pop", "run", "join", "wait", "send",
    "read", "write", "close", "open", "start", "stop", "items", "keys",
    "values", "append", "extend", "update", "clear", "copy", "sort",
    "reverse", "index", "count", "split", "strip", "seek", "flush",
    "remove", "discard", "insert", "setdefault", "popitem", "popleft",
    "appleft", "appendleft", "acquire", "release", "notify", "notify_all",
    "wait_for", "is_set", "cancel", "encode", "decode", "format",
    "search", "match", "sub", "findall", "group", "dump", "dumps",
    "load", "loads", "next", "submit", "result", "done", "empty",
    "qsize", "task_done", "get_nowait", "put_nowait", "list", "dict",
    "keys", "exists", "mkdir", "name", "kind", "path",
}

ANNOTATION_RE = re.compile(
    r"#\s*kairace:\s*single-writer\s*=\s*"
    r"(?P<roles>[A-Za-z0-9_.\-]+(?:\s*,\s*[A-Za-z0-9_.\-]+)*)")

MAIN_ROLE = "main"
HOOK_ROLE = "hook"
HTTP_ROLE = "http-handler"
EXECUTOR_ROLE = "executor"


@dataclass
class Access:
    """One field read/write: ``target`` is ``(class, attr)`` for
    instance fields or ``("<module stem>", name)`` for globals."""
    kind: str            # read | write
    write_kind: str      # "" | bind | aug | item | mutcall | deep | del
    target: tuple
    func: FuncId
    path: str
    line: int
    col: int
    lexical_guards: frozenset
    in_init: bool


@dataclass
class CallSite:
    caller: FuncId
    callee: FuncId
    line: int
    lexical_held: frozenset


@dataclass
class Spawn:
    """Thread/executor/hook entry point discovered at a call site."""
    role: str
    target: FuncId | None   # None: external callable (serve_forever)
    path: str
    line: int
    func: FuncId            # function containing the spawn site
    self_attr_args: tuple   # bare `self.<attr>` positional args (KRC005)
    kind: str               # thread | submit | hook


@dataclass
class FuncInfo:
    fid: FuncId
    node: ast.AST
    path: str
    cls: str | None
    is_init: bool


@dataclass
class Program:
    functions: dict = field(default_factory=dict)     # FuncId -> FuncInfo
    calls: list = field(default_factory=list)         # [CallSite]
    accesses: list = field(default_factory=list)      # [Access]
    spawns: list = field(default_factory=list)        # [Spawn]
    # (class, attr) -> declared single-writer role set
    annotations: dict = field(default_factory=dict)
    # (class, attr) -> (path, line) of the annotation (for KRC003 msgs)
    annotation_sites: dict = field(default_factory=dict)
    # canonical lock name -> [(path, line)] creation sites
    lock_sites: dict = field(default_factory=dict)
    # acquisition-order edges: (held, acquired) -> (path, line) sample
    order_edges: dict = field(default_factory=dict)
    # FuncId -> runs-on role set (after propagation)
    roles: dict = field(default_factory=dict)
    # FuncId -> interprocedurally inherited guard set H(f)
    inherited_guards: dict = field(default_factory=dict)
    # class name -> module path (first definition wins)
    class_module: dict = field(default_factory=dict)
    # per-class excluded attrs (locks/events/queues — sync primitives)
    sync_attrs: dict = field(default_factory=dict)
    # (class, attr) -> True when assigned a mutable container literal
    mutable_fields: dict = field(default_factory=dict)

    def guards_at(self, access: Access) -> frozenset:
        return access.lexical_guards | self.inherited_guards.get(
            access.func, frozenset())

    def roles_of(self, fid: FuncId) -> frozenset:
        return self.roles.get(fid, frozenset((MAIN_ROLE,)))


def _comment_lines(source: str) -> dict[int, str]:
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError):
        for i, raw in enumerate(source.splitlines(), 1):
            if "#" in raw:
                out[i] = raw
    return out


def _mod_stem(path: str) -> str:
    base = path.rsplit("/", 1)[-1]
    return base[:-3] if base.endswith(".py") else base


class _ModuleFacts:
    """Per-module resolution state built before body scanning."""

    def __init__(self, path: str, tree: ast.Module, source: str):
        self.path = path
        self.tree = tree
        self.source = source
        self.stem = _mod_stem(path)
        self.module_name = path[:-3].replace("/", ".") \
            if path.endswith(".py") else path.replace("/", ".")
        self.locks: ModuleLocks | None = None    # filled in pass 2
        # alias -> (module_name, symbol) for `from X import y [as a]`
        self.imports: dict[str, tuple] = {}
        # alias -> module_name for `import X [as a]`
        self.module_imports: dict[str, str] = {}
        # class name -> {method name -> FuncId}
        self.class_methods: dict[str, dict] = {}
        # top-level function name -> FuncId
        self.module_funcs: dict = {}
        # classes whose methods run on the http-handler role
        self.handler_classes: set = set()
        # (class, attr) -> lambda FuncId  (self.x = lambda ...)
        self.attr_lambdas: dict = {}
        self.comments = _comment_lines(source)

    def collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom):
                mod = resolve_relative_import(self.module_name, node)
                if mod is None:
                    continue
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = \
                        (mod, alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    self.module_imports[alias.asname or alias.name] = \
                        alias.name


class ProgramBuilder:
    def __init__(self, modules: list):
        """``modules``: [(path, tree, source)]."""
        self.program = Program()
        self.mods = [_ModuleFacts(p, t, s) for p, t, s in modules]
        self.by_module_name = {m.module_name: m for m in self.mods}
        # global name tables
        self.all_classes: dict[str, _ModuleFacts] = {}
        # method name -> [(class, FuncId)] for unique-name resolution
        self.methods_by_name: dict[str, list] = {}

    # -- pass 1: declarations ---------------------------------------------
    def _index_functions(self, mod: _ModuleFacts) -> None:
        prog = self.program

        def qual(parts: list[str]) -> str:
            return ".".join(parts)

        def visit(node, cls: str | None, prefix: list[str]) -> None:
            if isinstance(node, ast.ClassDef):
                self.all_classes.setdefault(node.name, mod)
                prog.class_module.setdefault(node.name, mod.path)
                mod.class_methods.setdefault(node.name, {})
                if any((dotted_name(b) or "").split(".")[-1]
                       in {b.split(".")[-1] for b in HTTP_HANDLER_BASES}
                       for b in node.bases):
                    mod.handler_classes.add(node.name)
                for child in ast.iter_child_nodes(node):
                    visit(child, node.name, prefix + [node.name])
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fid = (mod.path, cls, qual(prefix + [node.name]))
                prog.functions[fid] = FuncInfo(
                    fid, node, mod.path, cls,
                    is_init=node.name in ("__init__", "__post_init__"))
                if cls is not None and len(prefix) >= 1 and \
                        prefix[-1] == cls:
                    mod.class_methods[cls][node.name] = fid
                    self.methods_by_name.setdefault(node.name, []) \
                        .append((cls, fid))
                elif cls is None and not prefix:
                    mod.module_funcs[node.name] = fid
                for child in ast.iter_child_nodes(node):
                    visit(child, cls, prefix + [node.name])
                return
            for child in ast.iter_child_nodes(node):
                visit(child, cls, prefix)

        visit(mod.tree, None, [])

    # -- lock naming --------------------------------------------------------
    def canonical_lock(self, mod: _ModuleFacts, cls: str | None,
                       node: ast.AST) -> str | None:
        """Canonical program-wide name for a lock expression; None when
        the expression is not a lock.  Unresolvable lockish expressions
        get an opaque ``?dotted`` name (distinct, excluded from cycle
        detection)."""
        locks = mod.locks
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id == "self" and cls:
                decl = locks.class_locks.get(cls, {}).get(node.attr)
                if decl is not None:
                    return f"{cls}.{locks.resolve_alias(cls, node.attr)}"
                if node.attr in locks.class_events.get(cls, set()):
                    return None
                if lockish_name(node):
                    return f"{cls}.{node.attr}"
                return None
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and cls:
                owner = locks.attr_classes.get(cls, {}).get(base.attr)
                if owner:
                    omod = self.all_classes.get(owner, mod)
                    odecl = (omod.locks or locks).class_locks.get(
                        owner, {}).get(node.attr)
                    if odecl is not None:
                        return f"{owner}." + \
                            (omod.locks or locks).resolve_alias(
                                owner, node.attr)
        elif isinstance(node, ast.Name):
            decl = locks.module_locks.get(node.id)
            if decl is not None:
                return f"{mod.stem}.{node.id}"
            if node.id in locks.module_events:
                return None
            if lockish_name(node):
                return f"?{mod.stem}.{node.id}"
            return None
        if lockish_name(node):
            return f"?{dotted_name(node) or 'lock'}"
        return None

    # -- call resolution ----------------------------------------------------
    def resolve_call(self, mod: _ModuleFacts, cls: str | None,
                     scope_funcs: dict, func: ast.AST) -> FuncId | None:
        """Best-effort static callee for a Call's func expression."""
        if isinstance(func, ast.Name):
            name = func.id
            if name in scope_funcs:
                return scope_funcs[name]
            if name in mod.module_funcs:
                return mod.module_funcs[name]
            if name in mod.imports:
                imod_name, symbol = mod.imports[name]
                imod = self.by_module_name.get(imod_name)
                if imod is not None:
                    if symbol in imod.module_funcs:
                        return imod.module_funcs[symbol]
                    if symbol in imod.class_methods:
                        return imod.class_methods[symbol].get("__init__")
            if name in self.all_classes:
                owner = self.all_classes[name]
                return owner.class_methods.get(name, {}).get("__init__")
            return None
        if isinstance(func, ast.Attribute):
            base = func.value
            meth = func.attr
            if isinstance(base, ast.Name):
                if base.id == "self" and cls is not None:
                    if meth in mod.class_methods.get(cls, {}):
                        return mod.class_methods[cls][meth]
                    lam = mod.attr_lambdas.get((cls, meth))
                    if lam is not None:
                        return lam
                    # typed attr: self.api.create -> class method
                if base.id in self.all_classes:
                    owner = self.all_classes[base.id]
                    return owner.class_methods.get(base.id, {}).get(meth)
                if base.id in mod.module_imports:
                    imod = self.by_module_name.get(
                        mod.module_imports[base.id])
                    if imod is not None and meth in imod.module_funcs:
                        return imod.module_funcs[meth]
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and cls is not None:
                owner = (mod.locks.attr_classes.get(cls, {})
                         .get(base.attr)) if mod.locks else None
                if owner:
                    omod = self.all_classes.get(owner)
                    if omod is not None:
                        return omod.class_methods.get(owner, {}).get(meth)
            # unique-method-name resolution with a stdlib-shadow blocklist
            if meth not in CHA_BLOCKLIST and len(meth) >= 4:
                cands = self.methods_by_name.get(meth, [])
                if len(cands) == 1:
                    return cands[0][1]
        return None

    def resolve_callable_ref(self, mod: _ModuleFacts, cls: str | None,
                             scope_funcs: dict,
                             node: ast.AST) -> FuncId | None:
        """A callable passed by reference (thread target, hook cb)."""
        if isinstance(node, ast.Lambda):
            return None  # handled by the caller (synthetic FuncId)
        if isinstance(node, ast.Name):
            if node.id in scope_funcs:
                return scope_funcs[node.id]
            return mod.module_funcs.get(node.id)
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and cls is not None:
            fid = mod.class_methods.get(cls, {}).get(node.attr)
            if fid is not None:
                return fid
            return mod.attr_lambdas.get((cls, node.attr))
        return None

    # -- pass 2: body scan --------------------------------------------------
    def _scan_module(self, mod: _ModuleFacts) -> None:
        prog = self.program
        # single-writer annotations: comment line -> next assignment
        pending_annot: dict[int, frozenset] = {}
        for lineno, comment in mod.comments.items():
            m = ANNOTATION_RE.search(comment)
            if m:
                roles = frozenset(r.strip() for r in
                                  m.group("roles").split(",") if r.strip())
                pending_annot[lineno] = roles

        def note_annotation(cls, attr, lineno):
            # annotation on the same line, or standalone on the line above
            roles = pending_annot.get(lineno) or pending_annot.get(
                lineno - 1)
            if roles:
                prog.annotations[(cls, attr)] = roles
                prog.annotation_sites[(cls, attr)] = (mod.path, lineno)

        # lock creation sites for the runtime validator's site map
        for cls_name, attrs in (mod.locks.class_locks or {}).items():
            for attr, decl in attrs.items():
                base = mod.locks.resolve_alias(cls_name, attr)
                if base == attr:  # aliases map to their base lock
                    prog.lock_sites.setdefault(
                        f"{cls_name}.{attr}", []).append(
                        (mod.path, decl.line))
                else:
                    prog.lock_sites.setdefault(
                        f"{cls_name}.{base}", []).append(
                        (mod.path, decl.line))
        for name, decl in mod.locks.module_locks.items():
            prog.lock_sites.setdefault(
                f"{mod.stem}.{name}", []).append((mod.path, decl.line))
        for cls_name in mod.locks.class_locks:
            prog.sync_attrs.setdefault(cls_name, set()).update(
                mod.locks.class_locks[cls_name])
        for cls_name, attrs in mod.locks.class_events.items():
            prog.sync_attrs.setdefault(cls_name, set()).update(attrs)

        for fid, info in list(prog.functions.items()):
            if info.path != mod.path:
                continue
            self._scan_function(mod, info, note_annotation)

    def _scan_function(self, mod: _ModuleFacts, info: FuncInfo,
                       note_annotation) -> None:
        prog = self.program
        cls = info.cls
        fid = info.fid
        # nested defs visible by name from this body
        scope_funcs = {}
        for child_fid, child in prog.functions.items():
            if child.path == mod.path and child.cls == cls and \
                    child_fid[2].startswith(fid[2] + ".") and \
                    child_fid[2].count(".") == fid[2].count(".") + 1:
                scope_funcs[child_fid[2].rsplit(".", 1)[-1]] = child_fid
        body = (info.node.body
                if isinstance(info.node, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                else [info.node.body])
        lambda_count = [0]
        skip_loads: set = set()

        def self_attr(node) -> str | None:
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                # `self.__dict__.setdefault(...)` is the frozen-dataclass
                # memoization idiom (GIL-atomic, benign duplicate build),
                # not a shared field.
                if node.attr.startswith("__") and node.attr.endswith("__"):
                    return None
                return node.attr
            return None

        def is_sync_attr(attr: str) -> bool:
            if cls is None:
                return True
            if attr in prog.sync_attrs.get(cls, set()):
                return True
            # method references (`self._worker`, `self.flush`) are not
            # data fields
            return attr in mod.class_methods.get(cls, {})

        def record_access(kind, write_kind, target, node, held):
            prog.accesses.append(Access(
                kind=kind, write_kind=write_kind, target=target,
                func=fid, path=mod.path, line=node.lineno,
                col=getattr(node, "col_offset", 0),
                lexical_guards=frozenset(held),
                in_init=info.is_init))

        def global_names() -> set:
            out = set()
            for n in ast.walk(info.node):
                if isinstance(n, ast.Global):
                    out.update(n.names)
            return out

        func_globals = global_names() if not isinstance(
            info.node, ast.Lambda) else set()

        def handle_spawn(call: ast.Call, held) -> bool:
            """Thread()/submit()/hook-registration detection."""
            name = dotted_name(call.func) or ""
            leafattr = call.func.attr if isinstance(call.func,
                                                    ast.Attribute) else name
            target_node = None
            kind = None
            if name in SPAWN_THREAD_CTORS:
                kind = "thread"
                for kw in call.keywords:
                    if kw.arg == "target":
                        target_node = kw.value
                if name.endswith("Timer") and target_node is None and \
                        len(call.args) >= 2:
                    target_node = call.args[1]
            elif leafattr == "submit" and call.args:
                kind = "submit"
                target_node = call.args[0]
            elif leafattr in HOOK_METHODS:
                kind = "hook"
                # callback is whichever arg resolves to a callable
                for arg in call.args:
                    if isinstance(arg, ast.Lambda) or \
                            self.resolve_callable_ref(
                                mod, cls, scope_funcs, arg) is not None:
                        target_node = arg
                        break
                if target_node is None:
                    return False
            if kind is None:
                return False
            role = None
            if kind == "thread":
                for kw in call.keywords:
                    if kw.arg == "name" and \
                            isinstance(kw.value, ast.Constant) and \
                            isinstance(kw.value.value, str):
                        role = kw.value.value
            target_fid = None
            if isinstance(target_node, ast.Lambda):
                lambda_count[0] += 1
                target_fid = (mod.path, cls,
                              f"{fid[2]}.<lambda{target_node.lineno}>")
                prog.functions[target_fid] = FuncInfo(
                    target_fid, target_node, mod.path, cls, is_init=False)
                self._scan_function(mod, prog.functions[target_fid],
                                    note_annotation)
            elif target_node is not None:
                target_fid = self.resolve_callable_ref(
                    mod, cls, scope_funcs, target_node)
            if role is None:
                if kind == "hook":
                    role = HOOK_ROLE
                elif kind == "submit":
                    role = EXECUTOR_ROLE
                elif target_fid is not None:
                    tcls = prog.functions[target_fid].cls
                    leaf = target_fid[2].rsplit(".", 1)[-1]
                    role = f"{tcls}.{leaf}" if tcls else \
                        f"{_mod_stem(target_fid[0])}.{leaf}"
                elif target_node is not None:
                    leaf = (dotted_name(target_node) or "thread") \
                        .rsplit(".", 1)[-1]
                    role = leaf.lstrip("_") or "thread"
                else:
                    role = "thread"
            args_attrs = tuple(
                a for a in (self_attr(arg) for arg in call.args)
                if a is not None)
            # Thread(..., args=(self.x,)) publication
            for kw in call.keywords:
                if kw.arg == "args" and isinstance(kw.value,
                                                   (ast.Tuple, ast.List)):
                    args_attrs += tuple(
                        a for a in (self_attr(e) for e in kw.value.elts)
                        if a is not None)
            prog.spawns.append(Spawn(
                role=role, target=target_fid, path=mod.path,
                line=call.lineno, func=fid,
                self_attr_args=args_attrs, kind=kind))
            return True

        def scan(node, held: tuple) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return  # own FuncId; scanned separately
            if isinstance(node, ast.Lambda):
                # un-spawned lambda: body executes on the enclosing
                # function's role eventually — fold its accesses/calls
                # into this function, with NO inherited held locks.
                for child in ast.iter_child_nodes(node):
                    scan(child, ())
                return
            if isinstance(node, ast.With):
                names = []
                for item in node.items:
                    lname = self.canonical_lock(mod, cls,
                                                item.context_expr)
                    if lname is not None:
                        names.append(lname)
                        for h in held:
                            if h != lname:
                                prog.order_edges.setdefault(
                                    (h, lname),
                                    (mod.path, item.context_expr.lineno))
                    scan(item.context_expr, held)
                inner = held + tuple(n for n in names if n not in held)
                for stmt in node.body:
                    scan(stmt, inner)
                return
            if isinstance(node, ast.Assign):
                scan(node.value, held)
                for target in node.targets:
                    attr = self_attr(target)
                    if attr is not None and cls is not None:
                        if isinstance(node.value, ast.Lambda):
                            lambda_count[0] += 1
                            lam_fid = (mod.path, cls,
                                       f"{fid[2]}.<lambda{node.lineno}>")
                            prog.functions[lam_fid] = FuncInfo(
                                lam_fid, node.value, mod.path, cls,
                                is_init=False)
                            mod.attr_lambdas[(cls, attr)] = lam_fid
                            self._scan_function(
                                mod, prog.functions[lam_fid],
                                note_annotation)
                        note_annotation(cls, attr, node.lineno)
                        if not is_sync_attr(attr):
                            if isinstance(node.value, (ast.Dict, ast.List,
                                                       ast.Set,
                                                       ast.ListComp,
                                                       ast.DictComp,
                                                       ast.SetComp)):
                                prog.mutable_fields[(cls, attr)] = True
                            elif isinstance(node.value, ast.Call):
                                ctor = dotted_name(node.value.func) or ""
                                if ctor.split(".")[-1] in ("dict", "list",
                                                           "set",
                                                           "defaultdict",
                                                           "OrderedDict"):
                                    prog.mutable_fields[(cls, attr)] = True
                            record_access("write", "bind", (cls, attr),
                                          target, held)
                        continue
                    # self.a.b = v / self.a[k] = v mutate the object in a
                    if isinstance(target, ast.Attribute):
                        inner = self_attr(target.value)
                        if inner is not None and cls is not None and \
                                not is_sync_attr(inner):
                            record_access("write", "deep", (cls, inner),
                                          target, held)
                            skip_loads.add(id(target.value))
                    elif isinstance(target, ast.Subscript):
                        inner = self_attr(target.value)
                        if inner is not None and cls is not None and \
                                not is_sync_attr(inner):
                            record_access("write", "item", (cls, inner),
                                          target, held)
                            skip_loads.add(id(target.value))
                        else:
                            scan(target, held)
                    elif isinstance(target, ast.Name):
                        if target.id in func_globals:
                            record_access("write", "bind",
                                          (mod.stem, target.id),
                                          target, held)
                    elif isinstance(target, (ast.Tuple, ast.List)):
                        # `a, self.x = ...` tuple unpacking: each elt is
                        # its own Store target — a rebinding of a field
                        # hides here just as well as in a plain Assign.
                        for elt in target.elts:
                            eattr = self_attr(elt)
                            if eattr is not None and cls is not None and \
                                    not is_sync_attr(eattr):
                                record_access("write", "bind",
                                              (cls, eattr), elt, held)
                            elif isinstance(elt, ast.Name) and \
                                    elt.id in func_globals:
                                record_access("write", "bind",
                                              (mod.stem, elt.id),
                                              elt, held)
                            elif isinstance(elt, ast.Subscript):
                                inner = self_attr(elt.value)
                                if inner is not None and cls is not None \
                                        and not is_sync_attr(inner):
                                    record_access("write", "item",
                                                  (cls, inner), elt, held)
                                    skip_loads.add(id(elt.value))
                                else:
                                    scan(elt, held)
                            else:
                                scan(elt, held)
                return
            if isinstance(node, ast.AugAssign):
                scan(node.value, held)
                attr = self_attr(node.target)
                if attr is not None and cls is not None and \
                        not is_sync_attr(attr):
                    record_access("write", "aug", (cls, attr),
                                  node.target, held)
                    record_access("read", "", (cls, attr),
                                  node.target, held)
                elif isinstance(node.target, ast.Name) and \
                        node.target.id in func_globals:
                    record_access("write", "aug",
                                  (mod.stem, node.target.id),
                                  node.target, held)
                elif isinstance(node.target, ast.Subscript):
                    inner = self_attr(node.target.value)
                    if inner is not None and cls is not None and \
                            not is_sync_attr(inner):
                        record_access("write", "item", (cls, inner),
                                      node.target, held)
                return
            if isinstance(node, ast.AnnAssign):
                if node.value is not None:
                    scan(node.value, held)
                attr = self_attr(node.target)
                if attr is not None and cls is not None and \
                        not is_sync_attr(attr):
                    note_annotation(cls, attr, node.lineno)
                    record_access("write", "bind", (cls, attr),
                                  node.target, held)
                return
            if isinstance(node, ast.Delete):
                for target in node.targets:
                    if isinstance(target, ast.Subscript):
                        inner = self_attr(target.value)
                        if inner is not None and cls is not None and \
                                not is_sync_attr(inner):
                            record_access("write", "item", (cls, inner),
                                          target, held)
                            skip_loads.add(id(target.value))
                    attr = self_attr(target)
                    if attr is not None and cls is not None and \
                            not is_sync_attr(attr):
                        record_access("write", "del", (cls, attr),
                                      target, held)
                for target in node.targets:
                    scan(target, held)
                return
            if isinstance(node, ast.Call):
                spawned = handle_spawn(node, held)
                # receiver mutators: self.x.append(...)
                if isinstance(node.func, ast.Attribute):
                    recv = node.func.value
                    attr = self_attr(recv)
                    if attr is not None and cls is not None and \
                            node.func.attr in MUTATOR_METHODS and \
                            not is_sync_attr(attr):
                        record_access("write", "mutcall", (cls, attr),
                                      recv, held)
                        skip_loads.add(id(recv))
                callee = self.resolve_call(mod, cls, scope_funcs,
                                           node.func)
                if callee is not None and not spawned:
                    prog.calls.append(CallSite(
                        caller=fid, callee=callee, line=node.lineno,
                        lexical_held=frozenset(held)))
                for child in ast.iter_child_nodes(node):
                    scan(child, held)
                return
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load):
                attr = self_attr(node)
                if attr is not None and cls is not None and \
                        id(node) not in skip_loads and \
                        not is_sync_attr(attr):
                    record_access("read", "", (cls, attr), node, held)
                scan(node.value, held)
                return
            for child in ast.iter_child_nodes(node):
                scan(child, held)

        for stmt in body:
            scan(stmt, ())

    # -- pass 3: fixpoints --------------------------------------------------
    def _propagate(self) -> None:
        prog = self.program
        callees_of: dict = {}
        callers_of: dict = {}
        for site in prog.calls:
            if site.callee in prog.functions and \
                    site.caller in prog.functions:
                callees_of.setdefault(site.caller, []).append(site)
                callers_of.setdefault(site.callee, []).append(site)

        # roles -------------------------------------------------------------
        seeded: dict = {}
        for spawn in prog.spawns:
            if spawn.target is not None and spawn.target in prog.functions:
                seeded.setdefault(spawn.target, set()).add(spawn.role)
        for mod in self.mods:
            for cls in mod.handler_classes:
                for fid in mod.class_methods.get(cls, {}).values():
                    seeded.setdefault(fid, set()).add(HTTP_ROLE)
        roles: dict = {fid: set(r) for fid, r in seeded.items()}
        for fid in prog.functions:
            if fid not in roles and fid not in callers_of:
                roles[fid] = {MAIN_ROLE}
        changed = True
        while changed:
            changed = False
            for site in prog.calls:
                src = roles.get(site.caller)
                if not src or site.callee not in prog.functions:
                    continue
                dst = roles.setdefault(site.callee, set())
                before = len(dst)
                dst |= src
                if len(dst) != before:
                    changed = True
        prog.roles = {fid: frozenset(r) for fid, r in roles.items()}

        # inherited guards H(f) = meet over call sites ----------------------
        universe = frozenset(prog.lock_sites) | frozenset(
            l for edge in prog.order_edges for l in edge)
        H: dict = {}
        for fid in prog.functions:
            if fid in seeded or fid not in callers_of:
                H[fid] = frozenset()
            else:
                H[fid] = universe
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for fid, sites in callers_of.items():
                if fid in seeded:
                    continue
                met = None
                for site in sites:
                    eff = H.get(site.caller, frozenset()) | \
                        site.lexical_held
                    met = eff if met is None else (met & eff)
                met = met if met is not None else frozenset()
                if met != H.get(fid):
                    H[fid] = met
                    changed = True
        prog.inherited_guards = H

        # acquisition sets + interprocedural order edges --------------------
        lex_acquires: dict = {fid: set() for fid in prog.functions}
        for fid, info in prog.functions.items():
            mod = next(m for m in self.mods if m.path == info.path)
            acq = set()

            def collect_with(node):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.Lambda)) and node is not info.node:
                    return
                if isinstance(node, ast.With):
                    for item in node.items:
                        name = self.canonical_lock(mod, info.cls,
                                                   item.context_expr)
                        if name is not None:
                            acq.add(name)
                for child in ast.iter_child_nodes(node):
                    collect_with(child)

            collect_with(info.node)
            lex_acquires[fid] = acq

        A: dict = dict(lex_acquires)
        changed = True
        rounds = 0
        while changed and rounds < 50:
            changed = False
            rounds += 1
            for site in prog.calls:
                if site.callee not in A or site.caller not in A:
                    continue
                before = len(A[site.caller])
                A[site.caller] |= A[site.callee]
                if len(A[site.caller]) != before:
                    changed = True
        for site in prog.calls:
            eff_held = prog.inherited_guards.get(
                site.caller, frozenset()) | site.lexical_held
            for h in eff_held:
                for m in A.get(site.callee, ()):
                    if h != m:
                        prog.order_edges.setdefault(
                            (h, m), (site.caller[0], site.line))

    def build(self) -> Program:
        for mod in self.mods:
            mod.collect_imports()
            self._index_functions(mod)
        known = set(self.all_classes)
        for mod in self.mods:
            mod.locks = collect_module_locks(mod.tree,
                                             known_classes=known)
        for mod in self.mods:
            self._scan_module(mod)
        self._propagate()
        return self.program


def build_program(modules: list) -> Program:
    """``modules``: [(path, tree, source)] — the kairace pass-1 product."""
    return ProgramBuilder(modules).build()


def order_cycles(edges: dict) -> list:
    """Cycles in the acquisition graph (KRC002): returns a list of
    ``(cycle_locks, (path, line))`` — one entry per strongly connected
    component with more than one node.  Opaque ``?``-named locks are
    excluded (their identity is not established)."""
    graph: dict = {}
    for (a, b) in edges:
        if a.startswith("?") or b.startswith("?"):
            continue
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    # Tarjan SCC
    index_counter = [0]
    stack: list = []
    lowlink: dict = {}
    index: dict = {}
    on_stack: dict = {}
    sccs: list = []

    def strongconnect(v):
        work = [(v, iter(sorted(graph[v])))]
        index[v] = lowlink[v] = index_counter[0]
        index_counter[0] += 1
        stack.append(v)
        on_stack[v] = True
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = lowlink[w] = index_counter[0]
                    index_counter[0] += 1
                    stack.append(w)
                    on_stack[w] = True
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                elif on_stack.get(w):
                    lowlink[node] = min(lowlink[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    out = []
    for scc in sccs:
        # anchor at one edge inside the cycle
        anchor = None
        for (a, b), site in sorted(edges.items()):
            if a in scc and b in scc:
                anchor = site
                break
        out.append((scc, anchor or ("", 0)))
    return out
