"""kairace rule pack: the concurrency contracts, machine-enforced.

| id     | name                   | contract                                |
|--------|------------------------|-----------------------------------------|
| KRC001 | multi-role-write       | a field written on >=2 thread roles     |
|        |                        | shares a common lock across ALL writes  |
| KRC002 | lock-order-inversion   | the static acquisition graph is acyclic |
| KRC003 | single-writer          | `# kairace: single-writer=<role>`       |
|        |                        | fields are mutated only on that role    |
| KRC004 | guard-asymmetry        | if every read of a shared field is      |
|        |                        | guarded, every write holds that lock too|
| KRC005 | unguarded-publication  | mutable state handed to a thread/       |
|        |                        | executor has a lock or is never mutated |

All five run on the shared :class:`~.program.Program` index (built once
per engine run and cached): pass 1 discovers thread roles and lock
declarations, pass 2 maps lock scopes to the accesses they dominate,
pass 3 (these rules' ``finalize``) reports contract violations.

Write kinds covered: rebinding (``self.x = ...``), augmented assignment,
item stores (``self.x[k] = v`` / ``del self.x[k]``), container mutator
calls (``self.x.append(...)``), and sub-object attribute stores
(``self.x.y = v``).  ``__init__`` writes are exempt (object construction
happens-before any thread can see the instance).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..kailint.engine import Finding, ModuleContext, Rule
from .program import (MAIN_ROLE, Program, build_program, order_cycles)


class _ProgramRule(Rule):
    """Base: collect module contexts; build (or reuse) the whole-program
    index in finalize.  The index is cached per input fingerprint so the
    five rules don't each re-run the three analysis passes."""

    _cache: dict = {}   # class-level: fingerprint -> Program

    def __init__(self):
        self._modules: list = []

    def collect(self, ctx: ModuleContext) -> None:
        self._modules.append((ctx.path, ctx.tree, ctx.source))

    def _program(self) -> Program:
        key = tuple((path, hash(src)) for path, _t, src in self._modules)
        cached = _ProgramRule._cache.get(key)
        if cached is None:
            cached = build_program(self._modules)
            # single-slot cache: successive rule instances in ONE engine
            # run share it; a new input set evicts the old program.
            _ProgramRule._cache = {key: cached}
        return cached

    # helpers ---------------------------------------------------------------
    def _finding_at(self, path: str, line: int, col: int,
                    message: str, source_line: str = "",
                    related: tuple = ()) -> Finding:
        return Finding(rule=self.id, path=path, line=line, col=col,
                       message=message, source=source_line,
                       related=related)

    def _line_of(self, path: str, line: int) -> str:
        for p, _t, src in self._modules:
            if p == path:
                lines = src.splitlines()
                if 1 <= line <= len(lines):
                    return lines[line - 1].strip()
        return ""


def _fmt_roles(roles) -> str:
    return ", ".join(sorted(roles))


def _fmt_locks(locks) -> str:
    return ", ".join(sorted(locks)) if locks else "no lock"


def _field_table(prog: Program) -> dict:
    """(class, attr) -> {"writes": [...], "reads": [...]} of non-init
    accesses (plus init writes kept separately for container typing).

    Mutator-method writes (``self.x.update(...)``) only count when the
    field is KNOWN to hold a mutable container — `.update()` on an API
    client or `.pop()` on a template object is a method call, not a
    container mutation."""
    table: dict = {}
    for acc in prog.accesses:
        if acc.kind == "write" and acc.write_kind == "mutcall" and \
                not prog.mutable_fields.get(acc.target):
            continue
        entry = table.setdefault(acc.target, {"writes": [], "reads": [],
                                              "init_writes": []})
        if acc.kind == "write":
            (entry["init_writes"] if acc.in_init
             else entry["writes"]).append(acc)
        elif not acc.in_init:
            entry["reads"].append(acc)
    return table


class MultiRoleWriteRule(_ProgramRule):
    id = "KRC001"
    name = "multi-role-write"
    description = ("field written on >=2 thread roles without a common "
                   "lock across all writes")

    def finalize(self) -> Iterator[Finding]:
        prog = self._program()
        for target, entry in sorted(_field_table(prog).items()):
            if target in prog.annotations:
                continue  # KRC003 enforces the declared contract instead
            writes = entry["writes"]
            if not writes:
                continue
            roles = set()
            for w in writes:
                roles |= prog.roles_of(w.func)
            if len(roles) < 2:
                continue
            common = None
            for w in writes:
                g = prog.guards_at(w)
                common = g if common is None else (common & g)
            if common:
                continue
            worst = min(writes, key=lambda w: (len(prog.guards_at(w)),
                                               w.path, w.line))
            cls, attr = target
            yield self._finding_at(
                worst.path, worst.line, worst.col,
                f"`{cls}.{attr}` is written on roles "
                f"[{_fmt_roles(roles)}] with no common lock across its "
                f"writes (this one holds {_fmt_locks(prog.guards_at(worst))})"
                f" — guard every write with one lock, or declare the "
                f"contract with `# kairace: single-writer=<role>`",
                self._line_of(worst.path, worst.line),
                related=tuple(sorted({(w.path, w.line) for w in writes
                                      if w is not worst})))


class LockOrderInversionRule(_ProgramRule):
    id = "KRC002"
    name = "lock-order-inversion"
    description = "cycle in the static lock acquisition-order graph"

    def finalize(self) -> Iterator[Finding]:
        prog = self._program()
        for cycle, (path, line) in order_cycles(prog.order_edges):
            yield self._finding_at(
                path or (self._modules[0][0] if self._modules else ""),
                line or 1, 0,
                f"lock-order inversion: [{' -> '.join(cycle)}] can be "
                f"acquired in conflicting orders on different threads — "
                f"pick one global order and refactor the inner "
                f"acquisition out",
                self._line_of(path, line) if path else "")


class SingleWriterRule(_ProgramRule):
    id = "KRC003"
    name = "single-writer"
    description = ("`# kairace: single-writer=<role>` field mutated off "
                   "the declared role")

    def finalize(self) -> Iterator[Finding]:
        prog = self._program()
        table = _field_table(prog)
        for target, declared in sorted(prog.annotations.items()):
            entry = table.get(target)
            if entry is None:
                continue
            cls, attr = target
            for w in entry["writes"]:
                roles = prog.roles_of(w.func)
                extra = roles - declared
                if extra:
                    yield self._finding_at(
                        w.path, w.line, w.col,
                        f"`{cls}.{attr}` is declared single-writer="
                        f"{_fmt_roles(declared)} but this write also "
                        f"runs on [{_fmt_roles(extra)}] — move the "
                        f"mutation onto the owning role (queue/handoff) "
                        f"or update the annotation",
                        self._line_of(w.path, w.line))


class GuardAsymmetryRule(_ProgramRule):
    id = "KRC004"
    name = "guard-asymmetry"
    description = ("every read of a shared field is guarded but a write "
                   "bypasses the lock")

    def finalize(self) -> Iterator[Finding]:
        prog = self._program()
        for target, entry in sorted(_field_table(prog).items()):
            if target in prog.annotations:
                continue
            writes, reads = entry["writes"], entry["reads"]
            if not writes or not reads:
                continue
            roles = set()
            for acc in writes + reads:
                roles |= prog.roles_of(acc.func)
            if len(roles) < 2:
                continue
            read_common = None
            for r in reads:
                g = prog.guards_at(r)
                read_common = g if read_common is None \
                    else (read_common & g)
            if not read_common:
                continue  # lock-free reads are the author's choice
            # KRC001 already covers multi-role writes with no common
            # lock — skip the whole field, not each write.
            w_roles = set()
            for w in writes:
                w_roles |= prog.roles_of(w.func)
            common_w = None
            for w in writes:
                g = prog.guards_at(w)
                common_w = g if common_w is None else (common_w & g)
            if len(w_roles) >= 2 and not common_w:
                continue
            cls, attr = target
            for w in writes:
                if prog.guards_at(w) & read_common:
                    continue
                yield self._finding_at(
                    w.path, w.line, w.col,
                    f"`{cls}.{attr}`: every read holds "
                    f"[{_fmt_locks(read_common)}] but this write holds "
                    f"{_fmt_locks(prog.guards_at(w))} — the readers' "
                    f"lock protects nothing unless writers take it too",
                    self._line_of(w.path, w.line))


class UnguardedPublicationRule(_ProgramRule):
    id = "KRC005"
    name = "unguarded-publication"
    description = ("mutable field handed to a thread/executor while "
                   "also mutated without a lock")

    def finalize(self) -> Iterator[Finding]:
        prog = self._program()
        table = _field_table(prog)
        seen = set()
        for spawn in prog.spawns:
            for attr in spawn.self_attr_args:
                fn = prog.functions.get(spawn.func)
                cls = fn.cls if fn else None
                if cls is None:
                    continue
                target = (cls, attr)
                if target in prog.annotations or target in seen:
                    continue
                if not prog.mutable_fields.get(target):
                    continue
                entry = table.get(target)
                if not entry:
                    continue
                unguarded = [w for w in entry["writes"]
                             if not prog.guards_at(w)]
                if not unguarded:
                    continue
                seen.add(target)
                w = unguarded[0]
                yield self._finding_at(
                    spawn.path, spawn.line, 0,
                    f"`{cls}.{attr}` (mutable) is handed to a "
                    f"{spawn.kind} here but is mutated without a lock "
                    f"at {w.path}:{w.line} — publish a snapshot/copy, "
                    f"hand off through a queue, or lock both sides",
                    self._line_of(spawn.path, spawn.line))


RULE_CLASSES = [
    MultiRoleWriteRule,       # KRC001
    LockOrderInversionRule,   # KRC002
    SingleWriterRule,         # KRC003
    GuardAsymmetryRule,       # KRC004
    UnguardedPublicationRule,  # KRC005
]


def default_rules() -> list:
    return [cls() for cls in RULE_CLASSES]
