"""kairace command line.

Exit codes (kailint chassis): 0 = clean (every finding suppressed or
baselined), 1 = new findings, 2 = usage/internal error (including a file
the analyzer could not parse — an unchecked file is never a green one).

Beyond linting, two machine-readable exports feed the runtime validator:

  --lock-graph   the static lock acquisition graph (canonical lock names,
                 creation sites, order edges) that ``chaos_matrix
                 --races`` checks observed ``KAI_LOCKTRACE`` orders
                 against;
  --roles        the thread-role table (role -> entry points) documented
                 in docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..kailint.engine import (Engine, load_baseline, write_baseline)
from .program import build_program
from .rules import RULE_CLASSES, default_rules

BASELINE_NAME = ".kairace-baseline.json"


def package_root() -> str:
    """Default scan target: the kai_scheduler_tpu package itself."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _default_baseline_path(paths: list[str]) -> str:
    start = os.path.abspath(paths[0]) if paths else os.getcwd()
    cur = start if os.path.isdir(start) else os.path.dirname(start)
    while True:
        cand = os.path.join(cur, BASELINE_NAME)
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.join(os.getcwd(), BASELINE_NAME)
        cur = parent


def build_engine(select=None, ignore=None) -> Engine:
    return Engine(default_rules(), select=select, ignore=ignore,
                  tool="kairace")


def _program_for(paths: list[str]):
    """Build the whole-program index directly (for --lock-graph/--roles
    and the chaos-matrix validator)."""
    import ast as _ast

    from ..kailint.engine import iter_python_files, package_relative
    modules = []
    errors = []
    for fpath in iter_python_files(paths):
        try:
            with open(fpath, encoding="utf-8") as fh:
                src = fh.read()
            modules.append((package_relative(fpath),
                            _ast.parse(src, filename=fpath), src))
        except (OSError, UnicodeDecodeError, SyntaxError) as exc:
            errors.append(f"{fpath}: {exc}")
    return build_program(modules), errors


def lock_graph(paths: list[str]) -> dict:
    """The static lock graph: ``{"locks": {name: [{file, line}]},
    "edges": [[held, acquired]]}`` — the contract the KAI_LOCKTRACE
    runtime validator checks observed orders against."""
    prog, errors = _program_for(paths)
    return {
        "locks": {name: [{"file": f, "line": ln} for f, ln in sites]
                  for name, sites in sorted(prog.lock_sites.items())},
        "edges": sorted([a, b] for (a, b) in prog.order_edges),
        "errors": errors,
    }


def role_table(paths: list[str]) -> dict:
    prog, errors = _program_for(paths)
    roles: dict = {}
    for spawn in prog.spawns:
        entry = roles.setdefault(spawn.role, {"entry_points": set(),
                                              "kind": spawn.kind})
        tgt = (f"{spawn.target[0]}:{spawn.target[2]}"
               if spawn.target else f"{spawn.path}:{spawn.line}")
        entry["entry_points"].add(tgt)
    return {
        "roles": {r: {"kind": v["kind"],
                      "entry_points": sorted(v["entry_points"])}
                  for r, v in sorted(roles.items())},
        "annotations": {f"{c}.{a}": sorted(rs) for (c, a), rs
                        in sorted(prog.annotations.items())},
        "errors": errors,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kai_scheduler_tpu.tools.kairace",
        description="whole-program thread-role & lock-contract analyzer "
                    "for kai_scheduler_tpu (docs/STATIC_ANALYSIS.md); "
                    "runs on the kailint engine chassis")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: the whole "
                         "kai_scheduler_tpu package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: nearest {BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (e.g. KRC002)")
    ap.add_argument("--ignore", default=None)
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--lock-graph", action="store_true",
                    help="print the static lock acquisition graph as "
                         "JSON (locks, creation sites, order edges) and "
                         "exit — the KAI_LOCKTRACE validator's contract")
    ap.add_argument("--roles", action="store_true",
                    help="print the thread-role table (role -> entry "
                         "points) and single-writer annotations as JSON")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in RULE_CLASSES:
            print(f"{cls.id}  {cls.name:<22} {cls.description}")
        return 0
    paths = args.paths or [package_root()]
    for p in paths:
        if not os.path.exists(p):
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2
    if args.lock_graph or args.roles:
        payload = lock_graph(paths) if args.lock_graph \
            else role_table(paths)
        print(json.dumps(payload, indent=2))
        return 2 if payload["errors"] else 0

    known = {cls.id.upper() for cls in RULE_CLASSES}
    filters = {}
    for flag, spec in (("--select", args.select),
                       ("--ignore", args.ignore)):
        if spec is None:
            filters[flag] = None
            continue
        ids = {tok.strip().upper() for tok in spec.split(",")
               if tok.strip()}
        unknown = ids - known
        if unknown:
            print(f"error: unknown rule id(s) for {flag}: "
                  f"{', '.join(sorted(unknown))} (see --list-rules)",
                  file=sys.stderr)
            return 2
        filters[flag] = ids
    select, ignore = filters["--select"], filters["--ignore"]
    engine = build_engine(select=select, ignore=ignore)

    baseline_path = args.baseline or _default_baseline_path(paths)
    if args.write_baseline:
        if select or ignore:
            print("error: --write-baseline cannot be combined with "
                  "--select/--ignore (it would overwrite the other "
                  "rules' baseline entries)", file=sys.stderr)
            return 2
        report = engine.run(paths, baseline=None)
        if report.errors:
            for err in report.errors:
                print(f"kairace: parse error: {err}", file=sys.stderr)
            print("error: refusing to write a baseline from a partial "
                  "scan (fix the parse errors first)", file=sys.stderr)
            return 2
        n = write_baseline(baseline_path, report.findings,
                           tool="kairace")
        print(f"kairace: wrote {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} to {baseline_path}")
        return 0

    try:
        baseline = {} if args.no_baseline else \
            load_baseline(baseline_path, tool="kairace")
        report = engine.run(paths, baseline=baseline)
    except (OSError, ValueError, KeyError) as exc:
        print(f"kairace: error: {exc}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps(report.to_dict(), indent=2))
        return report.exit_code

    for f in report.findings:
        print(f.render())
    for err in report.errors:
        print(f"kairace: parse error: {err}", file=sys.stderr)
    summary = (f"kairace: {len(report.findings)} new finding(s), "
               f"{len(report.baselined)} baselined, "
               f"{report.suppressed} suppressed, "
               f"{report.files} file(s)")
    if report.stale_baseline:
        summary += (f", {len(report.stale_baseline)} stale baseline "
                    f"entr{'y' if len(report.stale_baseline) == 1 else 'ies'}"
                    f" (fixed — prune with --write-baseline)")
    print(summary)
    return report.exit_code
