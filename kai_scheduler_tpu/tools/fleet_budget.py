"""Fleet-phase budget smoke: fail CI when the host pipeline rots.

Runs ``bench.fleet_phase`` at a small committed shape and checks the
result against ``docs/scale-tests/fleet_budget.json``:

- **wall-clock budgets** (generous, noise-tolerant): ``grouped`` and
  ``snapshotted`` phase medians and the warm cycle must stay under the
  committed ceilings — the numbers the incremental host pipeline
  (watch-delta ClusterInfo, owner-coalesced grouping, batched binds)
  brought down must not silently creep back up;
- **structural gates** (deterministic): the incremental cache must
  actually run incrementally (``cluster_cache_full_refresh_total`` stays
  at priming counts — a fallback-per-cycle regression multiplies it by
  the cycle count) and the podgrouper's owner-resolution memo must see
  hits.  Wall clocks flake with CI noise; these do not.

Usage (ci_check.sh runs it):

    JAX_PLATFORMS=cpu python -m kai_scheduler_tpu.tools.fleet_budget
    ... --budget docs/scale-tests/fleet_budget.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("kai-fleet-budget")
    ap.add_argument("--budget", default=None,
                    help="threshold file (default: "
                         "docs/scale-tests/fleet_budget.json)")
    ap.add_argument("--json", action="store_true",
                    help="emit the measured result as JSON")
    args = ap.parse_args(argv)

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    budget_path = args.budget or os.path.join(
        repo_root, "docs", "scale-tests", "fleet_budget.json")
    with open(budget_path) as f:
        budget = json.load(f)

    sys.path.insert(0, repo_root)
    import bench
    from kai_scheduler_tpu.utils.metrics import METRICS

    shape = budget["shape"]
    refresh0 = METRICS.counters.get("cluster_cache_full_refresh_total", 0)
    result = bench.fleet_phase(shape["nodes"], shape["jobs"],
                               shape["gang"])
    refreshes = METRICS.counters.get(
        "cluster_cache_full_refresh_total", 0) - refresh0
    owner_hits = METRICS.counters.get("podgrouper_owner_cache_hits", 0)

    medians = result.get("pod_latency", {}).get("phase_median_ms", {})
    bound = result.get("pod_latency", {}).get("bound_pods", 0)
    expect = shape["jobs"] * shape["gang"]
    checks = [
        ("bound_pods", bound, ">=", expect),
        ("warm_cycle_s", result.get("warm_cycle_s"),
         "<=", budget["max_warm_cycle_s"]),
        ("grouped_median_ms", medians.get("grouped"),
         "<=", budget["max_grouped_ms"]),
        ("snapshotted_median_ms", medians.get("snapshotted"),
         "<=", budget["max_snapshotted_ms"]),
        ("cluster_cache_full_refreshes", refreshes,
         "<=", budget["max_full_refreshes"]),
        ("podgrouper_owner_cache_hits", owner_hits,
         ">=", budget["min_owner_cache_hits"]),
    ]

    failed = []
    for name, got, op, want in checks:
        ok = (got is not None
              and ((op == "<=" and got <= want)
                   or (op == ">=" and got >= want)))
        mark = "ok  " if ok else "FAIL"
        print(f"{mark} {name:32s} {got!r:>12} {op} {want!r}")
        if not ok:
            failed.append(name)

    if args.json:
        print(json.dumps(result))
    if failed:
        print(f"fleet budget: FAILED ({', '.join(failed)}); the "
              f"committed budget is {budget_path}")
        return 1
    print("fleet budget: within budget")
    return 0


if __name__ == "__main__":
    sys.exit(main())
