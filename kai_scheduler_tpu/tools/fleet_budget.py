"""Fleet-phase budget smoke: fail CI when the host pipeline rots.

Runs ``bench.fleet_phase`` at a small committed shape and checks the
result against ``docs/scale-tests/fleet_budget.json``:

- **wall-clock budgets** (generous, noise-tolerant): ``grouped`` and
  ``snapshotted`` phase medians and the warm cycle must stay under the
  committed ceilings — the numbers the incremental host pipeline
  (watch-delta ClusterInfo, owner-coalesced grouping, batched binds)
  brought down must not silently creep back up;
- **structural gates** (deterministic): the incremental cache must
  actually run incrementally (``cluster_cache_full_refresh_total`` stays
  at priming counts — a fallback-per-cycle regression multiplies it by
  the cycle count), the podgrouper's owner-resolution memo must see
  hits, and the GROUPED ALLOCATION path must actually take the fused
  kernel (``allocate_fused_taken_total`` counts per wrapper dispatch —
  a silent fall-back-to-legacy regression zeroes it while every
  wall-clock gate still passes on a fast machine);
- **allocate-kernel ceiling**: the grouped kernel itself is re-measured
  at a small committed shape (``allocate_shape``) and its median must
  stay under ``max_allocate_ms`` — the device-path analog of the
  host-pipeline medians above, so a fused-kernel regression is caught
  here instead of three PRs later at bench scale;
- **fair-share ceiling + structure**: the queue-forest division is
  re-measured at the committed 10k-queue shape (``fairshare_shape``)
  — its step median must stay under ``max_fairshare_ms`` (a silent
  fall-back to the per-level loop measures several times higher and
  trips this even on a fast machine), the prep cache must actually
  reuse (``min_prep_reuse`` hits of ``fairshare_prep_reuse_total``),
  and ``fairshare_dispatch_total`` must show exactly ONE dispatch per
  division — the structural single-dispatch guarantee of DESIGN §2b;
- **rank & time gates (DESIGN §13)**: the rank-assignment kernel is
  re-measured at ``rankplace_shape`` (median under
  ``max_rankplace_ms``, host-fallback parity asserted), and the
  usage-decay fold at ``usage_shape`` must count EXACTLY one
  ``usage_decay_dispatch_total`` per recorded cycle — a silent
  per-queue host loop multiplies it by Q while every wall clock still
  passes — with a fold-median ceiling on top;
- **wire budget (PR 19 observatory)**: the HTTP smoke runs under the
  wire observatory, and its per-cycle client-end byte/syscall/encode
  footprint plus the frame cache's BYTE-hit ratio must stay within the
  committed ``docs/scale-tests/wire_budget.json`` ceilings — disabling
  the preserialized frame cache (``KAI_WIRE_NO_FRAME_CACHE=1``)
  re-encodes every list/get response and trips the encode + byte-ratio
  gates loudly while every wall clock still passes on a fast machine;
  at least one server span must have grafted into a cycle trace, so a
  silently broken trace join fails here too;
- **compile budget (kaijit's runtime half)**: the whole run executes
  under utils/jittrace.py, and the per-kernel distinct abstract
  signatures (= XLA compilation keys) must stay within the committed
  ``docs/scale-tests/compile_budget.json`` ceilings — dropping a pow2
  bucket multiplies a kernel's signature count with every wall clock
  still green on a fast machine; a journaled kernel the static
  analyzer (tools/kaijit/) never discovered fails as an analyzer gap.

Usage (ci_check.sh runs it):

    JAX_PLATFORMS=cpu python -m kai_scheduler_tpu.tools.fleet_budget
    ... --budget docs/scale-tests/fleet_budget.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("kai-fleet-budget")
    ap.add_argument("--budget", default=None,
                    help="threshold file (default: "
                         "docs/scale-tests/fleet_budget.json)")
    ap.add_argument("--json", action="store_true",
                    help="emit the measured result as JSON")
    ap.add_argument("--compile-budget", default=None,
                    help="compile-budget manifest (default: "
                         "docs/scale-tests/compile_budget.json)")
    ap.add_argument("--wire-budget", default=None,
                    help="wire-budget manifest (default: "
                         "docs/scale-tests/wire_budget.json)")
    args = ap.parse_args(argv)

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    budget_path = args.budget or os.path.join(
        repo_root, "docs", "scale-tests", "fleet_budget.json")
    with open(budget_path) as f:
        budget = json.load(f)
    compile_budget_path = args.compile_budget or os.path.join(
        repo_root, "docs", "scale-tests", "compile_budget.json")

    sys.path.insert(0, repo_root)
    # Arm the compile-signature journal BEFORE bench imports bind any
    # kernel references — the whole budget run records under trace.
    from kai_scheduler_tpu.utils import jittrace
    jittrace.install()
    import bench
    from kai_scheduler_tpu.utils.metrics import METRICS

    shape = budget["shape"]
    refresh0 = METRICS.counters.get("cluster_cache_full_refresh_total", 0)
    col_fb0 = METRICS.counters.get("columnar_fallback_total", 0)

    def fused_taken():
        return sum(v for k, v in METRICS.counters.items()
                   if str(k).startswith("allocate_fused_taken_total"))

    fused0 = fused_taken()
    result = bench.fleet_phase(shape["nodes"], shape["jobs"],
                               shape["gang"])
    refreshes = METRICS.counters.get(
        "cluster_cache_full_refresh_total", 0) - refresh0
    owner_hits = METRICS.counters.get("podgrouper_owner_cache_hits", 0)
    fused_calls = fused_taken() - fused0

    # Allocate-kernel micro-measurement: the grouped kernel alone at the
    # committed shape, warm median over 5 runs.
    import time as _time

    import numpy as np

    from kai_scheduler_tpu.ops.allocate_grouped import allocate_grouped
    ashape = budget.get("allocate_shape",
                        {"nodes": 1024, "jobs": 16, "gang": 64})
    arrs = bench.build_arrays(ashape["nodes"], ashape["jobs"],
                              ashape["gang"], placeable=True)
    anodes, atasks = arrs[:6], arrs[6:10]
    # kailint: disable=KAI004 — budget micro-bench, no Session to dispatch through
    allocate_grouped(anodes, *atasks, arrs[10])  # warm/compile
    ts = []
    for _ in range(5):
        t0 = _time.perf_counter()
        # kailint: disable=KAI004 — budget micro-bench, no Session to dispatch through
        allocate_grouped(anodes, *atasks, arrs[10])
        ts.append((_time.perf_counter() - t0) * 1000.0)
    allocate_ms = float(np.median(ts))

    # Fair-share micro-measurement: the queue-forest division at the
    # committed 10k-queue shape (warm prep cache, median over 5 runs).
    fshape = budget.get("fairshare_shape", {"queues": 10000, "bands": 1})
    fs_iters = 5
    fsres = bench.fairshare_microbench(n_queues=fshape["queues"],
                                       bands=fshape.get("bands", 1),
                                       iters=fs_iters)

    # Rank-placement micro-measurement (ops/rankplace.py): the
    # assignment kernel alone at the committed gang/topology shape,
    # warm median over 5 runs.
    from kai_scheduler_tpu.ops import rankplace as rp
    rshape = budget.get("rankplace_shape",
                        {"nodes": 4096, "gang": 512, "levels": 3})
    rng = np.random.default_rng(0)
    r_nodes, r_gang = rshape["nodes"], rshape["gang"]
    r_levels = rshape.get("levels", 3)
    topo_rank = rng.permutation(r_nodes).astype(np.int32)
    level_segs = rng.integers(
        0, max(2, r_nodes // 8), (r_levels, r_nodes)).astype(np.int32)
    slots = rng.integers(0, r_nodes, r_gang).astype(np.int32)
    # kailint: disable=KAI004 — budget micro-bench, no Session to dispatch through
    rp.rank_place_padded(slots, topo_rank, level_segs)  # warm/compile
    ts = []
    for _ in range(5):
        t0 = _time.perf_counter()
        # kailint: disable=KAI004 — budget micro-bench, no Session to dispatch through
        perm, _hops = rp.rank_place_padded(slots, topo_rank, level_segs)
        np.asarray(perm)
        ts.append((_time.perf_counter() - t0) * 1000.0)
    rankplace_ms = float(np.median(ts))
    # Host-fallback parity doubles as the budget's sanity check.
    p_np, _h = rp.rank_place_np(slots, topo_rank, level_segs)
    rank_parity = bool(np.array_equal(p_np, np.asarray(perm)))

    # Usage-decay structural gate (ops/usage.py + utils/usagedb.py):
    # fold N cycles of Q-queue samples and PIN the dispatch count to
    # one per cycle — a silent per-queue host loop multiplies it by Q.
    from kai_scheduler_tpu.utils.usagedb import (InMemoryUsageDB,
                                                 UsageParams)
    ushape = budget.get("usage_shape", {"queues": 2048, "cycles": 5})
    udb = InMemoryUsageDB(UsageParams(half_life_period_seconds=600.0))
    u_alloc = {f"q{i}": rng.uniform(0, 8, 3)
               for i in range(ushape["queues"])}
    udb.record_cycle(0.0, u_alloc)  # warm/compile + row growth
    u0 = METRICS.counters.get("usage_decay_dispatch_total", 0)
    ts = []
    for cycle in range(ushape["cycles"]):
        t0 = _time.perf_counter()
        udb.record_cycle(60.0 * (cycle + 1), u_alloc)
        ts.append((_time.perf_counter() - t0) * 1000.0)
    usage_folds = METRICS.counters.get("usage_decay_dispatch_total",
                                       0) - u0
    usage_decay_ms = float(np.median(ts))

    # Arena scatter churn (compile-gate teeth): the fleet phase touches
    # only a handful of distinct dirty-row widths, so an un-bucketed
    # scatter pad would journal the SAME signature count as a bucketed
    # one and slip past the ceiling.  Sweep K=1..12 dirty rows through
    # the real DeviceStateCache scatter path: pow2 bucketing collapses
    # them to {1,2,4,8,16} compile keys, while a raw pad journals all
    # twelve — pushing ``compile_sigs:apply_deltas_kernel`` over its
    # committed ceiling.
    from kai_scheduler_tpu.framework.arena import DeviceStateCache

    class _ChurnSession:
        def __init__(self, n, r=3):
            crng = np.random.default_rng(1)
            self.node_idle = crng.uniform(0, 8, (n, r))
            self.node_releasing = np.zeros((n, r))
            self.node_room = crng.uniform(0, 110, n)
            self._dirty_rows: set[int] = set()

        def dispatch_kernel(self, thunk, label=None, validate=None):
            return thunk()

    cshape = budget.get("scatter_churn_shape",
                        {"nodes": 512, "max_rows": 12})
    churn = _ChurnSession(cshape["nodes"])
    dcache = DeviceStateCache()
    dcache.arrays(churn)  # cold upload; scatters follow
    for k in range(1, cshape["max_rows"] + 1):
        rows = rng.choice(cshape["nodes"], size=k, replace=False)
        churn.node_idle[rows] += 0.5
        churn._dirty_rows.update(int(x) for x in rows)
        dcache.arrays(churn)

    # Overlapped-pipeline smoke (DESIGN §10): the SAME fleet shape with
    # the commit executor armed.  min_overlap_ratio is the structural
    # gate — a pipeline that silently serialized (executor idle while
    # the cycle thread works) reads ~0 here while every wall clock still
    # passes on a fast machine; identical bound-pods proves the
    # speculative view never lost or doubled a placement.
    pres = bench.fleet_phase(shape["nodes"], shape["jobs"],
                             shape["gang"], pipelined=True)
    p_bound = pres.get("pod_latency", {}).get("bound_pods", 0)
    p_overlap = pres.get("pipeline", {}).get("overlap_ratio_mean")

    # HTTP daemon-regime smoke (DESIGN §12): the SAME fleet over a real
    # loopback apiserver + HTTPKubeAPI, pipelined.  The structural gates
    # are the transport-rot detectors: hot-kind list requests bounded to
    # the priming pass (steady-state cycles ship zero whole-kind lists),
    # the watch-mode cache never falls back to re-lists, bind waves land
    # through the bulk endpoints, and the preserialized frame cache
    # actually reuses its encodes.
    from kai_scheduler_tpu.utils.metrics import _key as _metric_key

    def _labeled(name, **labels):
        return METRICS.counters.get(_metric_key(name, labels), 0)

    hshape = budget.get("http_shape", {"nodes": 200, "jobs": 2,
                                       "gang": 50})
    hot_kinds = ("Pod", "Node", "Queue", "PodGroup")

    def hot_lists():
        return sum(_labeled("apiserver_list_requests_total", kind=k)
                   for k in hot_kinds)

    h_lists0 = hot_lists()
    h_refresh0 = METRICS.counters.get("cluster_cache_full_refresh_total",
                                      0)
    h_waves0 = _labeled("bulk_write_batches_total", path="bind_wave")
    h_bulk0 = (_labeled("apiserver_bulk_requests_total", op="create")
               + _labeled("apiserver_bulk_requests_total", op="patch"))
    h_hits0 = METRICS.counters.get("watch_frame_cache_hits_total", 0)
    h_miss0 = METRICS.counters.get("watch_frame_cache_misses_total", 0)
    h_graft0 = METRICS.counters.get("wire_spans_grafted_total", 0)
    hres = bench.fleet_phase(hshape["nodes"], hshape["jobs"],
                             hshape["gang"], pipelined=True,
                             substrate="http")
    h_bound = hres.get("pod_latency", {}).get("bound_pods", 0)
    h_expect = hshape["jobs"] * hshape["gang"]
    h_hits = METRICS.counters.get("watch_frame_cache_hits_total",
                                  0) - h_hits0
    h_miss = METRICS.counters.get("watch_frame_cache_misses_total",
                                  0) - h_miss0
    h_ratio = round(h_hits / max(h_hits + h_miss, 1), 3)

    # Wire-budget measurement (PR 19): the http smoke's own ``wire``
    # section is the byte/syscall delta across the whole phase; divide
    # by the cycles it took for per-cycle footprints.  Client-end
    # counters are the gated side — they move once per *attempt*, so a
    # retry storm shows up here even when the server saw each write
    # once.  Encodes = frame-cache misses (every one is a full
    # json.dumps on the serve path).
    from kai_scheduler_tpu.utils import wireobs
    wire = hres.get("wire") or {}
    h_cycles = max(1, (hres.get("cold_cycles") or 0)
                   + (hres.get("warm_cycles") or 0))

    def _wire(name, **labels):
        return wire.get(_metric_key(name, labels), 0)

    wire_client_bytes = sum(
        _wire("wire_bytes_total", dir=d, end="client", path=p)
        for d in ("in", "out") for p in wireobs.PATH_CLASSES)
    wire_client_syscalls = sum(
        _wire("wire_syscalls_total", end="client", op=op, path=p)
        for op in ("send", "recv") for p in wireobs.PATH_CLASSES)
    wire_encodes = wire.get("watch_frame_cache_misses_total", 0)
    wire_serve_encodes = wire.get("frame_cache_serve_encodes_total", 0)
    wire_cache_b = _wire("frame_cache_bytes_total", src="cache")
    wire_enc_b = _wire("frame_cache_bytes_total", src="encode")
    wire_byte_hit = round(
        wire_cache_b / max(wire_cache_b + wire_enc_b, 1), 3)
    wire_grafted = METRICS.counters.get("wire_spans_grafted_total",
                                        0) - h_graft0
    wire_budget_path = args.wire_budget or os.path.join(
        repo_root, "docs", "scale-tests", "wire_budget.json")
    with open(wire_budget_path) as f:
        wire_budget = json.load(f)

    # Columnar host-state gates (DESIGN §11): the warm fleet shape must
    # stay on the array-native snapshot path end to end — a single
    # fallback (resync aside, none should fire here) or a zero
    # columnar-rows gauge means the fast path silently rotted while
    # every wall clock still passes on a fast machine.  The build-time
    # ceiling is the direct analog of the phase medians: the median of
    # snapshot_build_latency_ms across every cycle both fleet runs took.
    col_fallbacks = METRICS.counters.get(
        "columnar_fallback_total", 0) - col_fb0
    col_rows = METRICS.gauges.get("snapshot_columnar_rows", 0)
    snap_hist = METRICS.histograms.get("snapshot_build_latency_ms")
    snap_build_ms = round(snap_hist.quantile(0.5), 1) \
        if snap_hist is not None else None

    medians = result.get("pod_latency", {}).get("phase_median_ms", {})
    bound = result.get("pod_latency", {}).get("bound_pods", 0)
    expect = shape["jobs"] * shape["gang"]
    checks = [
        ("bound_pods", bound, ">=", expect),
        ("warm_cycle_s", result.get("warm_cycle_s"),
         "<=", budget["max_warm_cycle_s"]),
        ("grouped_median_ms", medians.get("grouped"),
         "<=", budget["max_grouped_ms"]),
        ("snapshotted_median_ms", medians.get("snapshotted"),
         "<=", budget["max_snapshotted_ms"]),
        ("cluster_cache_full_refreshes", refreshes,
         "<=", budget["max_full_refreshes"]),
        ("podgrouper_owner_cache_hits", owner_hits,
         ">=", budget["min_owner_cache_hits"]),
        ("allocate_fused_taken", fused_calls,
         ">=", budget.get("min_fused_taken", 1)),
        ("allocate_kernel_median_ms", round(allocate_ms, 1),
         "<=", budget.get("max_allocate_ms", 400)),
        ("fairshare_step_median_ms", fsres["fairshare_step_ms"],
         "<=", budget.get("max_fairshare_ms", 150)),
        ("fairshare_prep_reuse", fsres["prep_reuse"],
         ">=", budget.get("min_prep_reuse", fs_iters - 1)),
        # Structural: one jitted dispatch per division (warm call + one
        # per measured iteration) — a per-level fallback multiplies this
        # by the hierarchy depth.
        ("fairshare_dispatches", fsres["dispatches"],
         "<=", fs_iters + 1),
        ("rankplace_kernel_median_ms", round(rankplace_ms, 2),
         "<=", budget.get("max_rankplace_ms", 80)),
        ("rankplace_kernel_host_parity", int(rank_parity), ">=", 1),
        # Structural: EXACTLY one jitted decay fold per recorded cycle
        # (never a per-queue host loop) — pinned from both sides.
        ("usage_decay_dispatches", usage_folds,
         "<=", ushape["cycles"]),
        ("usage_decay_dispatches_floor", usage_folds,
         ">=", ushape["cycles"]),
        ("usage_decay_median_ms", round(usage_decay_ms, 2),
         "<=", budget.get("max_usage_decay_ms", 80)),
        ("columnar_fallbacks", col_fallbacks,
         "<=", budget.get("max_columnar_fallbacks", 0)),
        ("columnar_rows", col_rows,
         ">=", budget.get("min_columnar_rows", 1)),
        ("snapshot_build_median_ms", snap_build_ms,
         "<=", budget.get("max_snapshot_build_ms", 400)),
        ("pipelined_bound_pods", p_bound, ">=", expect),
        ("pipelined_warm_cycle_s", pres.get("warm_cycle_s"),
         "<=", budget.get("max_pipelined_warm_cycle_s",
                          budget["max_warm_cycle_s"])),
        ("pipeline_overlap_ratio", p_overlap,
         ">=", budget.get("min_overlap_ratio", 0.08)),
        ("http_bound_pods", h_bound, ">=", h_expect),
        ("http_warm_cycle_s", hres.get("warm_cycle_s"),
         "<=", budget.get("max_http_warm_cycle_s", 3.0)),
        ("http_hot_kind_lists", hot_lists() - h_lists0,
         "<=", budget.get("max_http_hot_kind_lists", 10)),
        ("http_full_refreshes",
         METRICS.counters.get("cluster_cache_full_refresh_total", 0)
         - h_refresh0,
         "<=", budget.get("max_http_full_refreshes", 1)),
        ("http_bulk_bind_waves",
         _labeled("bulk_write_batches_total", path="bind_wave")
         - h_waves0,
         ">=", budget.get("min_http_bulk_bind_waves", 1)),
        ("http_bulk_requests",
         _labeled("apiserver_bulk_requests_total", op="create")
         + _labeled("apiserver_bulk_requests_total", op="patch")
         - h_bulk0,
         ">=", budget.get("min_http_bulk_requests", 2)),
        ("frame_cache_hit_ratio", h_ratio,
         ">=", budget.get("min_frame_cache_hit_ratio", 0.3)),
        ("wire_bytes_per_cycle",
         int(round(wire_client_bytes / h_cycles)),
         "<=", wire_budget["max_bytes_per_cycle"]),
        ("wire_syscalls_per_cycle",
         int(round(wire_client_syscalls / h_cycles)),
         "<=", wire_budget["max_syscalls_per_cycle"]),
        ("wire_encodes_per_cycle",
         int(round(wire_encodes / h_cycles)),
         "<=", wire_budget["max_encodes_per_cycle"]),
        # Serve-path re-encodes exclude the compulsory per-mutation
        # append encode, so a disabled/rotted frame cache reads hundreds
        # per cycle here against a near-zero warm baseline.
        ("wire_serve_encodes_per_cycle",
         int(round(wire_serve_encodes / h_cycles)),
         "<=", wire_budget["max_serve_encodes_per_cycle"]),
        ("frame_cache_byte_hit_ratio", wire_byte_hit,
         ">=", wire_budget["min_frame_cache_byte_hit_ratio"]),
        ("wire_spans_grafted", wire_grafted,
         ">=", wire_budget.get("min_spans_grafted", 1)),
    ]

    # Compile-budget gate (kaijit's runtime half): merge the journal
    # the whole run accumulated against the static jit surface and the
    # committed per-kernel signature ceilings.  A kernel the static
    # analyzer never discovered is an ANALYZER GAP and fails loud; a
    # ceiling breach means someone un-bucketed a shape axis (KJT001's
    # runtime shadow) — both invisible to every wall-clock gate above.
    surface = jittrace.discover_surface()
    cb = jittrace.load_budget(compile_budget_path)
    audit = jittrace.validate_observed(
        surface, [jittrace.TRACER.dump()], budget=cb)
    checks_compile = [
        ("compile_unexplained_kernels", len(audit["unexplained"]),
         "<=", 0),
        ("compile_uncovered_kernels", len(audit["uncovered"]),
         "<=", 0),
    ]
    for kern, n_sigs in audit["kernels"].items():
        ceiling = cb["kernels"].get(kern, cb["default_max"])
        checks_compile.append(
            (f"compile_sigs:{kern.rpartition('.')[2]}", n_sigs,
             "<=", ceiling))
    checks.extend(checks_compile)

    failed = []
    for name, got, op, want in checks:
        ok = (got is not None
              and ((op == "<=" and got <= want)
                   or (op == ">=" and got >= want)))
        mark = "ok  " if ok else "FAIL"
        print(f"{mark} {name:32s} {got!r:>12} {op} {want!r}")
        if not ok:
            failed.append(name)

    if args.json:
        print(json.dumps(result))
    if failed:
        print(f"fleet budget: FAILED ({', '.join(failed)}); the "
              f"committed budget is {budget_path}")
        return 1
    print("fleet budget: within budget")
    return 0


if __name__ == "__main__":
    sys.exit(main())
