"""Chaos matrix: re-run the chaos suite under a sweep of fault seeds.

A chaos test that passes once under one seed proves little — the whole
point of deterministic fault injection (``KAI_FAULT_INJECT`` +
``KAI_FAULT_SEED``) is that the SAME scenarios replay under different
interleavings by just changing the seed.  This harness runs the chaos
marker N times, each iteration with a different ``KAI_FAULT_SEED``, and
fails on ANY flake — one red iteration out of twenty is a real
control-plane bug with a reproducing seed, not noise to rerun away.

Usage:

    python -m kai_scheduler_tpu.tools.chaos_matrix --iterations 20
    python -m kai_scheduler_tpu.tools.chaos_matrix --seeds 7,11,13 \
        --tests tests/test_reconciler.py -k commitlog

The tier-1 suite wires a 3-iteration smoke of this harness
(tests/test_reconciler.py::test_chaos_matrix_smoke); the full sweep is
the ``stress`` pytest marker's job (slow-gated).  Exit code 0 = every
iteration green; 1 = at least one flake (the failing seeds are printed
for replay).
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import time

DEFAULT_TESTS = ["tests/test_reconciler.py", "tests/test_device_guard.py"]
# --arena: the device-arena delta suite — fault seeds exercise
# resync-during-delta and breaker-open-during-scatter interleavings
# (tests/test_snapshot_delta.py reads KAI_FAULT_SEED into its rng).
ARENA_TESTS = ["tests/test_snapshot_delta.py"]
# --latency: the pod-lifecycle suite — fault seeds reshuffle watch gaps,
# binder backoff, fenced aborts, and evict/resubmit interleavings while
# the timeline invariants (no leaked open phases, monotone stamps, new
# attempt per resubmit) are asserted each iteration.
LATENCY_TESTS = ["tests/test_lifecycle.py"]
# --incremental: the incremental-ClusterInfo suite — fault seeds
# reshuffle add/del/mod churn across every consumed kind, resync
# boundaries, and fenced evicts while incremental-vs-full equivalence
# (and identical allocate placements) is asserted at every step.
INCREMENTAL_TESTS = ["tests/test_incremental_cache.py"]
# --fused: the fused-allocation parity ring — each seed regenerates the
# randomized workloads (tests/test_fused_parity.py reads KAI_FAULT_SEED
# into its instance generator) and re-proves legacy/jnp/Pallas
# bit-identity plus the breaker-open fallback.
FUSED_TESTS = ["tests/test_fused_parity.py"]
# --shards: the concurrent-sharded-schedulers churn ring — each seed
# reshuffles the submit/complete stream while two shards cycle in real
# threads against one apiserver, asserting zero double-binds,
# fenced-loser abort, and cross-shard reclaim; plus the queue-forest
# fair-share parity ring (the division both shards rely on), whose
# randomized forests the seed also regenerates.
SHARDS_TESTS = ["tests/test_concurrent_shards.py",
                "tests/test_fairshare_forest.py"]
# --pipeline: the overlapped-cycle suite — each seed reshuffles the
# randomized churn stream while serial-vs-pipelined placement
# bit-identity, fenced-depose speculation rollback, crash-after-journal
# replay, and breaker-open drain-to-serial are asserted.
PIPELINE_TESTS = ["tests/test_pipeline_cycle.py"]
# --columnar: the columnar host-state parity ring — each seed reshuffles
# the randomized watch-delta stream (add/del/mod/resync/fence, plus
# speculative overlays and vocab overflow) while columnar-vs-object
# ClusterInfo equivalence, pack bit-identity, and identical allocate
# placements are asserted at every step.
COLUMNAR_TESTS = ["tests/test_columnar_store.py"]
# --timeaware: the rank & time subsystem rings — each seed regenerates
# the randomized topologies/gangs of the rank-placement parity ring
# (kernel-vs-host bit-identity, hop optimality, parse conventions) and
# re-runs the usage-tensor decay properties (kernel/numpy parity,
# half-life exactness, window cap, restart restore, stale->degraded)
# plus the full-System timeaware trace (over-user yields on bound-pod
# counts, single-dispatch pin, restart survival).
TIMEAWARE_TESTS = ["tests/test_rankplace.py", "tests/test_usagedb.py",
                   "tests/test_timeaware.py"]
# --wire: the daemon-scale apiserver transport ring — pagination
# cursors under concurrent mutation, 410-GONE continue recovery,
# field-selector parity across dialects, per-item bulk outcomes (fenced
# items, torn batch items, crash-after-journal replay through the batch
# path), pool-saturation backpressure, and the watch-mode cache's
# zero-whole-kind-list steady state over a real loopback wire.
WIRE_TESTS = ["tests/test_wire_protocol.py"]
# --wire-faults: the lying-wire ring — each seed reshuffles the churn
# stream while the wire-* fault family (truncated/corrupted watch
# frames, stalled streams, connection reset mid-bulk-POST, 429/503
# storms, 410-GONE compaction storms, response drops) is injected under
# a full System over loopback HTTP, asserting zero double-binds, zero
# lost pods, anti-entropy digest convergence, and bounded cycles —
# including scheduler crash-replay and apiserver restart (seq
# regression) mid bulk-bind-wave.
WIRE_FAULT_TESTS = ["tests/test_wire_faults.py"]
# --wiretrace: the wire-observatory ring (PR 19) — distributed trace
# joins (client wire spans + grafted server_request/phase spans, one
# trace id, Perfetto-exportable), /debug/spans cursor + bounded span
# ring + self-exclusion, graft idempotence (re-grafting the same window
# adds nothing) and client/server byte reconciliation under
# wire-corrupt/reset/drop, and the watch depth-cap GONE contract.
WIRETRACE_TESTS = ["tests/test_wiretrace.py"]
# --compile: the compile-contract ring — the kernel-heaviest suites
# (fused-parity regenerates randomized workloads per seed; rankplace
# and usagedb sweep the rank & time kernels) run with KAI_JITTRACE=1
# (utils/jittrace.py journals each kernel's abstract call signatures =
# XLA compilation keys) and the merged journals are validated against
# the static kaijit surface: a kernel that compiled at runtime but was
# never discovered statically is an analyzer gap and fails the sweep.
COMPILE_TESTS = ["tests/test_fused_parity.py", "tests/test_rankplace.py",
                 "tests/test_usagedb.py"]


def run_iteration(seed: int, tests: list[str], marker: str,
                  keyword: str | None, repo_root: str,
                  timeout_s: float,
                  trace_dir: str | None = None,
                  extra_env: dict | None = None) -> tuple[bool, float, str]:
    """One pytest run under one fault seed; (passed, seconds, tail)."""
    cmd = [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
           "-p", "no:randomly", "-m", marker, *tests]
    # Never select the matrix-harness tests themselves: an iteration
    # that re-runs the smoke/sweep would spawn pytest recursively.
    cmd += ["-k", f"({keyword}) and not chaos_matrix" if keyword
            else "not chaos_matrix"]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               KAI_FAULT_SEED=str(seed))
    # The matrix must control the fault spec per test, not inherit an
    # outer one armed for a different experiment.
    env.pop("KAI_FAULT_INJECT", None)
    # Likewise the locktrace contract: only --races arms it, with a
    # per-seed journal path — an inherited KAI_LOCKTRACE would make
    # iterations overwrite each other's dumps.
    for var in ("KAI_LOCKTRACE", "KAI_LOCKTRACE_OUT",
                "KAI_LOCKTRACE_GRAPH"):
        env.pop(var, None)
    # Same for the compile-signature journal: only --compile arms it.
    for var in ("KAI_JITTRACE", "KAI_JITTRACE_OUT"):
        env.pop(var, None)
    env.update(extra_env or {})
    if trace_dir:
        # The flight recorder (utils/tracing.py) dumps every aborted or
        # degraded cycle's Chrome trace JSON here — the post-mortem
        # artifact for a flaking seed.
        env["KAI_TRACE_DIR"] = trace_dir
    else:
        env.pop("KAI_TRACE_DIR", None)
    t0 = time.monotonic()
    try:
        proc = subprocess.run(cmd, cwd=repo_root, env=env,
                              capture_output=True, text=True,
                              timeout=timeout_s)
        out = (proc.stdout or "") + (proc.stderr or "")
        return proc.returncode == 0, time.monotonic() - t0, out[-2000:]
    except subprocess.TimeoutExpired as exc:
        out = ((exc.stdout or b"").decode(errors="replace")
               if isinstance(exc.stdout, bytes) else (exc.stdout or ""))
        return False, time.monotonic() - t0, \
            f"TIMEOUT after {timeout_s:g}s\n{out[-1000:]}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("kai-chaos-matrix")
    ap.add_argument("--iterations", type=int, default=5,
                    help="number of runs (seeds default to 1..N)")
    ap.add_argument("--seeds", default=None,
                    help="comma-separated explicit KAI_FAULT_SEED sweep "
                         "(overrides --iterations)")
    ap.add_argument("--tests", nargs="*", default=None,
                    help=f"test paths (default: {DEFAULT_TESTS})")
    ap.add_argument("--arena", action="store_true",
                    help="arena mode: sweep the device-arena delta suite "
                         f"({ARENA_TESTS}) — each seed reshuffles the "
                         "event interleavings around resync-during-delta "
                         "and breaker-open-during-scatter")
    ap.add_argument("--latency", action="store_true",
                    help="latency mode: sweep the pod-lifecycle suite "
                         f"({LATENCY_TESTS}) — each seed reshuffles "
                         "watch-gap/backoff/abort interleavings while "
                         "the timeline invariants are asserted")
    ap.add_argument("--incremental", action="store_true",
                    help="incremental mode: sweep the incremental-"
                         f"ClusterInfo suite ({INCREMENTAL_TESTS}) — "
                         "each seed reshuffles churn/resync/fence "
                         "interleavings while incremental-vs-full "
                         "snapshot equivalence is asserted")
    ap.add_argument("--fused", action="store_true",
                    help="fused mode: sweep the fused-allocation parity "
                         f"ring ({FUSED_TESTS}) — each seed regenerates "
                         "the randomized workloads and re-proves "
                         "legacy/jnp/Pallas placement bit-identity")
    ap.add_argument("--shards", action="store_true",
                    help="shards mode: sweep the concurrent-shards churn "
                         f"ring ({SHARDS_TESTS}) — each seed reshuffles "
                         "the submit/complete stream and the randomized "
                         "queue forests while zero-double-bind, "
                         "fenced-loser-abort, and fair-share bit-parity "
                         "are asserted")
    ap.add_argument("--pipeline", action="store_true",
                    help="pipeline mode: sweep the overlapped-cycle "
                         f"suite ({PIPELINE_TESTS}) — each seed "
                         "reshuffles the churn stream while serial-vs-"
                         "pipelined bit-identity, fenced rollback, "
                         "crash-after-journal replay, and breaker-open "
                         "drain-to-serial are asserted")
    ap.add_argument("--columnar", action="store_true",
                    help="columnar mode: sweep the columnar host-state "
                         f"parity ring ({COLUMNAR_TESTS}) — each seed "
                         "reshuffles the watch-delta stream while "
                         "columnar-vs-object equivalence, pack "
                         "bit-identity, and identical allocate "
                         "placements are asserted")
    ap.add_argument("--timeaware", action="store_true",
                    help="timeaware mode: sweep the rank & time "
                         f"subsystem rings ({TIMEAWARE_TESTS}) — each "
                         "seed regenerates the randomized rank-"
                         "placement instances and re-proves kernel/"
                         "host bit-identity, decay-math parity, and "
                         "the over-user-yields trace")
    ap.add_argument("--wire", action="store_true",
                    help="wire mode: sweep the apiserver transport ring "
                         f"({WIRE_TESTS}) — pagination under mutation, "
                         "GONE-continue recovery, field-selector "
                         "dialect parity, per-item bulk outcomes, pool "
                         "backpressure, and the zero-whole-kind-list "
                         "steady state over a real loopback wire")
    ap.add_argument("--wire-faults", action="store_true",
                    help="wire-faults mode: sweep the lying-wire ring "
                         f"({WIRE_FAULT_TESTS}) — each seed reshuffles "
                         "the churn stream under injected wire faults "
                         "(truncate/corrupt/stall/reset/storm/GONE/"
                         "drop) while zero-double-bind, zero-lost-pod, "
                         "and anti-entropy digest convergence are "
                         "asserted, incl. crash-replay and apiserver "
                         "restart mid bulk-bind-wave")
    ap.add_argument("--wiretrace", action="store_true",
                    help="wire-observatory mode: sweep the distributed-"
                         f"tracing ring ({WIRETRACE_TESTS}) — each seed "
                         "reshuffles fleet churn while trace joins "
                         "(grafted server spans, one trace id), graft "
                         "idempotence, client/server byte "
                         "reconciliation under wire-corrupt/reset/drop, "
                         "the bounded /debug/spans ring, and the watch "
                         "depth-cap GONE contract are asserted.  "
                         "Composes with --wire/--wire-faults/--pipeline")
    ap.add_argument("--races", action="store_true",
                    help="runtime lock-order validation: every iteration "
                         "runs with KAI_LOCKTRACE=1 (threading factories "
                         "traced, per-thread acquisition orders recorded "
                         "— utils/locktrace.py) and the merged observed "
                         "orders are checked against the static kairace "
                         "lock graph; any contradiction, uncovered "
                         "threaded subsystem, or empty journal fails "
                         "the sweep.  Composes with every mode flag")
    ap.add_argument("--compile", action="store_true",
                    help="compile-contract validation: sweep the kernel-"
                         f"heaviest suites ({COMPILE_TESTS}) with "
                         "KAI_JITTRACE=1 (every jitted kernel journals "
                         "its abstract call signatures = XLA compile "
                         "keys — utils/jittrace.py) and validate the "
                         "merged journals against the static kaijit "
                         "surface; a runtime compile from a kernel the "
                         "static model never discovered, or an empty "
                         "journal, fails the sweep.  Composes with "
                         "every mode flag (adds its suites + arms the "
                         "tracer for all of them)")
    ap.add_argument("-k", "--keyword", default=None,
                    help="pytest -k filter (narrow the smoke subset)")
    ap.add_argument("--marker", default="chaos",
                    help="pytest marker to select (default: chaos)")
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="per-iteration timeout in seconds")
    ap.add_argument("--trace-dir", default=None,
                    help="keep each FAILING iteration's cycle traces "
                         "(Chrome trace JSON from the flight recorder) "
                         "under <dir>/seed<seed>/ for post-mortem; "
                         "passing iterations' traces are cleaned up")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the fault grid (seed/tests/marker/"
                         "timeout per iteration) without running "
                         "anything — lets CI validate the matrix "
                         "definition cheaply")
    args = ap.parse_args(argv)

    seeds = ([int(s) for s in args.seeds.split(",") if s.strip()]
             if args.seeds else list(range(1, args.iterations + 1)))
    if args.tests:
        tests = args.tests
    else:
        # Modes compose: --arena --latency --incremental --fused
        # --shards --pipeline --columnar --timeaware --wire
        # --wire-faults sweeps every selected suite per seed.
        tests = (ARENA_TESTS if args.arena else []) + \
            (LATENCY_TESTS if args.latency else []) + \
            (INCREMENTAL_TESTS if args.incremental else []) + \
            (FUSED_TESTS if args.fused else []) + \
            (SHARDS_TESTS if args.shards else []) + \
            (PIPELINE_TESTS if args.pipeline else []) + \
            (COLUMNAR_TESTS if args.columnar else []) + \
            (TIMEAWARE_TESTS if args.timeaware else []) + \
            (WIRE_TESTS if args.wire else []) + \
            (WIRE_FAULT_TESTS if args.wire_faults else []) + \
            (WIRETRACE_TESTS if args.wiretrace else []) + \
            (COMPILE_TESTS if args.compile else [])
        if not tests:
            tests = DEFAULT_TESTS
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    if args.trace_dir:
        # The child resolves KAI_TRACE_DIR against cwd=repo_root while
        # the cleanup below resolves against the invoker's cwd — pin
        # both to one absolute path.
        args.trace_dir = os.path.abspath(args.trace_dir)

    def seed_trace_dir(seed: int) -> str | None:
        return (os.path.join(args.trace_dir, f"seed{seed}")
                if args.trace_dir else None)

    if args.dry_run:
        for seed in seeds:
            print(f"seed {seed:>6}  marker={args.marker}  "
                  f"keyword={args.keyword or '-'}  "
                  f"timeout={args.timeout:g}s  "
                  f"trace-dir={seed_trace_dir(seed) or '-'}  "
                  f"races={'on' if args.races else 'off'}  "
                  f"compile={'on' if args.compile else 'off'}  "
                  f"tests={' '.join(tests)}",
                  flush=True)
        if args.races:
            print("races mode: each iteration runs with KAI_LOCKTRACE=1 "
                  "+ a per-seed journal; merged orders are validated "
                  "against the static kairace lock graph", flush=True)
        if args.compile:
            print("compile mode: each iteration runs with KAI_JITTRACE=1 "
                  "+ a per-seed journal; merged compile signatures are "
                  "validated against the static kaijit surface",
                  flush=True)
        print(f"\nchaos matrix (dry run): {len(seeds)} iteration(s) "
              f"planned, nothing executed", flush=True)
        return 0

    races_dir, races_graph = None, None
    if args.races:
        # The static contract is computed ONCE per sweep (the package
        # doesn't change mid-run) and handed to every iteration: the
        # child validates online (live contradiction counters in
        # /metrics), the parent re-validates the merged journals below.
        import json as _json
        import tempfile

        from .kairace.cli import lock_graph, package_root
        races_graph = lock_graph([package_root()])
        if races_graph["errors"]:
            for err in races_graph["errors"]:
                print(f"races: static-graph parse error: {err}",
                      flush=True)
            return 1
        races_dir = tempfile.mkdtemp(prefix="kai-locktrace-")
        graph_path = os.path.join(races_dir, "lock_graph.json")
        with open(graph_path, "w", encoding="utf-8") as fh:
            _json.dump(races_graph, fh)
        print(f"races: static lock graph: "
              f"{len(races_graph['locks'])} lock(s), "
              f"{len(races_graph['edges'])} order edge(s)", flush=True)

    def races_env(seed: int) -> dict:
        if not args.races:
            return {}
        return {"KAI_LOCKTRACE": "1",
                "KAI_LOCKTRACE_OUT": os.path.join(races_dir,
                                                  f"seed{seed}.json"),
                "KAI_LOCKTRACE_GRAPH": os.path.join(races_dir,
                                                    "lock_graph.json")}

    compile_dir, compile_surface = None, None
    if args.compile:
        # The static jit surface is computed ONCE per sweep — the SAME
        # discovery kaijit runs (tools/kailint/jitsurface.py), so the
        # journal and the static model cannot drift.
        import tempfile

        from ..utils.jittrace import discover_surface
        compile_surface = discover_surface()
        if compile_surface["errors"]:
            for err in compile_surface["errors"]:
                print(f"compile: static-surface parse error: {err}",
                      flush=True)
            return 1
        compile_dir = tempfile.mkdtemp(prefix="kai-jittrace-")
        n_jitted = sum(1 for d in compile_surface["kernels"].values()
                       if d.get("jitted"))
        print(f"compile: static jit surface: {n_jitted} jitted "
              f"kernel(s) across "
              f"{len(compile_surface['kernels'])} surface entries",
              flush=True)

    def compile_env(seed: int) -> dict:
        if not args.compile:
            return {}
        return {"KAI_JITTRACE": "1",
                "KAI_JITTRACE_OUT": os.path.join(compile_dir,
                                                 f"seed{seed}.json")}

    rows, failed = [], []
    for seed in seeds:
        tdir = seed_trace_dir(seed)
        ok, secs, tail = run_iteration(seed, tests, args.marker,
                                       args.keyword, repo_root,
                                       args.timeout, trace_dir=tdir,
                                       extra_env={**races_env(seed),
                                                  **compile_env(seed)})
        rows.append((seed, ok, secs))
        status = "ok" if ok else "FLAKE"
        print(f"seed {seed:>6}  {status:<5}  {secs:6.1f}s", flush=True)
        if ok and tdir:
            # Chaos tests abort cycles on purpose; only a flaking seed's
            # traces are post-mortem material.
            shutil.rmtree(tdir, ignore_errors=True)
        if not ok:
            failed.append(seed)
            if tdir and os.path.isdir(tdir):
                print(f"cycle traces kept in {tdir}", flush=True)
            print(tail, flush=True)

    print(f"\nchaos matrix: {len(rows) - len(failed)}/{len(rows)} green",
          flush=True)

    races_red = False
    if args.races:
        races_red = not _report_races(races_dir, races_graph, seeds)
        if races_red or failed:
            # Post-mortem material: the per-seed journals + the static
            # graph they were validated against.
            print(f"races: journals kept in {races_dir}", flush=True)
        else:
            # A green sweep's journals are pure $TMPDIR litter —
            # repeated CI/soak runs would accumulate them unbounded.
            shutil.rmtree(races_dir, ignore_errors=True)

    compile_red = False
    if args.compile:
        compile_red = not _report_compile(compile_dir, compile_surface,
                                          seeds)
        if compile_red or failed:
            print(f"compile: journals kept in {compile_dir}", flush=True)
        else:
            shutil.rmtree(compile_dir, ignore_errors=True)

    if failed:
        print("replay a flake with: "
              f"KAI_FAULT_SEED={failed[0]} python -m pytest -m "
              f"{args.marker} {' '.join(tests)}", flush=True)
        return 1
    return 1 if (races_red or compile_red) else 0


def _report_races(races_dir: str, graph: dict, seeds: list) -> bool:
    """Merge the per-seed locktrace journals, validate against the
    static graph, print the coverage table.  True = validator green."""
    import json as _json

    from ..utils.locktrace import validate_observed
    dumps = []
    for seed in seeds:
        path = os.path.join(races_dir, f"seed{seed}.json")
        try:
            with open(path, encoding="utf-8") as fh:
                dumps.append(_json.load(fh))
        except (OSError, ValueError):
            print(f"races: seed {seed}: no journal at {path} "
                  f"(iteration died before the atexit dump?)",
                  flush=True)
    report = validate_observed(graph, dumps)

    print("\nraces: observed lock orders per threaded subsystem:",
          flush=True)
    for sub, ent in report["subsystems"].items():
        print(f"  {sub:<34} locks={ent['locks_created']:>4}  "
              f"acquires={ent['acquires']:>7}  "
              f"orders={ent['orders']:>3}", flush=True)
    print(f"races: {len(report['orders'])} distinct order(s), "
          f"{len(report['contradictions'])} contradiction(s), "
          f"{len(report['uncovered_subsystems'])} uncovered "
          f"subsystem(s)", flush=True)
    for c in report["contradictions"]:
        a, b = c["observed"]
        print(f"races: CONTRADICTION: observed {a} -> {b} but the "
              f"static graph orders {c['static_path']} — the analyzer "
              f"missed an acquisition path or an annotation rotted",
              flush=True)
    for sub in report["uncovered_subsystems"]:
        print(f"races: UNCOVERED: {sub} created statically-known locks "
              f"but recorded zero acquisitions — the sweep never "
              f"exercised it", flush=True)
    if not report["orders"]:
        print("races: EMPTY journal — a validator that records nothing "
              "validates nothing", flush=True)
    return report["ok"]


def _report_compile(compile_dir: str, surface: dict,
                    seeds: list) -> bool:
    """Merge the per-seed jittrace journals, validate against the
    static kaijit surface, print the signature table.  True = green."""
    import json as _json

    from ..utils.jittrace import validate_observed
    dumps = []
    for seed in seeds:
        path = os.path.join(compile_dir, f"seed{seed}.json")
        try:
            with open(path, encoding="utf-8") as fh:
                dumps.append(_json.load(fh))
        except (OSError, ValueError):
            print(f"compile: seed {seed}: no journal at {path} "
                  f"(iteration died before the atexit dump?)",
                  flush=True)
    report = validate_observed(surface, dumps)

    print("\ncompile: distinct signatures (XLA compile keys) per "
          "kernel, max across seeds:", flush=True)
    for kernel, n in report["kernels"].items():
        short = kernel.replace("kai_scheduler_tpu.", "")
        print(f"  {short:<44} sigs={n:>3}  "
              f"calls={report['calls'].get(kernel, 0):>7}", flush=True)
    print(f"compile: {len(report['kernels'])} kernel(s) journaled, "
          f"{len(report['unexplained'])} unexplained", flush=True)
    for kernel in report["unexplained"]:
        print(f"compile: UNEXPLAINED: {kernel} compiled at runtime but "
              f"the static kaijit surface never discovered it — the "
              f"analyzer's discovery has a gap", flush=True)
    if not report["kernels"]:
        print("compile: EMPTY journal — a validator that records "
              "nothing validates nothing", flush=True)
    return report["ok"]


if __name__ == "__main__":
    sys.exit(main())
