"""Snapshot tool: deterministic offline replay of one scheduling cycle.

Mirrors cmd/snapshot-tool (main.go:35-60): load a snapshot produced by the
snapshot plugin (plugins/snapshot_plugin.dump_cluster), rebuild the cluster
state, run the configured actions through the real framework, and report
what would have happened — with optional per-phase timing for profiling.

Usage:
  python -m kai_scheduler_tpu.tools.snapshot_tool --input snap.json [--time]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from ..api import (ClusterInfo, NodeInfo, PodGroupInfo, PodInfo, PodSet,
                   PodStatus, QueueInfo, QueueQuota)
from ..api.resources import ResourceRequirements
from ..framework import SchedulerConfig
from ..scheduler import Scheduler


def load_cluster(snapshot: dict) -> tuple[ClusterInfo, SchedulerConfig]:
    nodes = {}
    for n in snapshot.get("nodes", []):
        nodes[n["name"]] = NodeInfo(
            n["name"], np.array(n["allocatable"], float),
            labels=n.get("labels", {}), taints=set(n.get("taints", ())),
            gpu_memory_per_device=n.get("gpu_memory_per_device", 0.0),
            max_pods=n.get("max_pods", 110))
    queues = {}
    for q in snapshot.get("queues", []):
        queues[q["uid"]] = QueueInfo(
            q["uid"], name=q.get("name", q["uid"]), parent=q.get("parent"),
            priority=q.get("priority", 0),
            creation_ts=q.get("creation_ts", 0.0),
            quota=QueueQuota(
                deserved=np.array(q["deserved"], float),
                limit=np.array(q["limit"], float),
                over_quota_weight=np.array(q["over_quota_weight"], float)))
    for name, q in queues.items():
        if q.parent and q.parent in queues:
            queues[q.parent].children.append(name)
    podgroups = {}
    for pg_d in snapshot.get("podgroups", []):
        pg = PodGroupInfo(
            pg_d["uid"], pg_d["name"], namespace=pg_d.get("namespace",
                                                          "default"),
            queue_id=pg_d.get("queue", "default"),
            priority=pg_d.get("priority", 0),
            preemptible=pg_d.get("preemptible", True))
        if pg_d.get("pod_sets"):
            pg.set_pod_sets([PodSet(ps["name"], ps["min_available"])
                             for ps in pg_d["pod_sets"]])
        for p in pg_d.get("pods", []):
            req = np.array(p["req"], float)
            task = PodInfo(
                uid=p["uid"], name=p["name"],
                namespace=pg_d.get("namespace", "default"),
                subgroup=p.get("subgroup", "default"),
                status=PodStatus[p.get("status", "PENDING").upper()],
                node_name=p.get("node", ""),
                node_selector=p.get("node_selector", {}),
                tolerations=set(p.get("tolerations", ())),
                res_req=ResourceRequirements(base=req))
            pg.add_task(task)
        podgroups[pg.uid] = pg
    config_d = snapshot.get("config", {})
    config = SchedulerConfig(k_value=config_d.get("k_value", 1.0))
    if config_d.get("actions"):
        config.actions = list(config_d["actions"])
    return ClusterInfo(nodes, podgroups, queues,
                       now=snapshot.get("now", 0.0)), config


def replay(snapshot: dict, show_timing: bool = False) -> dict:
    cluster, config = load_cluster(snapshot)
    sched = Scheduler(lambda: cluster, config)
    t0 = time.perf_counter()
    ssn = sched.run_once()
    elapsed = (time.perf_counter() - t0) * 1000.0
    report = {
        "cycle_ms": round(elapsed, 2),
        "bind_requests": [
            {"pod": br.pod_name, "node": br.node_name}
            for br in ssn.cluster.bind_requests],
        "evictions": list(ssn.cache.evicted),
        "events": [{"reason": k, "message": m} for k, m in
                   ssn.cache.events],
        "fit_errors": {pg.name: pg.fit_errors
                       for pg in ssn.cluster.podgroups.values()
                       if pg.fit_errors},
    }
    if show_timing:
        from ..utils.metrics import METRICS
        report["action_latency_ms"] = {
            name: round(h.mean, 2)
            for name, h in METRICS.histograms.items()
            if name.startswith("action_")}
    return report


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--input", required=True)
    ap.add_argument("--time", action="store_true")
    ap.add_argument("--profile", default=None,
                    help="write a cProfile dump of the replayed cycle "
                         "(snapshot-tool's CPU-profile flag analog)")
    args = ap.parse_args(argv)
    with open(args.input) as f:
        snapshot = json.load(f)
    if args.profile:
        import cProfile
        profiler = cProfile.Profile()
        try:
            report = profiler.runcall(replay, snapshot, args.time)
        finally:
            profiler.dump_stats(args.profile)
    else:
        report = replay(snapshot, args.time)
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
