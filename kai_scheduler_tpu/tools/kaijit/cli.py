"""kaijit command line.

Exit codes (kailint chassis): 0 = clean (every finding suppressed or
baselined), 1 = new findings, 2 = usage/internal error (including a
file the analyzer could not parse — an unchecked file is never a green
one).  The committed baseline (.kaijit-baseline.json) is EMPTY by
contract: any finding is a new compilation-contract break to fix.

Beyond linting, one machine-readable export feeds the runtime auditor:

  --surface   the whole jit surface (kernels, static/dynamic argument
              split, donation, resident-state annotations) that
              ``utils/jittrace.py`` joins observed KAI_JITTRACE compile
              events against (``chaos_matrix --compile`` and the
              fleet_budget compile-budget gate).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ..kailint.engine import Engine, load_baseline, write_baseline
from ..kailint.jitsurface import collect_module_surface, surface_payload
from .rules import RULE_CLASSES, default_rules

BASELINE_NAME = ".kaijit-baseline.json"


def package_root() -> str:
    """Default scan target: the kai_scheduler_tpu package itself."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _default_baseline_path(paths: list[str]) -> str:
    start = os.path.abspath(paths[0]) if paths else os.getcwd()
    cur = start if os.path.isdir(start) else os.path.dirname(start)
    while True:
        cand = os.path.join(cur, BASELINE_NAME)
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.join(os.getcwd(), BASELINE_NAME)
        cur = parent


def build_engine(select=None, ignore=None) -> Engine:
    return Engine(default_rules(), select=select, ignore=ignore,
                  tool="kaijit")


def jit_surface(paths: list[str]) -> dict:
    """Build the whole-program jit-surface payload directly (for
    ``--surface`` and the chaos-matrix/fleet-budget validators)."""
    import ast as _ast

    from ..kailint.engine import iter_python_files, package_relative
    surfaces, errors = {}, []
    for fpath in iter_python_files(paths):
        rel = package_relative(fpath)
        try:
            with open(fpath, encoding="utf-8") as fh:
                src = fh.read()
            tree = _ast.parse(src, filename=fpath)
        except (OSError, UnicodeDecodeError, SyntaxError) as exc:
            errors.append(f"{fpath}: {exc}")
            continue
        module = rel[:-3].replace("/", ".")
        surface = collect_module_surface(tree, src.splitlines(),
                                         module, rel)
        if surface is not None:
            surfaces[module] = surface
    return surface_payload(surfaces, errors)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kai_scheduler_tpu.tools.kaijit",
        description="whole-program JAX compilation-contract analyzer "
                    "for kai_scheduler_tpu (docs/STATIC_ANALYSIS.md); "
                    "runs on the kailint engine chassis")
    ap.add_argument("paths", nargs="*",
                    help="files or directories (default: the whole "
                         "kai_scheduler_tpu package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: nearest {BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true")
    ap.add_argument("--write-baseline", action="store_true")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (e.g. KJT001)")
    ap.add_argument("--ignore", default=None)
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--surface", action="store_true",
                    help="print the discovered jit surface as JSON "
                         "(kernels, static/dynamic split, donation, "
                         "resident-state) and exit — the KAI_JITTRACE "
                         "validator's contract")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in RULE_CLASSES:
            print(f"{cls.id}  {cls.name:<24} {cls.description}")
        return 0
    paths = args.paths or [package_root()]
    for p in paths:
        if not os.path.exists(p):
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2
    if args.surface:
        payload = jit_surface(paths)
        print(json.dumps(payload, indent=2))
        return 2 if payload["errors"] else 0

    known = {cls.id.upper() for cls in RULE_CLASSES}
    filters = {}
    for flag, spec in (("--select", args.select),
                       ("--ignore", args.ignore)):
        if spec is None:
            filters[flag] = None
            continue
        ids = {tok.strip().upper() for tok in spec.split(",")
               if tok.strip()}
        unknown = ids - known
        if unknown:
            print(f"error: unknown rule id(s) for {flag}: "
                  f"{', '.join(sorted(unknown))} (see --list-rules)",
                  file=sys.stderr)
            return 2
        filters[flag] = ids
    select, ignore = filters["--select"], filters["--ignore"]
    engine = build_engine(select=select, ignore=ignore)

    baseline_path = args.baseline or _default_baseline_path(paths)
    if args.write_baseline:
        if select or ignore:
            print("error: --write-baseline cannot be combined with "
                  "--select/--ignore (it would overwrite the other "
                  "rules' baseline entries)", file=sys.stderr)
            return 2
        report = engine.run(paths, baseline=None)
        if report.errors:
            for err in report.errors:
                print(f"kaijit: parse error: {err}", file=sys.stderr)
            print("error: refusing to write a baseline from a partial "
                  "scan (fix the parse errors first)", file=sys.stderr)
            return 2
        n = write_baseline(baseline_path, report.findings,
                           tool="kaijit")
        print(f"kaijit: wrote {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} to {baseline_path}")
        return 0

    try:
        baseline = {} if args.no_baseline else \
            load_baseline(baseline_path, tool="kaijit")
        report = engine.run(paths, baseline=baseline)
    except (OSError, ValueError, KeyError) as exc:
        print(f"kaijit: error: {exc}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps(report.to_dict(), indent=2))
        return report.exit_code

    for f in report.findings:
        print(f.render())
    for err in report.errors:
        print(f"kaijit: parse error: {err}", file=sys.stderr)
    summary = (f"kaijit: {len(report.findings)} new finding(s), "
               f"{len(report.baselined)} baselined, "
               f"{report.suppressed} suppressed, "
               f"{report.files} file(s)")
    if report.stale_baseline:
        summary += (f", {len(report.stale_baseline)} stale baseline "
                    f"entr{'y' if len(report.stale_baseline) == 1 else 'ies'}"
                    f" (fixed — prune with --write-baseline)")
    print(summary)
    return report.exit_code
