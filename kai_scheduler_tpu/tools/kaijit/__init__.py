"""kaijit — whole-program JAX compilation-contract analyzer.

Built on the kailint engine chassis (3-pass rules, fingerprint
baseline, ``# kaijit: disable=`` suppressions, text/JSON CLI, exit
codes 0/1/2) and the shared jit-surface collector
(``tools/kailint/jitsurface.py``) — the same discovery KAI004 guards
with, so the two tools cannot drift.  See docs/STATIC_ANALYSIS.md for
the KJT rule catalog and the compile-key model; ``utils/jittrace.py``
+ ``chaos_matrix --compile`` + the ``tools/fleet_budget.py``
compile-budget gate validate the static model against observed runtime
compile events.
"""

from .cli import build_engine, jit_surface, main
from .rules import RULE_CLASSES, default_rules

__all__ = ["build_engine", "default_rules", "jit_surface", "main",
           "RULE_CLASSES"]
