"""kaijit rules KJT001-KJT006: the JAX compilation contract.

Pass 1 (collect) discovers the whole jit surface through the SHARED
collector ``tools/kailint/jitsurface.py`` — direct ``jax.jit``/
``pjit``/Pallas compile boundaries plus the transitive host wrappers
KAI004 guards — and each kernel's static/dynamic argument split.

Pass 2 builds per-function compile-key models (:class:`FunctionModel`):
which locals are RAW live-cluster sizes (``len(...)``, ``.shape[i]``,
``.size``) and which have been bucketed (a ``pow2``/``bucket`` helper
or the ``while p < t: p *= 2`` doubling idiom).  The model is what the
rules reason over: XLA's compilation key is (shapes, dtypes,
static-arg values), so anything that feeds a jit boundary from an
unbounded domain is a retrace waiting for a bigger cluster.

Pass 3 (check) applies the contract:

- KJT001  unbucketed dynamic shape feeding a jit boundary
- KJT002  retrace-prone static arg (unbounded value domain)
- KJT003  traced-value host escape outside a sanctioned materialize
- KJT004  dtype-pin violation on a resident-state kernel operand
- KJT005  mutable host state captured by a jit-reachable function
- KJT006  missing/unsound donation on resident-buffer update kernels
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..kailint.astutil import dotted_name, in_path, local_calls, \
    top_level_functions
from ..kailint.engine import Finding, ModuleContext, Rule
from ..kailint.jitsurface import (KernelDecl, ModuleSurface,
                                  collect_module_surface, kernel_aliases,
                                  resolve_kernel_call)
from ..kailint.lockscope import walk_executed

# Call-name leaf tokens that mark a bucketing helper: the value that
# comes OUT is drawn from a bounded set of dims no matter how the
# cluster grows.
_BUCKET_TOKENS = ("pow2", "bucket", "pad_to")

# Neutral transforms: the result is a size iff an argument is.
_TRANSPARENT_CALLS = {"max", "min", "int", "abs", "sum"}

# Array constructors whose first argument (or shape=) is a SHAPE.
_SHAPE_CTORS = {"zeros", "ones", "empty", "full", "arange"}

# numpy-ish constructors that accept a dtype, and where it lives
# (positional index; dtype= keyword always counts).
_DTYPE_CTORS = {"zeros": 1, "ones": 1, "empty": 1, "asarray": 1,
                "ascontiguousarray": 1, "full": 2, "array": 1}

_MUTABLE_FACTORIES = {"dict", "list", "set", "defaultdict",
                      "OrderedDict", "Counter", "deque"}


def _leaf(name: str | None) -> str:
    return name.rsplit(".", 1)[-1] if name else ""


def _is_bucket_call(name: str | None) -> bool:
    leaf = _leaf(name).lower()
    return any(tok in leaf for tok in _BUCKET_TOKENS)


class FunctionModel:
    """The compile-key model of one function body: classify each local
    as a raw live-count size ("size") or a bounded bucketed dim
    ("bucketed").  Two lexical passes reach the fixed point for the
    assignment chains the tree actually uses (alias-of-alias)."""

    def __init__(self, fn: ast.AST):
        self.size_vars: set[str] = set()
        self.bucketed_vars: set[str] = set()
        for _ in (0, 1):
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    if node.value is not None:
                        self._record(targets, node.value)
                elif isinstance(node, ast.AugAssign) and \
                        isinstance(node.op, ast.Mult) and \
                        isinstance(node.target, ast.Name):
                    # `p *= 2` — the while-doubling bucketing idiom.
                    self._set(node.target.id, "bucketed")

    def _set(self, name: str, cls: str | None) -> None:
        if cls == "size":
            self.size_vars.add(name)
            self.bucketed_vars.discard(name)
        elif cls == "bucketed":
            self.bucketed_vars.add(name)
            self.size_vars.discard(name)

    def _record(self, targets: list, value: ast.AST) -> None:
        # `a, b = x.shape` — every element is a raw dim.
        for target in targets:
            if isinstance(target, ast.Tuple) and \
                    isinstance(value, ast.Attribute) and \
                    value.attr == "shape" and \
                    isinstance(value.value, ast.Name):
                for elt in target.elts:
                    if isinstance(elt, ast.Name):
                        self._set(elt.id, "size")
            elif isinstance(target, ast.Name):
                self._set(target.id, self.classify(value))

    def classify(self, expr: ast.AST) -> str | None:
        """"size" (raw live count), "bucketed", or None (unknown /
        neither — params and attributes stay unclassified on purpose:
        flagging them would turn every caller into a false positive)."""
        if isinstance(expr, ast.Name):
            if expr.id in self.size_vars:
                return "size"
            if expr.id in self.bucketed_vars:
                return "bucketed"
            return None
        if isinstance(expr, ast.Call):
            name = dotted_name(expr.func)
            if _is_bucket_call(name):
                return "bucketed"
            if name == "len":
                return "size"
            if _leaf(name) in _TRANSPARENT_CALLS:
                return self._combine(expr.args)
            return None
        if isinstance(expr, ast.Attribute) and expr.attr == "size" \
                and isinstance(expr.value, ast.Name):
            return "size"
        if isinstance(expr, ast.Subscript):
            # `x.shape[i]` of a LOCALLY-FLOWING array is a live count;
            # `self.node_idle.shape[0]` / `snap.task_req.shape[1]` read
            # resident/snapshot state whose shape is ALREADY a compiled
            # key of the program — copying such a dim mints no new
            # signature.
            base = expr.value
            if isinstance(base, ast.Attribute) and \
                    base.attr == "shape" and \
                    isinstance(base.value, ast.Name):
                return "size"
            return None
        if isinstance(expr, ast.BinOp):
            return self._combine([expr.left, expr.right])
        if isinstance(expr, ast.IfExp):
            return self._combine([expr.body, expr.orelse])
        return None

    def _combine(self, exprs: list) -> str | None:
        classes = {self.classify(e) for e in exprs}
        if "size" in classes:
            return "size"
        if "bucketed" in classes:
            return "bucketed"
        return None

    def size_names_in(self, expr: ast.AST) -> set[str]:
        """Raw-size Names referenced anywhere inside ``expr``."""
        out = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in self.size_vars:
                out.add(node.id)
        return out


def _iter_functions(tree: ast.Module) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


class SurfaceRule(Rule):
    """Shared pass 1: every kaijit rule sees the same kernel surface."""

    def __init__(self):
        self.surfaces: dict[str, ModuleSurface] = {}

    def collect(self, ctx: ModuleContext) -> None:
        surface = collect_module_surface(ctx.tree, ctx.lines,
                                         ctx.module_name, ctx.path)
        if surface is not None:
            self.surfaces[ctx.module_name] = surface

    def _resolution(self, ctx: ModuleContext):
        direct, mod_alias = kernel_aliases(ctx.tree, ctx.module_name,
                                           self.surfaces)
        local = self.surfaces.get(ctx.module_name)
        return direct, mod_alias, local

    def _kernel_for(self, call: ast.Call, direct, mod_alias,
                    local) -> KernelDecl | None:
        return resolve_kernel_call(call, direct, mod_alias, local,
                                   self.surfaces)


class UnbucketedShapeRule(SurfaceRule):
    id = "KJT001"
    name = "unbucketed-shape"
    description = ("array dim derived from a live cluster count feeds a "
                   "jit boundary without a pow2/bucket helper on the "
                   "path — every new count is a retrace")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        direct, mod_alias, local = self._resolution(ctx)
        if not direct and not mod_alias and local is None:
            return
        for fn in _iter_functions(ctx.tree):
            model = FunctionModel(fn)
            # Names bound to arrays whose shape came from a raw size.
            tainted: dict[str, str] = {}
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                value = node.value
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                sizes = self._ctor_sizes(value, model)
                if not sizes:
                    continue
                for target in targets:
                    if isinstance(target, ast.Name):
                        tainted[target.id] = sizes[0]
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                decl = self._kernel_for(call, direct, mod_alias, local)
                if decl is None or not decl.jitted:
                    continue
                for arg in list(call.args) + \
                        [kw.value for kw in call.keywords]:
                    hit = self._arg_taint(arg, model, tainted)
                    if hit:
                        yield self.finding(
                            ctx, call,
                            f"array shaped by raw live count `{hit}` "
                            f"feeds jit boundary `{decl.name}` — bucket "
                            f"the dim (pow2 helper) before dispatch")
                        break

    @staticmethod
    def _ctor_sizes(expr: ast.AST | None, model: FunctionModel) -> list:
        """Raw-size names shaping an array-constructor expression."""
        if not isinstance(expr, ast.Call):
            return []
        if _leaf(dotted_name(expr.func)) not in _SHAPE_CTORS:
            return []
        shape_args = expr.args[:1] + \
            [kw.value for kw in expr.keywords if kw.arg == "shape"]
        out: list = []
        for sarg in shape_args:
            elts = sarg.elts if isinstance(sarg, ast.Tuple) else [sarg]
            for elt in elts:
                if model.classify(elt) == "size":
                    out.extend(sorted(model.size_names_in(elt)) or
                               ["<derived>"])
        return out

    def _arg_taint(self, arg: ast.AST, model: FunctionModel,
                   tainted: dict) -> str | None:
        for node in ast.walk(arg):
            if isinstance(node, ast.Name) and node.id in tainted:
                return tainted[node.id]
            if isinstance(node, ast.Call):
                sizes = self._ctor_sizes(node, model)
                if sizes:
                    return sizes[0]
        return None


class RetraceStaticArgRule(SurfaceRule):
    id = "KJT002"
    name = "retrace-static-arg"
    description = ("static_argnames value drawn from an unbounded "
                   "domain (live count, float cast, formatted string) — "
                   "every new value is a full retrace")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        direct, mod_alias, local = self._resolution(ctx)
        if not direct and not mod_alias and local is None:
            return
        for fn in _iter_functions(ctx.tree):
            model = FunctionModel(fn)
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                decl = self._kernel_for(call, direct, mod_alias, local)
                if decl is None or not decl.jitted or \
                        not decl.static_argnames:
                    continue
                static = set(decl.static_argnames)
                bound = list(zip(decl.params, call.args))
                bound += [(kw.arg, kw.value) for kw in call.keywords
                          if kw.arg]
                for pname, value in bound:
                    if pname not in static:
                        continue
                    why = self._unbounded(value, model)
                    if why:
                        yield self.finding(
                            ctx, call,
                            f"static arg `{pname}` of `{decl.name}` "
                            f"fed from {why} — an unbounded static "
                            f"domain retraces per value; bucket it or "
                            f"make it a traced operand")

    @staticmethod
    def _unbounded(expr: ast.AST, model: FunctionModel) -> str | None:
        if model.classify(expr) == "size":
            return "a raw live count"
        for node in ast.walk(expr):
            if isinstance(node, ast.JoinedStr):
                return "a formatted string"
            if isinstance(node, ast.Call):
                leaf = _leaf(dotted_name(node.func))
                if leaf == "float":
                    return "a float() cast"
                if leaf == "str":
                    return "a str() cast"
                if leaf == "len" and \
                        model.classify(node) == "size":
                    return "a raw live count"
        return None


class TracedHostEscapeRule(SurfaceRule):
    id = "KJT003"
    name = "traced-host-escape"
    description = ("np.*/float()/.item() on a pipelined kernel result "
                   "in the cycle path — forces a blocking device sync "
                   "outside the sanctioned materialize point")

    _HOST_PREFIXES = ("np.", "numpy.", "jnp.")
    _SCALAR_CASTS = {"float", "int", "bool"}

    def applies_to(self, ctx: ModuleContext) -> bool:
        return in_path(ctx.path, "framework", "actions", "plugins")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _iter_functions(ctx.tree):
            lazy = self._lazy_names(fn)
            if not lazy:
                continue
            # walk_executed skips nested defs/lambdas: a lambda handed
            # to a later dispatch_kernel IS the sanctioned materialize
            # point (`_dispatch_and_fetch`).  Walk the BODY statements —
            # walk_executed(fn) itself would stop at the FunctionDef.
            for node in (n for stmt in fn.body
                         for n in walk_executed(stmt)):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                is_host = name.startswith(self._HOST_PREFIXES) or \
                    name in self._SCALAR_CASTS
                is_item = isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item"
                target = node.func.value if is_item else None
                args = list(node.args) + \
                    [kw.value for kw in node.keywords]
                if is_item and isinstance(target, ast.Name) and \
                        target.id in lazy:
                    hit = target.id
                elif is_host:
                    hit = next((a.id for a in args
                                if isinstance(a, ast.Name)
                                and a.id in lazy), None)
                else:
                    continue
                if hit:
                    yield self.finding(
                        ctx, node,
                        f"host materialization of pipelined kernel "
                        f"result `{hit}` — fetch through a thunk on a "
                        f"second dispatch_kernel (the "
                        f"`_dispatch_and_fetch` idiom), not inline")

    @staticmethod
    def _lazy_names(fn: ast.AST) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if isinstance(value, ast.Call) and \
                    isinstance(value.func, ast.Attribute) and \
                    value.func.attr == "dispatch_kernel" and \
                    any(kw.arg == "blocking" and
                        isinstance(kw.value, ast.Constant) and
                        kw.value.value is False
                        for kw in value.keywords):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        out.add(target.id)
        return out


class DtypePinRule(SurfaceRule):
    id = "KJT004"
    name = "dtype-pin"
    description = ("operand to a resident-state kernel not pinned to "
                   "the arena's resident dtype (the cast-at-host rule) "
                   "— a mismatched width is a new compilation key AND "
                   "an in-kernel upcast of resident state")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        direct, mod_alias, local = self._resolution(ctx)
        # (a) the kernel's own body must fold value operands into the
        # resident dtype (`vals.astype(resident.dtype)`).
        if local is not None:
            funcs = top_level_functions(ctx.tree)
            for decl in local.kernels.values():
                if not decl.resident or not decl.jitted:
                    continue
                fn = funcs.get(decl.name)
                if fn is not None and not self._casts_to_resident(
                        fn, set(decl.resident)):
                    yield self.finding(
                        ctx, fn,
                        f"resident-state kernel `{decl.name}` never "
                        f"casts value operands into a resident dtype "
                        f"(`x.astype({decl.resident[0]}.dtype)`) — a "
                        f"wider host value silently upcasts the arena")
        # (b) call sites: host uploads into a resident kernel must pin
        # the dtype at construction.
        if not direct and not mod_alias and local is None:
            return
        for fn in _iter_functions(ctx.tree):
            ctor_of = self._local_ctors(fn)
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                decl = self._kernel_for(call, direct, mod_alias, local)
                if decl is None or not decl.resident or \
                        not decl.jitted:
                    continue
                for arg in list(call.args) + \
                        [kw.value for kw in call.keywords]:
                    bad = self._unpinned_upload(arg, ctor_of)
                    if bad:
                        yield self.finding(
                            ctx, call,
                            f"host operand `{bad}` uploaded to "
                            f"resident-state kernel `{decl.name}` "
                            f"without an explicit dtype — pin it at "
                            f"construction (cast-at-host rule)")

    @staticmethod
    def _casts_to_resident(fn: ast.AST, resident: set[str]) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "astype":
                for arg in node.args:
                    name = dotted_name(arg) or ""
                    base, _, leaf = name.rpartition(".")
                    if leaf == "dtype" and base in resident:
                        return True
        return False

    @staticmethod
    def _local_ctors(fn: ast.AST) -> dict[str, ast.Call]:
        """name -> the constructor Call it was assigned from."""
        out: dict[str, ast.Call] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        out[target.id] = node.value
        return out

    def _unpinned_upload(self, arg: ast.AST,
                         ctor_of: dict) -> str | None:
        """`jnp.asarray(x)` where x's constructor names no dtype."""
        if not (isinstance(arg, ast.Call) and
                _leaf(dotted_name(arg.func)) in ("asarray", "array")):
            return None
        if self._has_dtype(arg):
            return None
        inner = arg.args[0] if arg.args else None
        if isinstance(inner, ast.Call):
            if self._has_dtype(inner):
                return None
            return dotted_name(inner.func) or "<expr>"
        if isinstance(inner, ast.Name):
            ctor = ctor_of.get(inner.id)
            if ctor is None:
                return None    # param/attribute: origin unknown
            return None if self._has_dtype(ctor) else inner.id
        return None

    @staticmethod
    def _has_dtype(call: ast.Call) -> bool:
        if any(kw.arg == "dtype" for kw in call.keywords):
            return True
        leaf = _leaf(dotted_name(call.func))
        if isinstance(call.func, ast.Attribute) and leaf == "astype":
            return True
        pos = _DTYPE_CTORS.get(leaf)
        return pos is not None and len(call.args) > pos


class MutableClosureCaptureRule(SurfaceRule):
    id = "KJT005"
    name = "mutable-closure-capture"
    description = ("jit-reachable function reads mutable host state "
                   "(module-level container / os.environ) — traced "
                   "once at compile time, silently stale forever after")

    def applies_to(self, ctx: ModuleContext) -> bool:
        return in_path(ctx.path, "ops", "parallel")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        local = self.surfaces.get(ctx.module_name)
        if local is None:
            return
        mutables = self._module_mutables(ctx.tree)
        funcs = top_level_functions(ctx.tree)
        # Reachability FROM the compile boundaries: anything a jitted
        # body calls executes under trace.
        reach = {n for n in local.jitted_names() if n in funcs}
        changed = True
        while changed:
            changed = False
            for name in sorted(reach):
                for callee in local_calls(funcs[name], set(funcs)):
                    if callee not in reach:
                        reach.add(callee)
                        changed = True
        for name in sorted(reach):
            fn = funcs[name]
            params = {a.arg for a in fn.args.posonlyargs +
                      fn.args.args + fn.args.kwonlyargs}
            for node in ast.walk(fn):
                dn = dotted_name(node) if \
                    isinstance(node, ast.Attribute) else None
                if dn == "os.environ":
                    yield self.finding(
                        ctx, node,
                        f"jit-reachable `{name}` reads os.environ — "
                        f"the value is baked into the trace; resolve "
                        f"it at host level and pass it in")
                elif isinstance(node, ast.Name) and \
                        isinstance(node.ctx, ast.Load) and \
                        node.id in mutables and node.id not in params:
                    yield self.finding(
                        ctx, node,
                        f"jit-reachable `{name}` captures mutable "
                        f"module state `{node.id}` — mutations after "
                        f"the first trace are invisible to the "
                        f"compiled kernel")

    @staticmethod
    def _module_mutables(tree: ast.Module) -> set[str]:
        out: set[str] = set()
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            mutable = isinstance(value, (ast.Dict, ast.List, ast.Set))
            if isinstance(value, ast.Call) and \
                    _leaf(dotted_name(value.func)) in _MUTABLE_FACTORIES:
                mutable = True
            if mutable:
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        out.add(target.id)
        return out


class DonationRule(SurfaceRule):
    id = "KJT006"
    name = "resident-donation"
    description = ("resident-buffer update kernel with missing or "
                   "unsound donation — value buffers re-upload every "
                   "cycle, or a donated resident buffer breaks the "
                   "deviceguard retry contract")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        local = self.surfaces.get(ctx.module_name)
        if local is None:
            return
        funcs = top_level_functions(ctx.tree)
        for decl in local.kernels.values():
            if not decl.resident or not decl.jitted:
                continue
            fn = funcs.get(decl.name)
            node = fn if fn is not None else ctx.tree
            unsound = sorted(set(decl.donate) & set(decl.resident))
            if unsound:
                yield self.finding(
                    ctx, node,
                    f"resident-state kernel `{decl.name}` donates "
                    f"resident buffer(s) {', '.join(unsound)} — the "
                    f"deviceguard retry re-runs the thunk against a "
                    f"donated (invalidated) buffer and the arena's "
                    f"old-state-on-failure contract breaks")
            elif not decl.donate:
                yield self.finding(
                    ctx, node,
                    f"resident-state kernel `{decl.name}` declares no "
                    f"donation — per-cycle value operands "
                    f"(non-resident params) should be donated so XLA "
                    f"reuses their buffers instead of re-allocating "
                    f"every update")


RULE_CLASSES = [UnbucketedShapeRule, RetraceStaticArgRule,
                TracedHostEscapeRule, DtypePinRule,
                MutableClosureCaptureRule, DonationRule]


def default_rules() -> list[Rule]:
    return [cls() for cls in RULE_CLASSES]
