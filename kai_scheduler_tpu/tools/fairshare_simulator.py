"""Fair-share simulator: the offline correctness harness for the division
algorithm.

Mirrors cmd/fairshare-simulator (main.go:39-103): POST /simulate with
{"totalResource": {...}, "queues": [...]} -> per-queue fair share.  Grown
(per BASELINE.json config #1) with a --backend flag selecting the
sequential numpy reference or the JAX kernel, so the two can be diffed on
arbitrary snapshots.

Usage:
  python -m kai_scheduler_tpu.tools.fairshare_simulator --port 8099
  python -m kai_scheduler_tpu.tools.fairshare_simulator --input snap.json \
      --backend jax
"""

from __future__ import annotations

import argparse
import json
import sys
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np

from ..api import resources as rs
from ..ops import fairshare as fsops

RESOURCES = ("cpu", "memory", "gpu")


def _vec(d: dict | None, default: float) -> np.ndarray:
    if d is None:
        return np.full(rs.NUM_RES, default)
    return np.array([float(d.get(r, default)) for r in RESOURCES])


def simulate(payload: dict, backend: str = "numpy") -> dict:
    """payload: {"totalResource": {cpu,memory,gpu}, "kValue": float,
    "queues": [{"name", "parent", "priority", "creationTimestamp",
                "deserved", "limit", "overQuotaWeight", "request",
                "allocated", "usage"}]}"""
    queues = payload.get("queues", [])
    total = _vec(payload.get("totalResource"), 0.0)
    k = float(payload.get("kValue", 1.0))
    q = len(queues)
    if q == 0:
        return {"queues": {}}

    names = [qd["name"] for qd in queues]
    index = {n: i for i, n in enumerate(names)}
    parent = np.array([index.get(qd.get("parent"), -1) for qd in queues],
                      np.int64)
    priority = np.array([int(qd.get("priority", 0)) for qd in queues])
    creation = np.array([float(qd.get("creationTimestamp", 0))
                         for qd in queues])
    deserved = np.stack([_vec(qd.get("deserved"), rs.UNLIMITED)
                         for qd in queues])
    limit = np.stack([_vec(qd.get("limit"), rs.UNLIMITED) for qd in queues])
    oqw = np.stack([_vec(qd.get("overQuotaWeight"), 1.0) for qd in queues])
    leaf_request = np.stack([_vec(qd.get("request"), 0.0) for qd in queues])
    usage = np.stack([_vec(qd.get("usage"), 0.0) for qd in queues])
    request = fsops.roll_up_requests(parent, leaf_request)

    if backend == "jax":
        hier = fsops.QueueHierarchy.build(parent, priority, creation, names)
        # Offline CLI: there is no Session (and no device-guard) here —
        # the simulator exists to diff the jax kernel against the
        # sequential reference below, so the call is direct by design.
        # kailint: disable=KAI004 — offline simulator, no Session to dispatch through
        fair = fsops.fair_share_levels(total, k, hier, deserved, limit, oqw,
                                       request, usage)
    else:
        # Sequential reference, level by level (proportion.go:410-425).
        fair = np.zeros((q, rs.NUM_RES))
        by_depth: dict[int, list] = {}
        depth = [0] * q
        for i in range(q):
            d, p = 0, parent[i]
            while p >= 0:
                d, p = d + 1, parent[p]
            depth[i] = d
            by_depth.setdefault(d, []).append(i)
        for d in sorted(by_depth):
            groups: dict[int, list] = {}
            for i in by_depth[d]:
                groups.setdefault(parent[i], []).append(i)
            for p, idxs in groups.items():
                pool = total if p < 0 else fair[p]
                order = sorted(range(len(idxs)),
                               key=lambda j: (creation[idxs[j]],
                                              names[idxs[j]]))
                rank = np.empty(len(idxs), np.int64)
                for r_, j in enumerate(order):
                    rank[j] = r_
                fair[idxs] = fsops.set_resources_share_np(
                    pool, k, deserved[idxs], limit[idxs], oqw[idxs],
                    request[idxs], usage[idxs], priority[idxs], rank)

    return {"queues": {
        name: {"fairShare": {r: fair[i, j] for j, r in enumerate(RESOURCES)}}
        for i, name in enumerate(names)}}


class _Handler(BaseHTTPRequestHandler):
    backend = "numpy"

    def do_POST(self):
        if self.path != "/simulate":
            self.send_error(404)
            return
        length = int(self.headers.get("Content-Length", 0))
        payload = json.loads(self.rfile.read(length) or b"{}")
        result = simulate(payload, self.backend)
        body = json.dumps(result).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0,
                    help="serve HTTP /simulate on this port")
    ap.add_argument("--input", help="simulate a JSON file and print result")
    ap.add_argument("--backend", choices=("numpy", "jax"), default="numpy")
    args = ap.parse_args(argv)

    if args.input:
        with open(args.input) as f:
            payload = json.load(f)
        print(json.dumps(simulate(payload, args.backend), indent=1))
        return
    _Handler.backend = args.backend
    server = HTTPServer(("127.0.0.1", args.port), _Handler)
    print(f"fairshare-simulator listening on :{server.server_port} "
          f"(backend={args.backend})", file=sys.stderr)
    server.serve_forever()


if __name__ == "__main__":
    main()
