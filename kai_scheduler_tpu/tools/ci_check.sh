#!/usr/bin/env bash
# ci_check.sh — one command reproduces the full static + test gate
# locally, exactly as CI runs it:
#
#   ruff          style/pyflakes subset (config: pyproject.toml; the
#                 step is skipped with a warning when ruff is not
#                 installed — the hermetic test image does not bake it)
#   kailint       the project-specific invariant rules KAI001-KAI008
#                 (docs/STATIC_ANALYSIS.md) against the committed
#                 baseline (.kailint-baseline.json)
#   kairace       the whole-program thread-role & lock-contract rules
#                 KRC001-KRC005 (docs/STATIC_ANALYSIS.md) — the
#                 committed baseline (.kairace-baseline.json) is EMPTY
#                 by contract, so any finding is a new race to fix
#   kaijit        the whole-program JAX compilation-contract rules
#                 KJT001-KJT006 (docs/STATIC_ANALYSIS.md) — unbucketed
#                 shapes feeding jit, retrace-prone static args, traced
#                 host escapes, dtype-pin violations, mutable closure
#                 captures, donation contract; the committed baseline
#                 (.kaijit-baseline.json) is EMPTY by contract
#   chaos matrix  --dry-run validation of the fault-grid definition
#                 (including the --races KAI_LOCKTRACE lock-order
#                 validation mode, the --wire-faults lying-wire ring,
#                 the --compile KAI_JITTRACE compile-contract ring,
#                 and the --wiretrace distributed-trace/byte-account
#                 chaos ring)
#   conformance   tools/conformance.py --smoke: every proof in one
#                 command — all three analyzers, every chaos-matrix
#                 mode definition, and a real 1-seed wire-faults sweep
#   kernel parity fused-allocation ladder (Pallas/jnp/legacy) vs the
#                 exact kernel: placements must be bit-identical
#                 (tools/kernel_parity.py --smoke)
#   stackprof     continuous-profiler smoke: profile a short embedded
#                 fleet burst, fail on an empty folded profile
#   fleet budget  bench.py fleet phase at a small shape vs the committed
#                 threshold file (docs/scale-tests/fleet_budget.json):
#                 grouped/snapshotted phase medians, warm cycle, the
#                 incremental-cache structural gates, the fused-allocate
#                 kernel ceiling, the 10k-queue fair-share step
#                 ceiling + single-dispatch/prep-reuse structural gates,
#                 the overlapped-pipeline re-run (identical bound
#                 pods, overlap-ratio floor), the columnar
#                 host-state gates (zero fallbacks warm, columnar rows
#                 served, snapshot-build ceiling), and the http
#                 daemon-regime gates (zero steady-state whole-kind
#                 lists, bulk-endpoint hit floors, preserialized
#                 frame-cache hit ratio) must stay in budget — the
#                 whole run traces under KAI_JITTRACE, so the committed
#                 per-kernel compile-signature ceilings
#                 (docs/scale-tests/compile_budget.json) gate here too,
#                 as do the wire-observatory per-cycle ceilings
#                 (docs/scale-tests/wire_budget.json): bytes/syscalls/
#                 encodes per cycle, serve-path re-encode cap, the
#                 frame-cache byte-hit floor, and a grafted-span floor
#   tier-1 tests  pytest -m 'not slow' on CPU
#
# Usage: kai_scheduler_tpu/tools/ci_check.sh [--no-tests]
set -u
ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/../.." && pwd)"
cd "$ROOT"
fail=0

echo "== ruff =="
if command -v ruff >/dev/null 2>&1; then
    ruff check kai_scheduler_tpu/ tests/ bench.py || fail=1
else
    echo "skipped: ruff not installed (pip install ruff; config already"
    echo "in pyproject.toml [tool.ruff])"
fi

echo
echo "== kailint =="
python -m kai_scheduler_tpu.tools.kailint kai_scheduler_tpu/ || fail=1

echo
echo "== kairace (thread-role & lock-contract analyzer) =="
python -m kai_scheduler_tpu.tools.kairace kai_scheduler_tpu/ || fail=1

echo
echo "== kaijit (JAX compilation-contract analyzer) =="
python -m kai_scheduler_tpu.tools.kaijit kai_scheduler_tpu/ || fail=1

echo
echo "== chaos matrix definition (dry run) =="
python -m kai_scheduler_tpu.tools.chaos_matrix --dry-run || fail=1
python -m kai_scheduler_tpu.tools.chaos_matrix --pipeline --dry-run \
    || fail=1
python -m kai_scheduler_tpu.tools.chaos_matrix --columnar --dry-run \
    || fail=1
python -m kai_scheduler_tpu.tools.chaos_matrix --wire --dry-run \
    || fail=1
python -m kai_scheduler_tpu.tools.chaos_matrix --timeaware --dry-run \
    || fail=1
python -m kai_scheduler_tpu.tools.chaos_matrix --wire-faults --dry-run \
    || fail=1
python -m kai_scheduler_tpu.tools.chaos_matrix --races --dry-run \
    || fail=1
python -m kai_scheduler_tpu.tools.chaos_matrix --compile --dry-run \
    || fail=1
python -m kai_scheduler_tpu.tools.chaos_matrix --wiretrace --dry-run \
    || fail=1

echo
echo "== conformance ring (--smoke: analyzers + matrix defs + 1-seed"
echo "   wire-faults sweep in one command — tools/conformance.py) =="
JAX_PLATFORMS=cpu python -m kai_scheduler_tpu.tools.conformance --smoke \
    || fail=1

echo
echo "== kernel-parity smoke (fused ladder vs legacy vs exact) =="
JAX_PLATFORMS=cpu python -m kai_scheduler_tpu.tools.kernel_parity \
    --smoke || fail=1

echo
echo "== stackprof smoke (profile a short fleet burst) =="
JAX_PLATFORMS=cpu python -m kai_scheduler_tpu.utils.stackprof --smoke \
    || fail=1

echo
echo "== fleet-phase budget (host-pipeline medians vs committed budget) =="
JAX_PLATFORMS=cpu python -m kai_scheduler_tpu.tools.fleet_budget \
    || fail=1

if [ "${1:-}" != "--no-tests" ]; then
    echo
    echo "== tier-1 tests (pytest -m 'not slow') =="
    JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
        -p no:cacheprovider || fail=1
fi

echo
if [ "$fail" -eq 0 ]; then
    echo "ci_check: ALL GREEN"
else
    echo "ci_check: FAILED (see sections above)"
fi
exit "$fail"
