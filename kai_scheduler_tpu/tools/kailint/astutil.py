"""Shared AST helpers for kailint rules."""

from __future__ import annotations

import ast
from typing import Iterator


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain; None for anything else."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


def iter_calls(tree: ast.AST) -> Iterator[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def is_jit_decorator(dec: ast.AST) -> bool:
    """``@jax.jit`` / ``@jit`` / ``@functools.partial(jax.jit, ...)`` /
    ``@partial(jit, ...)``."""
    name = dotted_name(dec)
    if name is not None:
        return name == "jit" or name.endswith(".jit")
    if isinstance(dec, ast.Call):
        fn = dotted_name(dec.func) or ""
        if fn == "jit" or fn.endswith(".jit"):
            return True
        if fn == "partial" or fn.endswith(".partial"):
            return any(is_jit_decorator(a) for a in dec.args)
    return False


def static_argnames_of(dec: ast.AST) -> set[str]:
    """The ``static_argnames`` of a ``partial(jax.jit, ...)`` decorator."""
    out: set[str] = set()
    if not isinstance(dec, ast.Call):
        return out
    for kw in dec.keywords:
        if kw.arg == "static_argnames":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and \
                        isinstance(node.value, str):
                    out.add(node.value)
    return out


def function_params(fn: ast.FunctionDef | ast.AsyncFunctionDef |
                    ast.Lambda) -> set[str]:
    args = fn.args
    names = [a.arg for a in (args.posonlyargs + args.args +
                             args.kwonlyargs)]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return set(names)


def top_level_functions(tree: ast.Module) -> dict[str, ast.FunctionDef]:
    return {node.name: node for node in tree.body
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}


def local_calls(fn: ast.AST, local_names: set[str]) -> set[str]:
    """Names from ``local_names`` that ``fn``'s body calls (or merely
    references — a function passed to ``lax.scan`` is 'called')."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id in local_names:
            out.add(node.id)
    return out


def resolve_relative_import(importer_module: str,
                            node: ast.ImportFrom) -> str | None:
    """Absolute dotted module for a (possibly relative) ImportFrom seen
    inside ``importer_module`` (a module, not a package)."""
    if node.level == 0:
        return node.module
    parts = importer_module.split(".")
    if node.level > len(parts):
        return None
    base = parts[:-node.level]
    if node.module:
        base += node.module.split(".")
    return ".".join(base) if base else None


def in_path(ctx_path: str, *segments: str) -> bool:
    """True when any of ``segments`` appears as a path component (or
    trailing path suffix) of the module's package-relative path."""
    padded = "/" + ctx_path
    return any(f"/{seg.strip('/')}/" in padded or
               padded.endswith("/" + seg.strip("/"))
               for seg in segments)
