"""kailint command line.

Exit codes: 0 = clean (every finding suppressed or baselined),
1 = new findings, 2 = usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .engine import BASELINE_NAME, Engine, load_baseline, write_baseline
from .rules import RULE_CLASSES, default_rules


def _default_baseline_path(paths: list[str]) -> str:
    """Walk up from the first scanned path looking for a committed
    baseline; fall back to CWD so --write-baseline has a target."""
    start = os.path.abspath(paths[0]) if paths else os.getcwd()
    cur = start if os.path.isdir(start) else os.path.dirname(start)
    while True:
        cand = os.path.join(cur, BASELINE_NAME)
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.join(os.getcwd(), BASELINE_NAME)
        cur = parent


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kai_scheduler_tpu.tools.kailint",
        description="AST invariant checker for the kai_scheduler_tpu "
                    "safety contracts (docs/STATIC_ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", help="files or directories")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: nearest {BASELINE_NAME})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baselined or not")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline")
    ap.add_argument("--select", default=None,
                    help="comma-separated rule ids to run (e.g. KAI003)")
    ap.add_argument("--ignore", default=None,
                    help="comma-separated rule ids to skip")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in RULE_CLASSES:
            print(f"{cls.id}  {cls.name:<22} {cls.description}")
        return 0
    if not args.paths:
        ap.print_usage(sys.stderr)
        print("error: no paths given", file=sys.stderr)
        return 2
    for p in args.paths:
        if not os.path.exists(p):
            print(f"error: no such path: {p}", file=sys.stderr)
            return 2

    known = {cls.id.upper() for cls in RULE_CLASSES}
    filters = {}
    for flag, spec in (("--select", args.select),
                      ("--ignore", args.ignore)):
        if spec is None:
            filters[flag] = None
            continue
        ids = {tok.strip().upper() for tok in spec.split(",")
               if tok.strip()}
        unknown = ids - known
        if unknown:
            # A typo'd id silently gating nothing is the worst failure
            # mode a linter can have — refuse loudly instead.
            print(f"error: unknown rule id(s) for {flag}: "
                  f"{', '.join(sorted(unknown))} (see --list-rules)",
                  file=sys.stderr)
            return 2
        filters[flag] = ids
    select, ignore = filters["--select"], filters["--ignore"]
    engine = Engine(default_rules(), select=select, ignore=ignore)

    baseline_path = args.baseline or _default_baseline_path(args.paths)
    if args.write_baseline:
        if select or ignore:
            # A filtered run sees only a subset of findings; writing it
            # out would erase every other rule's entries from the ledger.
            print("error: --write-baseline cannot be combined with "
                  "--select/--ignore (it would overwrite the other "
                  "rules' baseline entries)", file=sys.stderr)
            return 2
        report = engine.run(args.paths, baseline=None)
        if report.errors:
            # Refuse to regenerate the ledger from a partial scan: a
            # baseline written while a file was unparseable would look
            # clean for invariants that were never checked.
            for err in report.errors:
                print(f"kailint: parse error: {err}", file=sys.stderr)
            print("error: refusing to write a baseline from a partial "
                  "scan (fix the parse errors first)", file=sys.stderr)
            return 2
        n = write_baseline(baseline_path, report.findings)
        print(f"kailint: wrote {n} baseline entr"
              f"{'y' if n == 1 else 'ies'} to {baseline_path}")
        return 0

    try:
        baseline = {} if args.no_baseline else load_baseline(baseline_path)
        report = engine.run(args.paths, baseline=baseline)
    except (OSError, ValueError, KeyError) as exc:
        # A corrupt baseline is an internal error (exit 2), never
        # "findings exist" (exit 1) and never a green gate.
        print(f"kailint: error: {exc}", file=sys.stderr)
        return 2

    if args.format == "json":
        print(json.dumps(report.to_dict(), indent=2))
        return report.exit_code

    for f in report.findings:
        print(f.render())
    for err in report.errors:
        print(f"kailint: parse error: {err}", file=sys.stderr)
    summary = (f"kailint: {len(report.findings)} new finding(s), "
               f"{len(report.baselined)} baselined, "
               f"{report.suppressed} suppressed, "
               f"{report.files} file(s)")
    if report.stale_baseline:
        summary += (f", {len(report.stale_baseline)} stale baseline "
                    f"entr{'y' if len(report.stale_baseline) == 1 else 'ies'}"
                    f" (fixed — prune with --write-baseline)")
    print(summary)
    return report.exit_code
