"""Shared jit-kernel-surface discovery: the ONE place that knows what
the device-kernel surface is.

Both kailint's KAI004 (unguarded dispatch) and kaijit (the whole-program
compilation-contract analyzer, ``tools/kaijit/``) need the same answer
to "which functions dispatch to the device?":

- functions directly compiled — ``@jax.jit`` / ``@pjit`` /
  ``@functools.partial(jax.jit, ...)`` decorations, or a body that calls
  ``pl.pallas_call`` (a Pallas launch IS a compile boundary);
- host-facing wrappers that reach a compiled sibling transitively
  (``allocate_grouped`` dispatches to the device even though the
  ``@jit`` sits on an inner kernel) — computed to a fixed point;
- each kernel's compilation-key split: params, ``static_argnames``,
  donated params, and the ``# kaijit: resident-state=`` annotation that
  marks which params are the arena's resident device buffers.

Keeping this in one module means the two tools cannot drift (the
lockscope.py pattern): a kernel KAI004 guards is a kernel kaijit
budget-checks, and a new decoration idiom taught here is immediately
visible to both.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .astutil import (dotted_name, in_path, is_jit_decorator,
                      local_calls, resolve_relative_import,
                      top_level_functions)

# `# kaijit: resident-state=a,b,c` on (or in the comment block directly
# above) a kernel's decorator/def lines: the named params are resident
# device buffers (framework/arena.py keeps them alive across cycles).
RESIDENT_RE = re.compile(
    r"#\s*kaijit:\s*resident-state\s*=\s*"
    r"(?P<params>\w+(?:\s*,\s*\w+)*)")


@dataclass(frozen=True)
class KernelDecl:
    """One device-dispatching function in ops/ or parallel/."""
    name: str
    module: str                 # dotted module (kai_scheduler_tpu.ops.x)
    path: str                   # package-relative posix path
    line: int
    jitted: bool                # directly compiled (jit/pjit/pallas)
    pallas: bool = False        # body launches pl.pallas_call
    params: tuple = ()          # positional parameter order
    static_argnames: tuple = () # sorted
    donate: tuple = ()          # donated PARAM NAMES (argnums resolved)
    resident: tuple = ()        # kaijit: resident-state annotation
    wraps: tuple = ()           # surface names this wrapper reaches

    @property
    def qualname(self) -> str:
        return f"{self.module}.{self.name}"

    def to_dict(self) -> dict:
        return {"name": self.name, "module": self.module,
                "path": self.path, "line": self.line,
                "jitted": self.jitted, "pallas": self.pallas,
                "params": list(self.params),
                "static_argnames": list(self.static_argnames),
                "donate": list(self.donate),
                "resident": list(self.resident),
                "wraps": list(self.wraps)}


@dataclass
class ModuleSurface:
    """The kernel surface of one ops/parallel module."""
    module: str
    path: str
    kernels: dict[str, KernelDecl] = field(default_factory=dict)

    @property
    def names(self) -> set[str]:
        return set(self.kernels)

    def jitted_names(self) -> set[str]:
        return {n for n, k in self.kernels.items() if k.jitted}


def _static_argnames(fn: ast.FunctionDef) -> tuple:
    out: set[str] = set()
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for node in ast.walk(kw.value):
                    if isinstance(node, ast.Constant) and \
                            isinstance(node.value, str):
                        out.add(node.value)
    return tuple(sorted(out))


def _donated_params(fn: ast.FunctionDef, params: tuple) -> tuple:
    """``donate_argnames`` names plus ``donate_argnums`` indices
    resolved against the positional parameter order."""
    out: set[str] = set()
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg == "donate_argnames":
                for node in ast.walk(kw.value):
                    if isinstance(node, ast.Constant) and \
                            isinstance(node.value, str):
                        out.add(node.value)
            elif kw.arg == "donate_argnums":
                for node in ast.walk(kw.value):
                    if isinstance(node, ast.Constant) and \
                            isinstance(node.value, int) and \
                            0 <= node.value < len(params):
                        out.add(params[node.value])
    return tuple(sorted(out))


def _launches_pallas(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if name == "pallas_call" or name.endswith(".pallas_call"):
                return True
    return False


def _resident_annotation(fn: ast.FunctionDef,
                         lines: list[str]) -> tuple:
    """Parse ``# kaijit: resident-state=...`` from the decorator/def
    lines or the contiguous comment block directly above them (the
    kairace single-writer annotation placement)."""
    first = min([d.lineno for d in fn.decorator_list] + [fn.lineno])
    candidates: list[str] = []
    j = first - 2                     # 0-based index of the line above
    while j >= 0 and lines[j].lstrip().startswith("#"):
        candidates.append(lines[j])
        j -= 1
    body_line = fn.body[0].lineno if fn.body else fn.lineno
    candidates.extend(lines[first - 1:body_line - 1])
    for raw in candidates:
        m = RESIDENT_RE.search(raw)
        if m:
            return tuple(p.strip() for p in
                         m.group("params").split(","))
    return ()


def collect_module_surface(tree: ast.Module, lines: list[str],
                           module_name: str,
                           path: str) -> ModuleSurface | None:
    """The kernel surface of one module, or None outside ops/parallel
    (host layers never DEFINE kernels; they only call them)."""
    if not in_path(path, "ops", "parallel"):
        return None
    funcs = top_level_functions(tree)
    jitted: dict[str, bool] = {}      # name -> launches pallas
    for name, fn in funcs.items():
        direct = any(is_jit_decorator(d) for d in fn.decorator_list)
        pallas = _launches_pallas(fn)
        if direct or pallas:
            jitted[name] = pallas
    # Host wrappers that call a kernel dispatch to the device too;
    # iterate to a fixed point (wrapper-of-wrapper).
    surface_names = set(jitted)
    changed = True
    while changed:
        changed = False
        for name, fn in funcs.items():
            if name in surface_names:
                continue
            if local_calls(fn, surface_names):
                surface_names.add(name)
                changed = True
    if not surface_names:
        return None
    out = ModuleSurface(module=module_name, path=path)
    for name in sorted(surface_names):
        fn = funcs[name]
        params = tuple(a.arg for a in fn.args.posonlyargs + fn.args.args)
        is_jit = name in jitted
        wraps = () if is_jit else tuple(sorted(
            local_calls(fn, surface_names - {name})))
        out.kernels[name] = KernelDecl(
            name=name, module=module_name, path=path, line=fn.lineno,
            jitted=is_jit, pallas=jitted.get(name, False),
            params=params, static_argnames=_static_argnames(fn),
            donate=_donated_params(fn, params),
            resident=_resident_annotation(fn, lines), wraps=wraps)
    return out


def kernel_aliases(tree: ast.Module, module_name: str,
                   surfaces: dict[str, ModuleSurface]
                   ) -> tuple[dict, dict]:
    """Resolve a module's import aliases against the discovered surface:
    ``direct`` maps a local alias to its (module, kernel) and
    ``mod_alias`` maps an imported-module alias to its dotted module
    (``from ..ops import rankplace as rp; rp.rank_place_kernel(...)``)."""
    direct: dict[str, tuple[str, str]] = {}
    mod_alias: dict[str, str] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ImportFrom):
            continue
        resolved = resolve_relative_import(module_name, node)
        if resolved is None:
            continue
        surf = surfaces.get(resolved)
        for alias in node.names:
            if surf is not None and alias.name in surf.kernels:
                direct[alias.asname or alias.name] = \
                    (resolved, alias.name)
            if f"{resolved}.{alias.name}" in surfaces:
                mod_alias[alias.asname or alias.name] = \
                    f"{resolved}.{alias.name}"
    return direct, mod_alias


def resolve_kernel_call(call: ast.Call, direct: dict, mod_alias: dict,
                        local_surface: ModuleSurface | None,
                        surfaces: dict[str, ModuleSurface]
                        ) -> KernelDecl | None:
    """The KernelDecl a call site targets, through any alias form —
    local name, ``from ..ops.x import k``, or ``m.k(...)`` module
    alias — or None for a non-kernel call."""
    name = dotted_name(call.func)
    if name is None:
        return None
    if local_surface is not None and name in local_surface.kernels:
        return local_surface.kernels[name]
    if name in direct:
        mod, kernel = direct[name]
        return surfaces[mod].kernels.get(kernel)
    if "." in name:
        base, attr = name.split(".", 1)
        mod = mod_alias.get(base)
        if mod is not None:
            return surfaces[mod].kernels.get(attr)
    return None


def surface_payload(surfaces: dict[str, ModuleSurface],
                    errors: list[str] | None = None) -> dict:
    """The machine-readable export (``kaijit --surface``) that
    utils/jittrace.py's ``validate_observed`` merges runtime compile
    events against."""
    kernels = {}
    for mod in sorted(surfaces):
        for decl in surfaces[mod].kernels.values():
            kernels[decl.qualname] = decl.to_dict()
    return {"kernels": kernels, "errors": list(errors or [])}


__all__ = ["KernelDecl", "ModuleSurface", "RESIDENT_RE",
           "collect_module_surface", "kernel_aliases",
           "resolve_kernel_call", "surface_payload"]
