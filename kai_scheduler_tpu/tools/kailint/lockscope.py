"""Shared lock-scope collector: the ONE place that knows what a lock is.

Both kailint's KAI006 (lock discipline) and kairace (the whole-program
thread-role & lock-contract analyzer, ``tools/kairace/``) need the same
facts about a module:

- which attributes/globals are synchronization primitives, discovered by
  TYPE (``self._x = threading.RLock()``) and not just by name — KAI006's
  original name heuristic missed every ``RLock``/``Condition`` whose
  name didn't contain "lock";
- which Condition objects ALIAS an underlying lock
  (``threading.Condition(self._lock)`` — acquiring the condition IS
  acquiring ``_lock``, so guard analysis must treat them as one);
- which attributes hold instances of in-tree classes
  (``self.log = EventLog(...)``), so ``with self.log.cond:`` resolves to
  ``EventLog.cond``;
- the lexical ``with <lock>:`` regions of a function, with nesting.

Keeping this in one module means the two tools cannot drift: a new lock
kind (or a new aliasing form) taught here is immediately visible to both
the lint rule and the race analyzer.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from .astutil import dotted_name

# Factory callables that mint a synchronization primitive, mapped to the
# primitive kind.  Bare names cover ``from threading import Lock``.
LOCK_FACTORY_KINDS = {
    "threading.Lock": "lock", "Lock": "lock",
    "_thread.allocate_lock": "lock",
    "threading.RLock": "rlock", "RLock": "rlock",
    "threading.Condition": "condition", "Condition": "condition",
    "threading.Semaphore": "semaphore", "Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
    "BoundedSemaphore": "semaphore",
}

# Name tokens that mark a lock even without a visible factory call (a
# lock received as a parameter, or created behind a helper).  Whole-word
# tokens: `journal_lock` is a lock, `clock` is not.
LOCKISH_TOKENS = {"lock", "mutex", "rlock", "semaphore", "sem",
                  "cond", "condition", "cv"}

# Primitives that are NOT locks for ordering/guard purposes: calling
# their methods is thread-safe by construction and holding no lock while
# doing so is fine.
EVENT_FACTORIES = {"threading.Event", "Event", "queue.Queue", "Queue",
                   "queue.SimpleQueue", "SimpleQueue",
                   "collections.deque", "deque",
                   "threading.local", "local", "threading.Barrier",
                   "Barrier"}


def lockish_name(node: ast.AST) -> bool:
    """Name-token heuristic (KAI006's original detector, now shared)."""
    name = dotted_name(node)
    if not name:
        return False
    leaf = name.split(".")[-1].lower()
    tokens = set(re.split(r"[_\W]+", leaf)) - {""}
    return bool(tokens & LOCKISH_TOKENS)


@dataclass
class LockDecl:
    """One declared synchronization attribute/global."""
    kind: str                  # lock | rlock | condition | semaphore
    line: int                  # declaration line (creation site)
    alias_of: str | None = None   # Condition(self._x): alias of attr x


@dataclass
class ModuleLocks:
    """Per-module lock facts (one collector pass over the AST)."""
    # class name -> {attr name -> LockDecl}
    class_locks: dict[str, dict[str, LockDecl]] = field(
        default_factory=dict)
    # module-global name -> LockDecl
    module_locks: dict[str, LockDecl] = field(default_factory=dict)
    # class name -> {attr name -> class name} for self.x = KnownClass()
    attr_classes: dict[str, dict[str, str]] = field(default_factory=dict)
    # class name -> {attr name} for Event/Queue/deque-typed attrs
    class_events: dict[str, set[str]] = field(default_factory=dict)
    # module-global Event/Queue names
    module_events: set[str] = field(default_factory=set)
    # every class name defined in the module (incl. nested)
    classes: set[str] = field(default_factory=set)

    def lock_kind(self, cls: str | None, attr: str) -> str | None:
        if cls is not None:
            decl = self.class_locks.get(cls, {}).get(attr)
            if decl is not None:
                return decl.kind
        return None

    def resolve_alias(self, cls: str, attr: str) -> str:
        """Follow Condition->lock aliasing to the base attribute."""
        seen = set()
        while True:
            decl = self.class_locks.get(cls, {}).get(attr)
            if decl is None or decl.alias_of is None or attr in seen:
                return attr
            seen.add(attr)
            attr = decl.alias_of


def _factory_kind(value: ast.AST) -> str | None:
    if isinstance(value, ast.Call):
        name = dotted_name(value.func)
        if name in LOCK_FACTORY_KINDS:
            return LOCK_FACTORY_KINDS[name]
    return None


def _is_event_factory(value: ast.AST) -> bool:
    if isinstance(value, ast.Call):
        name = dotted_name(value.func)
        return name in EVENT_FACTORIES
    return False


def collect_module_locks(tree: ast.Module,
                         known_classes: set[str] | None = None
                         ) -> ModuleLocks:
    """One pass over a module AST: every ``self.x = <factory>()`` /
    ``X = <factory>()`` declaration, Condition aliasing, and in-tree
    instance attributes.  ``known_classes``: class names from OTHER
    modules, so ``self.log = EventLog(...)`` resolves across imports."""
    out = ModuleLocks()
    known_classes = known_classes or set()

    class_stack: list[str] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.ClassDef):
            out.classes.add(node.name)
            class_stack.append(node.name)
            for child in ast.iter_child_nodes(node):
                visit(child)
            class_stack.pop()
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            value = node.value
            if value is not None:
                _record_assignment(out, class_stack, targets, value,
                                   node.lineno, known_classes)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and class_stack:
            _record_param_types(out, class_stack[-1], node, known_classes)
        for child in ast.iter_child_nodes(node):
            visit(child)

    visit(tree)
    return out


def _record_param_types(out: ModuleLocks, cls: str,
                        fn: ast.FunctionDef | ast.AsyncFunctionDef,
                        known_classes: set[str]) -> None:
    """``self.api = api`` where the ``api`` parameter is annotated with
    an in-tree class types the attribute (the dominant injection idiom:
    ``def __init__(self, api: InMemoryKubeAPI)``)."""
    ann: dict[str, str] = {}
    for arg in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
        if arg.annotation is not None:
            name = dotted_name(arg.annotation)
            if name is None and isinstance(arg.annotation, ast.BinOp):
                # `api: InMemoryKubeAPI | None` — take the left arm
                name = dotted_name(arg.annotation.left)
            if name:
                leaf = name.split(".")[-1]
                if leaf in known_classes or leaf in out.classes:
                    ann[arg.arg] = leaf
    if not ann:
        return
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            value = node.value
            # `self.api = api or InMemoryKubeAPI()` unwraps to the param
            if isinstance(value, ast.BoolOp) and value.values:
                value = value.values[0]
            if isinstance(value, ast.Name) and value.id in ann:
                for target in node.targets:
                    if isinstance(target, ast.Attribute) and \
                            isinstance(target.value, ast.Name) and \
                            target.value.id == "self":
                        out.attr_classes.setdefault(cls, {}) \
                            .setdefault(target.attr, ann[value.id])


def _record_assignment(out: ModuleLocks, class_stack: list[str],
                       targets: list[ast.AST], value: ast.AST,
                       lineno: int, known_classes: set[str]) -> None:
    if isinstance(value, ast.BoolOp) and value.values:
        # `self.api = api or InMemoryKubeAPI()`: the fallback arm still
        # types the attribute.
        for arm in value.values:
            if isinstance(arm, ast.Call):
                value = arm
                break
    kind = _factory_kind(value)
    cls = class_stack[-1] if class_stack else None
    for target in targets:
        self_attr = (isinstance(target, ast.Attribute)
                     and isinstance(target.value, ast.Name)
                     and target.value.id == "self")
        if kind is not None:
            alias = None
            if kind == "condition" and isinstance(value, ast.Call) \
                    and value.args:
                inner = value.args[0]
                if isinstance(inner, ast.Attribute) and \
                        isinstance(inner.value, ast.Name) and \
                        inner.value.id == "self":
                    alias = inner.attr
            if self_attr and cls is not None:
                out.class_locks.setdefault(cls, {})[target.attr] = \
                    LockDecl(kind, lineno, alias_of=alias)
            elif isinstance(target, ast.Name) and not class_stack:
                out.module_locks[target.id] = LockDecl(kind, lineno)
        elif _is_event_factory(value):
            if self_attr and cls is not None:
                out.class_events.setdefault(cls, set()).add(target.attr)
            elif isinstance(target, ast.Name) and not class_stack:
                out.module_events.add(target.id)
        elif isinstance(value, ast.Call):
            ctor = dotted_name(value.func)
            leaf = ctor.split(".")[-1] if ctor else None
            if leaf and (leaf in out.classes or leaf in known_classes) \
                    and self_attr and cls is not None:
                out.attr_classes.setdefault(cls, {})[target.attr] = leaf


# -- lexical with-scope walking ---------------------------------------------

def walk_executed(stmt: ast.AST):
    """ast.walk that does NOT descend into nested function/lambda bodies:
    code merely *defined* under a lock does not run while it is held."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # deferred body — not executed here
        stack.extend(ast.iter_child_nodes(node))


def iter_with_lock_scopes(func_node: ast.AST, is_lock) -> list:
    """Every ``with <lock>:`` region in ``func_node``'s executed body:
    ``[(with_node, lock_exprs, enclosing_lock_exprs)]`` where
    ``enclosing_lock_exprs`` are the lock expressions of lexically
    enclosing ``with`` blocks (nesting order preserved).  ``is_lock`` is
    a predicate over the context expression."""
    scopes: list = []

    def visit(node: ast.AST, held: tuple) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not func_node:
            return  # deferred body
        if isinstance(node, ast.With):
            locks = [item.context_expr for item in node.items
                     if is_lock(item.context_expr)]
            if locks:
                scopes.append((node, locks, list(held)))
                held = held + tuple(locks)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    visit(func_node, ())
    return scopes
