"""kailint engine: module loading, suppressions, baseline, reporting.

The engine is rule-agnostic plumbing.  It walks ``.py`` files, parses
each into a :class:`ModuleContext` (AST + per-line suppression map), runs
every registered rule through a two-pass protocol — ``collect`` over all
modules first (cross-module facts like "which ops functions are jitted
kernels"), then ``check`` per module, then ``finalize`` for whole-tree
rules — and filters the resulting findings through per-line/per-file
suppressions and the committed baseline.

Finding identity (the baseline key) is deliberately line-number-free:
``sha1(rule | relpath | normalized source text)``.  Edits above a
baselined site don't invalidate it; editing the flagged line itself
does — which is exactly when a human should re-decide.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Iterable, Iterator

def suppress_re(tool: str) -> re.Pattern:
    """The per-tool suppression marker.  The engine is shared chassis
    (kailint and kairace both run on it); each tool reads only its OWN
    ``# <tool>: disable=`` comments, so a kairace suppression never
    silently disables a kailint rule on the same line (and vice versa)."""
    return re.compile(
        rf"#\s*{re.escape(tool)}:\s*disable(?P<file>-file)?\s*=\s*"
        r"(?P<rules>all|[A-Za-z]+\d+(?:\s*,\s*[A-Za-z]+\d+)*)")


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # posix path, package-relative (kai_scheduler_tpu/..)
    line: int
    col: int
    message: str
    source: str = ""   # stripped text of the flagged line
    # Other sites that constitute the SAME defect (multi-site contract
    # findings: e.g. KRC001 reports one write but the conflict is the
    # SET of writes).  A suppression at any related site silences the
    # finding — the author reviewed that site of the conflict.  Excluded
    # from the fingerprint and the baseline schema on purpose.
    related: tuple = ()   # ((path, line), ...)

    @property
    def fingerprint(self) -> str:
        norm = " ".join(self.source.split())
        raw = f"{self.rule}|{self.path}|{norm}".encode()
        return hashlib.sha1(raw).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "source": self.source, "fingerprint": self.fingerprint}

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule} {self.message}")


class ModuleContext:
    """One parsed module: AST, source lines, and its suppression map."""

    def __init__(self, path: str, source: str, tool: str = "kailint"):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.tool = tool
        self._suppress_re = suppress_re(tool)
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line number -> set of rule ids (or "ALL") suppressed there
        self.line_suppressions: dict[int, set[str]] = {}
        self.file_suppressions: set[str] = set()
        self._parse_suppressions()

    @property
    def module_name(self) -> str:
        return self.path[:-3].replace("/", ".") if \
            self.path.endswith(".py") else self.path.replace("/", ".")

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def _comment_lines(self) -> dict[int, str]:
        """line number -> comment text, via the tokenizer — a string
        literal that merely *mentions* the suppression syntax must not
        disable enforcement on its line."""
        out: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):
            # ast.parse accepted the file, so this is near-unreachable;
            # degrade to the raw lines rather than dropping suppressions.
            return {i: raw for i, raw in enumerate(self.lines, 1)
                    if "#" in raw}
        return out

    def _parse_suppressions(self) -> None:
        comments = self._comment_lines()
        pending: set[str] | None = None
        for i, raw in enumerate(self.lines, 1):
            stripped = raw.strip()
            m = self._suppress_re.search(comments.get(i, ""))
            if m:
                spec = m.group("rules")
                rules = ({"ALL"} if spec == "all" else
                         {r.strip().upper() for r in spec.split(",")})
                if m.group("file"):
                    self.file_suppressions |= rules
                elif stripped.startswith("#"):
                    # Standalone comment line: applies to the next
                    # non-comment line (multi-line statements put the
                    # marker above the statement).
                    pending = set(rules) | (pending or set())
                else:
                    # A code line with its own inline suppression is
                    # also "the next non-comment line" for any pending
                    # standalone marker above it — consume the pending
                    # here, or it would leak onto a later unrelated
                    # line and silently suppress real findings there.
                    self.line_suppressions.setdefault(i, set()) \
                        .update(rules | (pending or set()))
                    pending = None
                continue
            if stripped and not stripped.startswith("#") and pending:
                self.line_suppressions.setdefault(i, set()) \
                    .update(pending)
                pending = None

    def is_suppressed(self, finding: Finding) -> bool:
        if self.is_line_suppressed(finding.rule, None):
            return True
        return self.is_line_suppressed(finding.rule, finding.line)

    def is_line_suppressed(self, rule: str, line: int | None) -> bool:
        """``line=None`` asks only about file-level suppression."""
        keys = {rule.upper(), "ALL"}
        if self.file_suppressions & keys:
            return True
        if line is None:
            return False
        return bool(self.line_suppressions.get(line, set()) & keys)


class Rule:
    """Base class: subclasses set ``id``/``name``/``description`` and
    override any of the three passes."""

    id = "KAI000"
    name = "base"
    description = ""

    def applies_to(self, ctx: ModuleContext) -> bool:
        return True

    def collect(self, ctx: ModuleContext) -> None:
        """Pass 1 over every module (cross-module fact gathering)."""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        """Pass 2: yield findings for one module."""
        return iter(())

    def finalize(self) -> Iterator[Finding]:
        """Pass 3: whole-tree findings (duplicate registrations etc.)."""
        return iter(())

    # -- helpers -----------------------------------------------------------
    def finding(self, ctx: ModuleContext, node: ast.AST,
                message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=self.id, path=ctx.path, line=line, col=col,
                       message=message, source=ctx.line_at(line))


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)   # non-baselined
    baselined: list[Finding] = field(default_factory=list)
    suppressed: int = 0
    files: int = 0
    errors: list[str] = field(default_factory=list)
    stale_baseline: list[dict] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        # Parse errors are exit 2: a file the analyzer could not read is
        # a file whose invariants went UNCHECKED — that must never look
        # like a green gate.
        if self.errors:
            return 2
        return 1 if self.findings else 0

    def to_dict(self) -> dict:
        return {
            "findings": [f.to_dict() for f in self.findings],
            "baselined": [f.to_dict() for f in self.baselined],
            "suppressed": self.suppressed,
            "files": self.files,
            "errors": self.errors,
            "stale_baseline": self.stale_baseline,
            "exit_code": self.exit_code,
        }


# -- path anchoring ---------------------------------------------------------

def package_relative(path: str) -> str:
    """Anchor ``path`` at the outermost enclosing package: walk up while
    an ``__init__.py`` sibling exists, then return the path relative to
    that package's parent.  Makes findings/baselines stable no matter
    what directory the analyzer is invoked from."""
    path = os.path.abspath(path)
    root = os.path.dirname(path)
    while os.path.isfile(os.path.join(root, "__init__.py")):
        parent = os.path.dirname(root)
        if parent == root:
            break
        root = parent
    return os.path.relpath(path, root).replace(os.sep, "/")


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if not d.startswith(".") and d != "__pycache__")
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield os.path.join(dirpath, fn)


# -- engine -----------------------------------------------------------------

class Engine:
    def __init__(self, rules: list[Rule] | None = None,
                 select: set[str] | None = None,
                 ignore: set[str] | None = None,
                 tool: str = "kailint"):
        self.tool = tool
        if rules is None:
            from .rules import default_rules
            rules = default_rules()
        if select:
            sel = {s.upper() for s in select}
            rules = [r for r in rules if r.id.upper() in sel]
        if ignore:
            ign = {s.upper() for s in ignore}
            rules = [r for r in rules if r.id.upper() not in ign]
        self.rules = rules
        # A filtered run sees only a subset of findings, so "this
        # baseline entry matched nothing" proves nothing — stale
        # reporting is only meaningful on a full-rule run.
        self.filtered = bool(select or ignore)

    # -- in-memory entry point (fixture tests) ----------------------------
    def run_modules(self, modules: list[tuple[str, str]]) -> Report:
        """Run the full pipeline over ``[(relpath, source), ...]``."""
        # Fresh rule instances per run: stateful rules (KAI004's kernel
        # map, KAI008's call sites) must not leak facts from a previous
        # run into this one — a reused Engine is a supported caller.
        rules = [type(r)() for r in self.rules]
        report = Report()
        contexts: list[ModuleContext] = []
        for relpath, source in modules:
            try:
                contexts.append(ModuleContext(relpath, source,
                                              tool=self.tool))
            except SyntaxError as exc:
                report.errors.append(f"{relpath}: {exc}")
        report.files = len(contexts)
        for rule in rules:
            for ctx in contexts:
                if rule.applies_to(ctx):
                    rule.collect(ctx)
        raw: list[Finding] = []
        by_path = {ctx.path: ctx for ctx in contexts}
        for rule in rules:
            for ctx in contexts:
                if rule.applies_to(ctx):
                    raw.extend(rule.check(ctx))
        for rule in rules:
            raw.extend(rule.finalize())
        seen: set[tuple] = set()
        for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
            # One defect, one finding: overlapping walks (nested lock
            # blocks, nested defs) may surface the same site twice.
            key = (f.rule, f.path, f.line, f.col, f.message)
            if key in seen:
                continue
            seen.add(key)
            ctx = by_path.get(f.path)
            suppressed = ctx is not None and ctx.is_suppressed(f)
            if not suppressed:
                # A multi-site finding (f.related) is one defect spread
                # over several sites; a suppression at ANY of them is a
                # reviewed decision about the whole conflict.
                for rpath, rline in f.related:
                    rctx = by_path.get(rpath)
                    if rctx is not None and \
                            rctx.is_line_suppressed(f.rule, rline):
                        suppressed = True
                        break
            if suppressed:
                report.suppressed += 1
            else:
                report.findings.append(f)
        return report

    # -- filesystem entry point -------------------------------------------
    def run(self, paths: Iterable[str],
            baseline: dict | None = None) -> Report:
        modules: list[tuple[str, str]] = []
        errors: list[str] = []
        for fpath in iter_python_files(paths):
            try:
                with open(fpath, encoding="utf-8") as fh:
                    modules.append((package_relative(fpath), fh.read()))
            except (OSError, UnicodeDecodeError) as exc:
                # An unreadable file is an UNCHECKED file — it must land
                # in report.errors (exit 2), not crash the analyzer.
                errors.append(f"{fpath}: {exc}")
        report = self.run_modules(modules)
        report.errors = errors + report.errors
        if baseline is not None:
            apply_baseline(report, baseline,
                           report_stale=not self.filtered)
        return report


# -- baseline ---------------------------------------------------------------

BASELINE_NAME = ".kailint-baseline.json"


def load_baseline(path: str, tool: str = "kailint") -> dict:
    """fingerprint -> entry dict.  Missing file = empty baseline; a
    shape-corrupt file raises ValueError (exit 2 at the CLI), never a
    raw traceback that an exit-code consumer misreads as findings."""
    if not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    entries = data.get("entries", []) if isinstance(data, dict) else None
    if not isinstance(entries, list) or not all(
            isinstance(e, dict) and "fingerprint" in e for e in entries):
        raise ValueError(
            f"{path}: not a {tool} baseline (expected an object with "
            f"an 'entries' list of fingerprinted records); regenerate "
            f"with --write-baseline")
    return {e["fingerprint"]: e for e in entries}


def write_baseline(path: str, findings: list[Finding],
                   tool: str = "kailint") -> int:
    seen: dict[str, dict] = {}
    for f in findings:
        entry = seen.setdefault(f.fingerprint, {
            "rule": f.rule, "path": f.path, "source": f.source,
            "message": f.message, "fingerprint": f.fingerprint,
            "count": 0})
        # Identical lines share a fingerprint; the count pins how many
        # occurrences the ledger covers, so ADDING another copy of a
        # baselined violation still fails the gate.
        entry["count"] += 1
    entries = sorted(seen.values(),
                     key=lambda e: (e["path"], e["rule"], e["fingerprint"]))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "tool": tool, "entries": entries},
                  fh, indent=2, sort_keys=True)
        fh.write("\n")
    return len(entries)


def apply_baseline(report: Report, baseline: dict,
                   report_stale: bool = True) -> None:
    """Split report.findings into new vs baselined; record stale
    baseline entries (fixed sites a human should prune).  Pass
    ``report_stale=False`` for rule-filtered runs — an entry unmatched
    because its rule never ran is not stale."""
    new: list[Finding] = []
    matched: dict[str, int] = {}
    for f in report.findings:
        entry = baseline.get(f.fingerprint)
        budget = int(entry.get("count", 1)) if entry else 0
        if entry is not None and matched.get(f.fingerprint, 0) < budget:
            matched[f.fingerprint] = matched.get(f.fingerprint, 0) + 1
            report.baselined.append(f)
        else:
            new.append(f)
    report.findings = new
    if report_stale:
        report.stale_baseline = [e for fp, e in sorted(baseline.items())
                                 if fp not in matched]
