"""KAI007: exception swallowing in controller loops.

A reconciler that catches ``Exception`` and does *nothing* converts
every bug into silence: the loop keeps spinning, the object never
converges, and the operator has no signal.  The failure modes PR 2
hardened against (fenced writes, watch gaps, crash recovery) were all
diagnosed from logs and counters — a swallowed exception deletes that
evidence.

Scope: ``controllers/`` and ``server.py``.  Flagged: a bare ``except:``
or ``except Exception/BaseException:`` whose body neither raises nor
calls anything (no log, no metric, no event) — i.e. pure
``pass``/``continue``/bare ``return``.  The fix is to narrow the
exception type, or to log + count (``METRICS.inc``) before moving on;
both make the handler invisible to this rule.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..astutil import in_path
from ..engine import Finding, ModuleContext, Rule

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in _BROAD
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in _BROAD
                   for e in t.elts)
    return False


def _swallows(handler: ast.ExceptHandler) -> bool:
    """True when the body has no observable effect: no raise, no call
    (log/metric/event), no assignment feeding later handling."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Call, ast.Assign,
                             ast.AugAssign, ast.Yield, ast.YieldFrom)):
            return False
        if isinstance(node, ast.Return) and node.value is not None:
            return False
    return True


class ExceptionSwallowingRule(Rule):
    id = "KAI007"
    name = "exception-swallowing"
    description = ("broad except that drops the error in controller "
                   "loops — narrow it, or log + count")

    def applies_to(self, ctx: ModuleContext) -> bool:
        return in_path(ctx.path, "controllers", "server.py")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and \
                    _is_broad(node) and _swallows(node):
                what = "bare except" if node.type is None else \
                    "except Exception"
                yield self.finding(
                    ctx, node,
                    f"{what} swallows the error — narrow the exception "
                    f"type, or log it and count it (METRICS.inc) so the "
                    f"failure is visible")
