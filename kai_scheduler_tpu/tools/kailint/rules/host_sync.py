"""KAI002: host sync in the hot path.

``block_until_ready`` / ``device_get`` force a device->host round trip
(~70-100ms each on a tunneled TPU).  The device-guard is the ONE commit
point allowed to sync — it owns the watchdog deadline that makes a hung
sync recoverable (PR 1).  Anywhere else, a sync silently serializes the
pipelined cycle and bypasses the watchdog: a dead device hangs the
scheduler instead of tripping the breaker.

``print`` in hot-path modules (ops/, parallel/, framework/, actions/,
plugins/) is flagged too: printing a traced array forces the same sync,
and the repo's ScopedLogger is the sanctioned output path.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..astutil import dotted_name, in_path, iter_calls
from ..engine import Finding, ModuleContext, Rule

# The device-guard IS the commit point: its _sync() is where the
# watchdog-supervised materialization happens by design.
ALLOWLIST = ("utils/deviceguard.py",)

_SYNC_ATTRS = {"block_until_ready", "device_get"}
_PRINT_SCOPE = ("ops", "parallel", "framework", "actions", "plugins")


class HostSyncRule(Rule):
    id = "KAI002"
    name = "host-sync-in-hot-path"
    description = ("block_until_ready/device_get outside the device-guard "
                   "commit point; print in hot-path modules")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        allowed = any(ctx.path.endswith(a) for a in ALLOWLIST)
        hot = in_path(ctx.path, *_PRINT_SCOPE)
        for call in iter_calls(ctx.tree):
            name = dotted_name(call.func) or ""
            attr = call.func.attr if \
                isinstance(call.func, ast.Attribute) else name
            if not allowed and attr in _SYNC_ATTRS:
                yield self.finding(
                    ctx, call,
                    f"`{attr}` outside the device-guard commit point — "
                    f"route the dispatch through Session.dispatch_kernel "
                    f"so the watchdog supervises the sync")
            elif hot and name == "print":
                yield self.finding(
                    ctx, call,
                    "print() in a hot-path module — printing a traced "
                    "array forces a device sync; use the ScopedLogger")
