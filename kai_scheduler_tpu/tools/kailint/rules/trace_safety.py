"""KAI001: trace-safety inside jit-reachable code.

Scope: modules under ``ops/`` and ``parallel/`` — the code that runs
under ``jax.jit``.  A function is *jit-reachable* when it is decorated
with ``jax.jit``/``partial(jax.jit, ...)`` or is (transitively) called
from one that is, within the same module.  Inside that code, host-level
Python control flow and host materialization break tracing — either a
``ConcretizationTypeError`` at runtime or, worse, a silent recompile per
distinct value:

- ``bool(x)`` / ``float(x)`` / ``int(x)`` / ``x.item()`` on a traced
  value force a device sync at trace time;
- ``np.*`` calls drop the tracer to host numpy (constant-folds the
  traced value or crashes);
- ``if``/``while`` on a traced expression raises under jit.

Static arguments (``static_argnames``) are concrete at trace time and
exempt; so are shape/dtype accesses, ``is None`` staging checks, and
host helpers that jitted code never calls.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..astutil import (dotted_name, function_params, in_path,
                       is_jit_decorator, local_calls, static_argnames_of,
                       top_level_functions)
from ..engine import Finding, ModuleContext, Rule

_CASTS = {"bool", "float", "int"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "at"}
_STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr", "range",
                 "enumerate", "zip", "type", "tuple", "list", "dict"}
# jax host-introspection calls: concrete Python values at trace time.
_STATIC_DOTTED = {"jax.default_backend", "jax.device_count",
                  "jax.local_device_count", "jax.devices",
                  "jax.local_devices"}


class TraceSafetyRule(Rule):
    id = "KAI001"
    name = "trace-safety"
    description = ("host control flow / host numpy / device sync inside "
                   "jit-reachable ops code")

    def applies_to(self, ctx: ModuleContext) -> bool:
        return in_path(ctx.path, "ops", "parallel")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        funcs = top_level_functions(ctx.tree)
        jitted: dict[str, set[str]] = {}
        for name, fn in funcs.items():
            if any(is_jit_decorator(d) for d in fn.decorator_list):
                statics: set[str] = set()
                for d in fn.decorator_list:
                    statics |= static_argnames_of(d)
                jitted[name] = statics
        # Transitive closure: helpers called from jitted code trace too.
        reachable: dict[str, set[str]] = dict(jitted)
        frontier = list(jitted)
        while frontier:
            fn = funcs[frontier.pop()]
            for callee in local_calls(fn, set(funcs)):
                if callee not in reachable:
                    reachable[callee] = set()  # helper args: all traced
                    frontier.append(callee)
        for name, statics in reachable.items():
            yield from self._check_function(ctx, funcs[name], statics)

    def _check_function(self, ctx: ModuleContext, fn: ast.FunctionDef,
                        statics: set[str]) -> Iterator[Finding]:
        traced_params = function_params(fn) - statics
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                yield from self._check_call(ctx, fn, node, traced_params)
            elif isinstance(node, (ast.If, ast.While)):
                if self._is_traced(node.test, traced_params):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield self.finding(
                        ctx, node,
                        f"Python `{kind}` on a traced value in "
                        f"jit-reachable `{fn.name}` — use lax.cond/"
                        f"lax.while_loop or jnp.where")

    def _check_call(self, ctx: ModuleContext, fn: ast.FunctionDef,
                    call: ast.Call,
                    traced_params: set[str]) -> Iterator[Finding]:
        name = dotted_name(call.func)
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr == "item":
            yield self.finding(
                ctx, call,
                f".item() in jit-reachable `{fn.name}` forces a host "
                f"sync — keep the value on device")
            return
        if name and (name.startswith("np.") or name.startswith("numpy.")):
            yield self.finding(
                ctx, call,
                f"host numpy call `{name}` in jit-reachable `{fn.name}` "
                f"— use jnp (host numpy constant-folds or crashes the "
                f"tracer)")
            return
        if name in _CASTS and len(call.args) == 1 and \
                self._is_traced(call.args[0], traced_params):
            yield self.finding(
                ctx, call,
                f"`{name}()` on a traced value in jit-reachable "
                f"`{fn.name}` forces a host sync at trace time")

    # -- traced-ness heuristic --------------------------------------------
    def _is_traced(self, node: ast.AST, params: set[str]) -> bool:
        """Conservative: an expression is traced when it (dataflow-
        visibly) touches a non-static parameter.  Shape/dtype accesses,
        ``is``/``is not`` staging checks, and host calls are static."""
        if isinstance(node, ast.Name):
            return node.id in params
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self._is_traced(node.value, params)
        if isinstance(node, ast.Subscript):
            return self._is_traced(node.value, params)
        if isinstance(node, ast.UnaryOp):
            return self._is_traced(node.operand, params)
        if isinstance(node, ast.BinOp):
            return self._is_traced(node.left, params) or \
                self._is_traced(node.right, params)
        if isinstance(node, ast.BoolOp):
            return any(self._is_traced(v, params) for v in node.values)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False  # `x is None` stages out at trace time
            return self._is_traced(node.left, params) or \
                any(self._is_traced(c, params) for c in node.comparators)
        if isinstance(node, ast.Call):
            fname = dotted_name(node.func) or ""
            if fname in _STATIC_DOTTED:
                return False
            if fname in _STATIC_CALLS or fname.split(".")[-1] in \
                    _STATIC_CALLS:
                return False
            if fname.startswith(("jnp.", "jax.", "lax.")):
                return True  # jnp.any(...) & co produce traced arrays
            if isinstance(node.func, ast.Attribute) and node.func.attr in {
                    "any", "all", "sum", "max", "min", "mean", "astype"}:
                return self._is_traced(node.func.value, params)
            return False  # other host calls are concrete at trace time
        return False
