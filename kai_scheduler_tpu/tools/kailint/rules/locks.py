"""KAI006: lock discipline.

Two failure shapes, both of which have bitten every threaded scheduler:

- **Bare ``lock.acquire()``** as a statement: any exception between
  ``acquire`` and ``release`` leaks the lock and wedges every other
  thread forever.  ``with lock:`` is exception-safe and costs nothing.
  (``acquired = lock.acquire(timeout=...)`` try-lock patterns keep the
  result and are not flagged.)

- **Blocking calls while holding a lock**: an HTTP round trip, fsync,
  sleep, or device dispatch under a lock turns one slow syscall into a
  fleet-wide stall — every thread contending on that lock inherits the
  latency (and, with the device-guard, a hung dispatch holds the lock
  for the whole watchdog deadline).  Flagged lexically inside ``with
  <lock>:`` blocks.  Sites where the serialization IS the contract (WAL
  appends in utils/commitlog.py) carry explicit suppressions.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..astutil import dotted_name
from ..engine import Finding, ModuleContext, Rule

_LOCKISH = {"lock", "mutex", "rlock", "semaphore", "sem"}

_BLOCKING_DOTTED = {
    "time.sleep", "os.fsync", "urllib.request.urlopen", "subprocess.run",
    "subprocess.check_call", "subprocess.check_output", "socket.create_connection",
}
_BLOCKING_ATTRS = {"fsync", "urlopen", "dispatch_kernel",
                   "block_until_ready"}


def _is_lockish(node: ast.AST) -> bool:
    name = dotted_name(node)
    if not name:
        return False
    # Whole-word tokens, not substrings: `journal_lock` is a lock,
    # `clock` (which merely CONTAINS "lock") is not.
    leaf = name.split(".")[-1].lower()
    tokens = set(re.split(r"[_\W]+", leaf)) - {""}
    return bool(tokens & _LOCKISH)


class LockDisciplineRule(Rule):
    id = "KAI006"
    name = "lock-discipline"
    description = ("bare lock.acquire() instead of `with`; blocking call "
                   "made while a lock is held")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Expr) and \
                    isinstance(node.value, ast.Call):
                # An .acquire() whose result is DISCARDED (expression
                # statement) is always wrong: with no args it leaks on
                # exception; with timeout= the False result is dropped
                # and the code proceeds unlocked.  Try-lock patterns
                # keep the result (Assign/If) and are not Expr nodes.
                call = node.value
                if isinstance(call.func, ast.Attribute) and \
                        call.func.attr == "acquire" and \
                        _is_lockish(call.func.value):
                    yield self.finding(
                        ctx, node,
                        "bare .acquire() on a lock — use `with lock:` "
                        "(or keep the acquire result and check it) so "
                        "an exception or timeout cannot leave the lock "
                        "state wrong")
            elif isinstance(node, ast.With):
                if any(_is_lockish(item.context_expr)
                       for item in node.items):
                    yield from self._check_held(ctx, node)

    def _check_held(self, ctx: ModuleContext,
                    with_node: ast.With) -> Iterator[Finding]:
        for stmt in with_node.body:
            for node in _walk_executed(stmt):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                attr = node.func.attr if \
                    isinstance(node.func, ast.Attribute) else name
                if name in _BLOCKING_DOTTED or attr in _BLOCKING_ATTRS:
                    yield self.finding(
                        ctx, node,
                        f"blocking call `{name or attr}` while holding a "
                        f"lock — every contending thread inherits this "
                        f"latency; move it outside the critical section")


def _walk_executed(stmt: ast.AST):
    """Walk like ast.walk but do not descend into nested function or
    lambda bodies: code merely *defined* under the lock does not run
    while the lock is held."""
    stack = [stmt]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # deferred body — not executed under the lock
        stack.extend(ast.iter_child_nodes(node))
