"""KAI006: lock discipline.

Three failure shapes, all of which have bitten every threaded scheduler:

- **Bare ``lock.acquire()``** as a statement: any exception between
  ``acquire`` and ``release`` leaks the lock and wedges every other
  thread forever.  ``with lock:`` is exception-safe and costs nothing.
  (``acquired = lock.acquire(timeout=...)`` try-lock patterns keep the
  result and are not flagged.)  Locks are recognized by NAME (whole-word
  tokens: lock/mutex/rlock/semaphore/cond/cv) **and by TYPE** via the
  shared lock-scope collector (``tools/kailint/lockscope.py``): an
  ``RLock``/``Condition``/``Semaphore`` assigned to an innocently named
  attribute is still a lock.

- **Blocking calls while holding a lock**: an HTTP round trip, fsync,
  sleep, or device dispatch under a lock turns one slow syscall into a
  fleet-wide stall — every thread contending on that lock inherits the
  latency (and, with the device-guard, a hung dispatch holds the lock
  for the whole watchdog deadline).  Flagged lexically inside ``with
  <lock>:`` blocks.  Sites where the serialization IS the contract (WAL
  appends in utils/commitlog.py) carry explicit suppressions.

- **``notify``/``wait`` outside the condition's lock**: calling
  ``Condition.notify()``/``notify_all()``/``wait()`` without holding the
  condition raises ``RuntimeError`` at runtime — but only on the
  interleaving that reaches it, which is exactly the interleaving a test
  suite misses.  Flagged statically; ``threading.Condition(self._lock)``
  aliasing is honored, so ``with self._lock: self._cv.notify()`` is
  clean.

The lock-scope collector is shared with kairace (the whole-program
thread-role analyzer) so the two tools cannot drift on what counts as a
lock.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..astutil import dotted_name
from ..engine import Finding, ModuleContext, Rule
from ..lockscope import (ModuleLocks, collect_module_locks, lockish_name,
                         walk_executed)

_BLOCKING_DOTTED = {
    "time.sleep", "os.fsync", "urllib.request.urlopen", "subprocess.run",
    "subprocess.check_call", "subprocess.check_output", "socket.create_connection",
}
_BLOCKING_ATTRS = {"fsync", "urlopen", "dispatch_kernel",
                   "block_until_ready"}

_CONDITION_METHODS = {"notify", "notify_all", "wait", "wait_for"}


class LockDisciplineRule(Rule):
    id = "KAI006"
    name = "lock-discipline"
    description = ("bare lock.acquire() instead of `with`; blocking call "
                   "made while a lock is held; Condition notify/wait "
                   "outside its lock")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        locks = collect_module_locks(ctx.tree)
        yield from self._visit(ctx, locks, ctx.tree, cls=None, held=())

    # -- lock identity ------------------------------------------------------
    def _declared_kind(self, locks: ModuleLocks, cls: str | None,
                       node: ast.AST) -> str | None:
        """Primitive kind of a lock expression, via the collector: a
        self-attr declared in the enclosing class, a module global, or a
        one-hop instance attribute (``self.log.cond``)."""
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id == "self" and cls:
                decl = locks.class_locks.get(cls, {}).get(node.attr)
                return decl.kind if decl else None
            if isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and cls:
                owner = locks.attr_classes.get(cls, {}).get(base.attr)
                if owner:
                    decl = locks.class_locks.get(owner, {}).get(node.attr)
                    return decl.kind if decl else None
        elif isinstance(node, ast.Name):
            decl = locks.module_locks.get(node.id)
            return decl.kind if decl else None
        return None

    def _is_event(self, locks: ModuleLocks, cls: str | None,
                  node: ast.AST) -> bool:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and cls:
            return node.attr in locks.class_events.get(cls, set())
        if isinstance(node, ast.Name):
            return node.id in locks.module_events
        return False

    def _is_lockish(self, locks: ModuleLocks, cls: str | None,
                    node: ast.AST) -> bool:
        if self._declared_kind(locks, cls, node) is not None:
            return True
        # Name tokens only count when the attribute is not KNOWN to be a
        # non-lock primitive (an Event named `_sem_ready` is an Event).
        return lockish_name(node) and not self._is_event(locks, cls, node)

    def _canonical(self, locks: ModuleLocks, cls: str | None,
                   node: ast.AST) -> str:
        """Identity for held-vs-used comparison: self attrs resolve
        Condition->lock aliases; everything else compares dotted text."""
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and cls:
            return f"{cls}.{locks.resolve_alias(cls, node.attr)}"
        return dotted_name(node) or ast.dump(node)

    # -- the walk -----------------------------------------------------------
    def _visit(self, ctx: ModuleContext, locks: ModuleLocks,
               node: ast.AST, cls: str | None,
               held: tuple) -> Iterator[Finding]:
        if isinstance(node, ast.ClassDef):
            for child in ast.iter_child_nodes(node):
                yield from self._visit(ctx, locks, child, node.name, held)
            return
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            call = node.value
            if isinstance(call.func, ast.Attribute) and \
                    call.func.attr == "acquire" and \
                    self._is_lockish(locks, cls, call.func.value):
                # An .acquire() whose result is DISCARDED (expression
                # statement) is always wrong: with no args it leaks on
                # exception; with timeout= the False result is dropped
                # and the code proceeds unlocked.  Try-lock patterns
                # keep the result (Assign/If) and are not Expr nodes.
                yield self.finding(
                    ctx, node,
                    "bare .acquire() on a lock — use `with lock:` "
                    "(or keep the acquire result and check it) so "
                    "an exception or timeout cannot leave the lock "
                    "state wrong")
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _CONDITION_METHODS:
            recv = node.func.value
            if self._declared_kind(locks, cls, recv) == "condition":
                want = self._canonical(locks, cls, recv)
                if want not in held:
                    yield self.finding(
                        ctx, node,
                        f"Condition.{node.func.attr}() without holding "
                        f"the condition's lock — RuntimeError at "
                        f"runtime, but only on the interleaving that "
                        f"reaches it; wrap in `with {dotted_name(recv)}:`")
        if isinstance(node, ast.With):
            lock_items = [item.context_expr for item in node.items
                          if self._is_lockish(locks, cls,
                                              item.context_expr)]
            if lock_items:
                yield from self._check_held(ctx, node)
                held = held + tuple(self._canonical(locks, cls, e)
                                    for e in lock_items)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # A nested def/lambda body is deferred: locks held HERE are
            # not held when it runs, so its walk starts with empty held.
            for child in ast.iter_child_nodes(node):
                yield from self._visit(ctx, locks, child, cls, ())
            return
        for child in ast.iter_child_nodes(node):
            yield from self._visit(ctx, locks, child, cls, held)

    def _check_held(self, ctx: ModuleContext,
                    with_node: ast.With) -> Iterator[Finding]:
        for stmt in with_node.body:
            for node in walk_executed(stmt):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                attr = node.func.attr if \
                    isinstance(node.func, ast.Attribute) else name
                if name in _BLOCKING_DOTTED or attr in _BLOCKING_ATTRS:
                    yield self.finding(
                        ctx, node,
                        f"blocking call `{name or attr}` while holding a "
                        f"lock — every contending thread inherits this "
                        f"latency; move it outside the critical section")
