"""KAI004: unguarded device dispatch.

Every kernel invocation from host code must route through
``Session.dispatch_kernel`` — that is where the watchdog deadline,
bounded retry, circuit breaker, and CPU degradation live (PR 1).  A
direct call to a jitted kernel bypasses all of it: a hung device wedges
the scheduling cycle with no deadline and no breaker trip.

The kernel surface itself comes from the SHARED discovery module
``tools/kailint/jitsurface.py`` (the lockscope pattern): pass 1 scans
``ops/`` and ``parallel/`` modules for top-level functions that are
jit/pjit/Pallas-compiled OR (transitively) call a compiled sibling —
host-facing wrappers like ``allocate_grouped`` dispatch to the device
even though the ``@jit`` sits on an inner kernel.  kaijit (the
compilation-contract analyzer) consumes the same surface, so the two
tools cannot drift.  Pass 2 then flags any call to one of those names
from host layers, resolving ``from ..ops.x import k`` aliases and
``from ..ops import x as m; m.k(...)`` module aliases.  Calls inside a
``lambda`` are exempt — that is precisely the thunk handed to
``dispatch_kernel`` — and so are calls inside a named nested function
that is itself passed to a ``dispatch_kernel(...)`` call (the
multi-statement thunk idiom).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..astutil import dotted_name, in_path
from ..engine import Finding, ModuleContext, Rule
from ..jitsurface import (ModuleSurface, collect_module_surface,
                          kernel_aliases)


class UnguardedDispatchRule(Rule):
    id = "KAI004"
    name = "unguarded-dispatch"
    description = ("direct kernel call bypassing Session.dispatch_kernel "
                   "(no watchdog, no breaker, no CPU fallback)")

    def __init__(self):
        # module dotted name -> its discovered kernel surface
        self.surfaces: dict[str, ModuleSurface] = {}

    def applies_to(self, ctx: ModuleContext) -> bool:
        return True

    def collect(self, ctx: ModuleContext) -> None:
        surface = collect_module_surface(ctx.tree, ctx.lines,
                                         ctx.module_name, ctx.path)
        if surface is not None:
            self.surfaces[ctx.module_name] = surface

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        # ops/parallel modules compose kernels freely (they ARE the
        # device layer); the guard boundary is everything else.
        if in_path(ctx.path, "ops", "parallel") or \
                ctx.path.endswith("utils/deviceguard.py"):
            return
        direct, mod_alias = kernel_aliases(ctx.tree, ctx.module_name,
                                           self.surfaces)
        if not direct and not mod_alias:
            return
        thunks = self._dispatch_thunk_names(ctx.tree)
        yield from self._walk(ctx, ctx.tree, direct, mod_alias,
                              thunks, in_thunk=False)

    @staticmethod
    def _dispatch_thunk_names(tree: ast.AST) -> set[str]:
        """Names of functions passed (as a bare Name argument) to a
        ``dispatch_kernel(...)`` call — named thunks are guarded."""
        out: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "dispatch_kernel":
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        out.add(arg.id)
        return out

    def _walk(self, ctx: ModuleContext, node: ast.AST, direct: dict,
              mod_alias: dict, thunks: set[str],
              in_thunk: bool) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            child_in_thunk = in_thunk or isinstance(child, ast.Lambda) \
                or (isinstance(child, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                    and child.name in thunks)
            if isinstance(child, ast.Call) and not child_in_thunk:
                name = dotted_name(child.func)
                flagged = None
                if name in direct:
                    flagged = direct[name][1]
                elif name and "." in name:
                    base, attr = name.split(".", 1)
                    mod = mod_alias.get(base)
                    if mod is not None and \
                            attr in self.surfaces[mod].kernels:
                        flagged = name
                if flagged:
                    yield self.finding(
                        ctx, child,
                        f"direct call to device kernel `{flagged}` — "
                        f"wrap it in a thunk and route through "
                        f"Session.dispatch_kernel")
            yield from self._walk(ctx, child, direct, mod_alias,
                                  thunks, child_in_thunk)
