"""KAI004: unguarded device dispatch.

Every kernel invocation from host code must route through
``Session.dispatch_kernel`` — that is where the watchdog deadline,
bounded retry, circuit breaker, and CPU degradation live (PR 1).  A
direct call to a jitted kernel bypasses all of it: a hung device wedges
the scheduling cycle with no deadline and no breaker trip.

The rule discovers the kernel surface itself rather than keeping a
hand-maintained list: pass 1 scans ``ops/`` and ``parallel/`` modules
for top-level functions that are jit-decorated OR (transitively) call a
jitted sibling — host-facing wrappers like ``allocate_grouped`` dispatch
to the device even though the ``@jit`` sits on an inner kernel.  Pass 2
then flags any call to one of those names from host layers, resolving
``from ..ops.x import k`` aliases and ``from ..ops import x as m;
m.k(...)`` module aliases.  Calls inside a ``lambda`` are exempt — that
is precisely the thunk handed to ``dispatch_kernel`` — and so are calls
inside a named nested function that is itself passed to a
``dispatch_kernel(...)`` call (the multi-statement thunk idiom).
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..astutil import (dotted_name, in_path, is_jit_decorator, local_calls,
                       resolve_relative_import, top_level_functions)
from ..engine import Finding, ModuleContext, Rule


class UnguardedDispatchRule(Rule):
    id = "KAI004"
    name = "unguarded-dispatch"
    description = ("direct kernel call bypassing Session.dispatch_kernel "
                   "(no watchdog, no breaker, no CPU fallback)")

    def __init__(self):
        # module dotted name -> set of kernel (device-dispatching) names
        self.kernels_by_module: dict[str, set[str]] = {}

    def applies_to(self, ctx: ModuleContext) -> bool:
        return True

    def collect(self, ctx: ModuleContext) -> None:
        if not in_path(ctx.path, "ops", "parallel"):
            return
        funcs = top_level_functions(ctx.tree)
        kernels = {name for name, fn in funcs.items()
                   if any(is_jit_decorator(d) for d in fn.decorator_list)}
        # Host wrappers that call a kernel dispatch to the device too;
        # iterate to a fixed point (wrapper-of-wrapper).
        changed = True
        while changed:
            changed = False
            for name, fn in funcs.items():
                if name in kernels:
                    continue
                if local_calls(fn, kernels):
                    kernels.add(name)
                    changed = True
        if kernels:
            self.kernels_by_module[ctx.module_name] = kernels

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        # ops/parallel modules compose kernels freely (they ARE the
        # device layer); the guard boundary is everything else.
        if in_path(ctx.path, "ops", "parallel") or \
                ctx.path.endswith("utils/deviceguard.py"):
            return
        direct: dict[str, str] = {}    # local alias -> kernel name
        mod_alias: dict[str, set[str]] = {}  # alias -> kernel names
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            resolved = resolve_relative_import(ctx.module_name, node)
            if resolved is None:
                continue
            kernels = self.kernels_by_module.get(resolved)
            for alias in node.names:
                if kernels and alias.name in kernels:
                    direct[alias.asname or alias.name] = alias.name
                sub = self.kernels_by_module.get(
                    f"{resolved}.{alias.name}")
                if sub:
                    mod_alias[alias.asname or alias.name] = sub
        if not direct and not mod_alias:
            return
        thunks = self._dispatch_thunk_names(ctx.tree)
        yield from self._walk(ctx, ctx.tree, direct, mod_alias,
                              thunks, in_thunk=False)

    @staticmethod
    def _dispatch_thunk_names(tree: ast.AST) -> set[str]:
        """Names of functions passed (as a bare Name argument) to a
        ``dispatch_kernel(...)`` call — named thunks are guarded."""
        out: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "dispatch_kernel":
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        out.add(arg.id)
        return out

    def _walk(self, ctx: ModuleContext, node: ast.AST, direct: dict,
              mod_alias: dict, thunks: set[str],
              in_thunk: bool) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            child_in_thunk = in_thunk or isinstance(child, ast.Lambda) \
                or (isinstance(child, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                    and child.name in thunks)
            if isinstance(child, ast.Call) and not child_in_thunk:
                name = dotted_name(child.func)
                flagged = None
                if name in direct:
                    flagged = direct[name]
                elif name and "." in name:
                    base, attr = name.split(".", 1)
                    if attr in mod_alias.get(base, ()):
                        flagged = name
                if flagged:
                    yield self.finding(
                        ctx, child,
                        f"direct call to device kernel `{flagged}` — "
                        f"wrap it in a thunk and route through "
                        f"Session.dispatch_kernel")
            yield from self._walk(ctx, child, direct, mod_alias,
                                  thunks, child_in_thunk)
