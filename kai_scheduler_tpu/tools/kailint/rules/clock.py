"""KAI003: wall-clock discipline in timing-sensitive modules.

Lease expiry, watchdog deadlines, retry backoff, and fencing decisions
must never be computed from the wall clock: NTP steps turn every clock
jump into a spurious leader takeover or a watchdog misfire (PR 2 made
``LeaseElector`` expiry observation-based on ``time.monotonic`` for
exactly this reason).  In scoped modules (``utils/``, ``controllers/``,
``framework/``, ``scheduler.py``, ``server.py``) a *call* to
``time.time()`` / ``datetime.now()`` / ``datetime.utcnow()`` is flagged.

Two sanctioned patterns are NOT flagged:

- injection points — ``def __init__(self, clock=time.time)`` references
  the function without calling it, and the injected ``self.clock()``
  call site is opaque to this rule by design;
- legitimately-wall-clock sites (journal timestamps, certificate
  validity, ``status.backoffUntil`` that other processes compare against
  their own wall clock) carry an explicit suppression::

      now = time.time()  # kailint: disable=KAI003 — wall-clock intentional
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..astutil import dotted_name, in_path, iter_calls
from ..engine import Finding, ModuleContext, Rule

_WALL_CLOCK_CALLS = {
    "time.time": "time.monotonic() (or an injected clock)",
    "datetime.now": "time.monotonic() for durations",
    "datetime.utcnow": "time.monotonic() for durations",
    "datetime.datetime.now": "time.monotonic() for durations",
    "datetime.datetime.utcnow": "time.monotonic() for durations",
}

_SCOPE = ("utils", "controllers", "framework", "scheduler.py", "server.py")


class WallClockRule(Rule):
    id = "KAI003"
    name = "wall-clock-discipline"
    description = ("time.time()/datetime.now() in lease/backoff/fencing "
                   "paths — must be monotonic or an injected clock")

    def applies_to(self, ctx: ModuleContext) -> bool:
        return in_path(ctx.path, *_SCOPE)

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        aliases = self._import_aliases(ctx.tree)
        for call in iter_calls(ctx.tree):
            name = dotted_name(call.func)
            canonical = aliases.get(name or "", name or "")
            want = _WALL_CLOCK_CALLS.get(canonical)
            if want:
                yield self.finding(
                    ctx, call,
                    f"wall-clock `{name}()` in a timing-sensitive module "
                    f"— use {want}; if wall-clock is intentional, "
                    f"suppress with a reason")

    @staticmethod
    def _import_aliases(tree: ast.AST) -> dict[str, str]:
        """Map aliased call spellings back to canonical dotted names so
        neither ``from time import time`` / ``from datetime import
        datetime as dt`` nor ``import time as clk`` / ``import datetime
        as dt`` can evade the gate."""
        out: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and not node.level:
                for alias in node.names:
                    local = alias.asname or alias.name
                    if node.module == "time" and alias.name == "time":
                        out[local] = "time.time"
                    elif node.module == "datetime" and \
                            alias.name == "datetime":
                        out[f"{local}.now"] = "datetime.datetime.now"
                        out[f"{local}.utcnow"] = \
                            "datetime.datetime.utcnow"
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name
                    if alias.name == "time":
                        out[f"{local}.time"] = "time.time"
                    elif alias.name == "datetime":
                        out[f"{local}.datetime.now"] = \
                            "datetime.datetime.now"
                        out[f"{local}.datetime.utcnow"] = \
                            "datetime.datetime.utcnow"
                        out[f"{local}.now"] = "datetime.datetime.now"
                        out[f"{local}.utcnow"] = \
                            "datetime.datetime.utcnow"
        return out
