"""KAI008: metrics hygiene.

The metrics registry (utils/metrics.py) is schemaless by design — which
means nothing but convention stops two call sites from colliding: the
same name used as both a counter and a histogram renders twice in the
Prometheus text exposition (a scrape error), and a name that isn't
``snake_case`` breaks every PromQL consumer.  Label-key consistency
matters for the same reason: ``metric{queue="a"}`` and a bare ``metric``
are different series that Prometheus refuses to merge.

Per-module checks: metric-name literals must be ``snake_case``
(``^[a-z][a-z0-9_]*$``, no ``__``, no trailing ``_``).  Whole-tree
checks (finalize): one name must map to exactly one instrument type
(inc / observe / set_gauge), and every call site of a name must pass the
same label-key set.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ..astutil import dotted_name, iter_calls
from ..engine import Finding, ModuleContext, Rule

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_INSTRUMENTS = {"inc": "counter", "observe": "histogram",
                "set_gauge": "gauge"}


class MetricsHygieneRule(Rule):
    id = "KAI008"
    name = "metrics-hygiene"
    description = ("non-snake_case metric names; one name used as two "
                   "instrument types; inconsistent label keys")

    def __init__(self):
        # name -> instrument -> list[(Finding-shaped site, label keys)]
        self.sites: dict[str, dict[str, list]] = {}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for call in iter_calls(ctx.tree):
            if not isinstance(call.func, ast.Attribute):
                continue
            instrument = _INSTRUMENTS.get(call.func.attr)
            if instrument is None:
                continue
            base = (dotted_name(call.func.value) or "").split(".")[-1]
            if base.lower() != "metrics":
                continue
            if not call.args or not isinstance(call.args[0], ast.Constant) \
                    or not isinstance(call.args[0].value, str):
                continue
            name = call.args[0].value
            site = self.finding(ctx, call, "")
            labels = frozenset(kw.arg for kw in call.keywords
                               if kw.arg is not None and
                               kw.arg != "value")
            self.sites.setdefault(name, {}).setdefault(
                instrument, []).append((site, labels))
            if not _NAME_RE.match(name) or "__" in name or \
                    name.endswith("_"):
                yield self.finding(
                    ctx, call,
                    f"metric name `{name}` is not snake_case "
                    f"(^[a-z][a-z0-9_]*$) — PromQL consumers break on it")

    def finalize(self) -> Iterator[Finding]:
        for name, by_instrument in sorted(self.sites.items()):
            if len(by_instrument) > 1:
                kinds = "/".join(sorted(by_instrument))
                for sites in by_instrument.values():
                    site, _ = sites[0]
                    yield Finding(
                        rule=self.id, path=site.path, line=site.line,
                        col=site.col, source=site.source,
                        message=(f"metric `{name}` registered as "
                                 f"{kinds} — one name, one instrument "
                                 f"type (duplicate registration)"))
            for instrument, sites in by_instrument.items():
                label_sets = {labels for _, labels in sites}
                if len(label_sets) > 1:
                    site, _ = sites[0]
                    rendered = " vs ".join(
                        "{" + ",".join(sorted(s)) + "}"
                        for s in sorted(label_sets, key=sorted))
                    yield Finding(
                        rule=self.id, path=site.path, line=site.line,
                        col=site.col, source=site.source,
                        message=(f"metric `{name}` ({instrument}) used "
                                 f"with inconsistent label keys "
                                 f"{rendered} — Prometheus treats these "
                                 f"as unmergeable series"))
