"""kailint rule pack: the PR1/PR2 safety contracts, machine-enforced.

| id     | name                  | contract                                |
|--------|-----------------------|-----------------------------------------|
| KAI001 | trace-safety          | ops/parallel code stays jit-traceable   |
| KAI002 | host-sync-in-hot-path | device syncs only at the guard          |
| KAI003 | wall-clock-discipline | lease/backoff math on monotonic clocks  |
| KAI004 | unguarded-dispatch    | kernels route through dispatch_kernel   |
| KAI005 | unfenced-write        | scheduler writes carry the epoch        |
| KAI006 | lock-discipline       | `with` locks; no blocking under a lock  |
| KAI007 | exception-swallowing  | controller errors are logged + counted  |
| KAI008 | metrics-hygiene       | one name, one instrument, snake_case    |

Each rule is registered here; ``default_rules()`` returns fresh
instances (rules carry cross-module state between passes, so instances
must never be shared across engine runs).
"""

from __future__ import annotations

from ..engine import Rule
from .clock import WallClockRule
from .dispatch import UnguardedDispatchRule
from .excepts import ExceptionSwallowingRule
from .fencing import UnfencedWriteRule
from .host_sync import HostSyncRule
from .locks import LockDisciplineRule
from .metrics_hygiene import MetricsHygieneRule
from .trace_safety import TraceSafetyRule

RULE_CLASSES: list[type[Rule]] = [
    TraceSafetyRule,        # KAI001
    HostSyncRule,           # KAI002
    WallClockRule,          # KAI003
    UnguardedDispatchRule,  # KAI004
    UnfencedWriteRule,      # KAI005
    LockDisciplineRule,     # KAI006
    ExceptionSwallowingRule,  # KAI007
    MetricsHygieneRule,     # KAI008
]


def default_rules() -> list[Rule]:
    return [cls() for cls in RULE_CLASSES]
