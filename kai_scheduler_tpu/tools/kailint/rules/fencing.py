"""KAI005: unfenced control-plane writes on the scheduler's write path.

PR 2's split-brain defence: every mutating write the *scheduler* makes —
BindRequest create/supersede/GC-delete and pod eviction — must carry the
leadership fencing epoch so the store can reject a deposed leader
(``kubeapi.Fenced``).  One forgotten call site re-opens the hole: a
paused old leader commits a stale placement after a new leader took
over.

Scoped to the scheduler write-path modules (``controllers/
cache_builder.py``, ``framework/statement.py``, ``scheduler.py``).  A
call is flagged when it mutates a BindRequest (literal ``"BindRequest"``
kind argument, or a local dict assigned ``"kind": "BindRequest"``) or
lives inside an ``evict`` method, and carries neither an explicit
``epoch=``/``fence=`` keyword nor a ``**fence_kwargs`` splat.  The
binder and other non-leading controllers write unfenced by design and
are out of scope.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..astutil import dotted_name
from ..engine import Finding, ModuleContext, Rule

SCOPE = ("controllers/cache_builder.py", "framework/statement.py",
         "scheduler.py")

_MUTATORS = {"create", "update", "patch", "delete"}


class UnfencedWriteRule(Rule):
    id = "KAI005"
    name = "unfenced-write"
    description = ("scheduler write-path BindRequest/evict API call "
                   "missing the fencing epoch")

    def applies_to(self, ctx: ModuleContext) -> bool:
        return any(ctx.path.endswith(s) for s in SCOPE)

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(ctx, node)

    def _check_function(self, ctx: ModuleContext,
                        fn: ast.FunctionDef) -> Iterator[Finding]:
        bind_locals = self._bind_request_locals(fn)
        fence_locals = self._fence_locals(fn)
        in_evict = fn.name == "evict"
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute) or \
                    node.func.attr not in _MUTATORS:
                continue
            base = dotted_name(node.func.value) or ""
            if "api" not in base.split(".")[-1]:
                continue  # only API-store mutations
            if not (in_evict or
                    self._touches_bind_request(node, bind_locals)):
                continue
            if self._carries_fence(node, fence_locals):
                continue
            yield self.finding(
                ctx, node,
                f"unfenced `{node.func.attr}` on the scheduler write "
                f"path — pass the fencing epoch "
                f"(**self._fence_kwargs() / epoch=/fence=) so a deposed "
                f"leader cannot commit")

    @staticmethod
    def _bind_request_locals(fn: ast.FunctionDef) -> set[str]:
        """Local names assigned a dict literal with kind BindRequest."""
        out: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Dict):
                if _dict_kind(node.value) == "BindRequest":
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            out.add(tgt.id)
        return out

    @staticmethod
    def _touches_bind_request(call: ast.Call,
                              bind_locals: set[str]) -> bool:
        for arg in call.args:
            if isinstance(arg, ast.Constant) and arg.value == "BindRequest":
                return True
            if isinstance(arg, ast.Name) and arg.id in bind_locals:
                return True
            if isinstance(arg, ast.Dict) and \
                    _dict_kind(arg) == "BindRequest":
                return True
        return False

    @staticmethod
    def _fence_locals(fn: ast.FunctionDef) -> set[str]:
        """Local names assigned from a fence-kwargs source (``fk =
        self._fence_kwargs()`` and the like)."""
        out: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    "fence" in (dotted_name(node.value.func) or "").lower():
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        return out

    @staticmethod
    def _carries_fence(call: ast.Call, fence_locals: set[str]) -> bool:
        for kw in call.keywords:
            if kw.arg in ("epoch", "fence"):
                return True
            if kw.arg is None:
                # A splat only counts when it visibly derives from a
                # fence source — `**self._fence_kwargs()` or a local
                # assigned from one.  `**retry_opts` must NOT pass the
                # gate just because it is a splat.
                v = kw.value
                name = dotted_name(v.func) if isinstance(v, ast.Call) \
                    else dotted_name(v)
                if name and ("fence" in name.lower() or
                             name.split(".")[-1] in fence_locals):
                    return True
        return False


def _dict_kind(node: ast.Dict) -> str | None:
    for k, v in zip(node.keys, node.values):
        if isinstance(k, ast.Constant) and k.value == "kind" and \
                isinstance(v, ast.Constant):
            return v.value
    return None
