"""kailint: AST-based invariant checker for the kai_scheduler_tpu contracts.

The hot loop this repo lifts into JAX/XLA only stays fast and
crash-consistent if a set of conventions hold *everywhere*: ops code must
be trace-safe, fenced control-plane writes must carry the leadership
epoch, lease/backoff logic must run on the monotonic clock, and every
kernel call must route through ``Session.dispatch_kernel``.  PR 1 and
PR 2 established those contracts by hand; kailint makes them *checked*,
not remembered — the tier-1 gate (``tests/test_kailint.py``) runs the
analyzer over the whole package and fails on any non-baselined finding.

Usage::

    python -m kai_scheduler_tpu.tools.kailint kai_scheduler_tpu/
    python -m kai_scheduler_tpu.tools.kailint --list-rules
    python -m kai_scheduler_tpu.tools.kailint --write-baseline pkg/

Suppress a deliberate violation on its own line (a reason after the
rule list is encouraged and conventional)::

    t = time.time()  # kailint: disable=KAI003 — wall-clock intentional

See docs/STATIC_ANALYSIS.md for the rule catalog and workflow.
"""

from .engine import (  # noqa: F401
    Engine,
    Finding,
    ModuleContext,
    Report,
    Rule,
    load_baseline,
    write_baseline,
)
from .rules import default_rules  # noqa: F401
