"""Conformance ring: every proof this repo makes, in ONE command.

ROADMAP item 5 ("make the proofs run where we run"), folded into one
gate: the static analyzers (kailint, kairace, kaijit), the FULL
chaos-matrix mode set — default reconciler rings plus --arena
--incremental --fused --shards --pipeline --latency --columnar --wire
--timeaware, the PR 15 --wire-faults lying-wire ring, and the
--compile compile-contract ring (KAI_JITTRACE journals vs the static
kaijit surface) — and the fleet budget (tools/fleet_budget.py, which
also enforces the committed per-kernel compile-signature ceilings),
swept per fault seed and reported as one pass/fail table.  A future PR
that breaks any invariant the previous sessions proved fails HERE, in
one command, with the failing mode and a replay seed named.

Tiers:

  python -m kai_scheduler_tpu.tools.conformance            # full sweep
  python -m kai_scheduler_tpu.tools.conformance --smoke    # the CI gate

``--smoke`` (run by tools/ci_check.sh) keeps the wall time CI-sized:
all three analyzers for real, a --dry-run validation of EVERY
chaos-matrix mode definition, and one real single-seed sweep of the
wire-faults ring
(the newest, least-soaked invariant).  The fleet budget is part of the
full tier (and of ci_check.sh directly); ``--with-budget`` pulls it
into smoke too.

``--dry-run`` prints the step plan without executing anything — the
self-validation the chaos matrix pioneered, one level up.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

# Every chaos-matrix mode flag; "" is the default reconciler/device ring.
MATRIX_MODES = ["", "--arena", "--incremental", "--fused", "--shards",
                "--pipeline", "--latency", "--columnar", "--wire",
                "--timeaware", "--wire-faults", "--compile"]

# The smoke tier's one REAL sweep: the wire-faults ring, one seed, the
# fast subset (the same -k the tier-1 smoke uses).
SMOKE_REAL_SWEEP = ["--wire-faults", "--seeds", "1",
                    "-k", "converge or replays or lagging",
                    "--timeout", "300"]


def _mode_label(mode: str) -> str:
    return mode.lstrip("-") or "default"


def build_plan(smoke: bool, seeds: str, with_budget: bool,
               races: bool) -> list:
    """The ordered (name, argv) step list; argv is run as
    ``sys.executable -m <module> ...``."""
    plan: list = [
        ("kailint", ["kai_scheduler_tpu.tools.kailint",
                     "kai_scheduler_tpu/"]),
        ("kairace", ["kai_scheduler_tpu.tools.kairace",
                     "kai_scheduler_tpu/"]),
        ("kaijit", ["kai_scheduler_tpu.tools.kaijit",
                    "kai_scheduler_tpu/"]),
    ]
    matrix = "kai_scheduler_tpu.tools.chaos_matrix"
    if smoke:
        for mode in MATRIX_MODES:
            argv = [matrix, "--dry-run"] + ([mode] if mode else [])
            plan.append((f"matrix-def:{_mode_label(mode)}", argv))
        if races:
            plan.append(("matrix-def:races", [matrix, "--races",
                                              "--dry-run"]))
        plan.append(("matrix:wire-faults(1 seed)",
                     [matrix] + SMOKE_REAL_SWEEP))
    else:
        for mode in MATRIX_MODES:
            argv = [matrix, "--seeds", seeds, "--timeout", "600"] \
                + ([mode] if mode else [])
            if races:
                argv.append("--races")
            plan.append((f"matrix:{_mode_label(mode)}", argv))
    if with_budget or not smoke:
        plan.append(("fleet-budget",
                     ["kai_scheduler_tpu.tools.fleet_budget"]))
    return plan


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("kai-conformance")
    ap.add_argument("--smoke", action="store_true",
                    help="the CI tier: analyzers + every matrix mode "
                         "definition (dry run) + one real 1-seed "
                         "wire-faults sweep")
    ap.add_argument("--seeds", default="1,2,3",
                    help="fault-seed sweep for the full tier "
                         "(default: 1,2,3)")
    ap.add_argument("--with-budget", action="store_true",
                    help="run tools/fleet_budget.py in the smoke tier "
                         "too (always part of the full tier)")
    ap.add_argument("--races", action="store_true",
                    help="arm KAI_LOCKTRACE lock-order validation on "
                         "every matrix sweep (full tier) / validate "
                         "the races mode definition (smoke)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the step plan without executing")
    args = ap.parse_args(argv)

    plan = build_plan(args.smoke, args.seeds, args.with_budget,
                      args.races)
    tier = "smoke" if args.smoke else "full"
    if args.dry_run:
        for name, step_argv in plan:
            print(f"step {name:<28} python -m {' '.join(step_argv)}",
                  flush=True)
        print(f"\nconformance (dry run): {len(plan)} step(s) planned "
              f"[{tier} tier], nothing executed", flush=True)
        return 0

    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # Steps control their own fault/locktrace arming; an inherited spec
    # would skew every sweep the same way.
    for var in ("KAI_FAULT_INJECT", "KAI_LOCKTRACE", "KAI_JITTRACE"):
        env.pop(var, None)
    rows, failed = [], []
    for name, step_argv in plan:
        print(f"\n== conformance [{tier}]: {name} ==", flush=True)
        t0 = time.monotonic()
        proc = subprocess.run([sys.executable, "-m", *step_argv],
                              cwd=repo_root, env=env)
        secs = time.monotonic() - t0
        ok = proc.returncode == 0
        rows.append((name, ok, secs))
        if not ok:
            failed.append(name)

    print("\nconformance summary:", flush=True)
    for name, ok, secs in rows:
        print(f"  {name:<28} {'ok' if ok else 'FAIL':<5} {secs:7.1f}s",
              flush=True)
    print(f"conformance [{tier}]: "
          f"{len(rows) - len(failed)}/{len(rows)} green", flush=True)
    if failed:
        print(f"conformance: FAILED steps: {', '.join(failed)}",
              flush=True)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
