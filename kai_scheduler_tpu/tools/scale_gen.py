"""Scale harness: synthetic clusters + the scale-test scenarios.

The KWOK-ring analog (docs/scale-tests/README.md, test/e2e/scale/
kwok_test.go:128-520): generate virtual clusters of N nodes and pending-job
waves, run the scenarios the reference measures (cluster fill, whole-GPU
allocation, distributed gangs, reclaim latency, burst), and log durations.

Usage:
  python -m kai_scheduler_tpu.tools.scale_gen --nodes 500 --scenario fill
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from ..framework import SchedulerConfig
from ..scheduler import Scheduler
from ..utils.cluster_spec import build_cluster


def gen_spec(n_nodes: int, n_queues: int = 4, seed: int = 0,
             gpu_per_node: int = 8) -> dict:
    rng = np.random.default_rng(seed)
    nodes = {f"node-{i:05d}": {
        "gpu": gpu_per_node, "cpu": "64", "mem": "512Gi",
        "labels": {"zone": f"z{i % 8}", "rack": f"r{i % 64}"}}
        for i in range(n_nodes)}
    total_gpu = n_nodes * gpu_per_node
    queues = {f"q{i}": {"deserved": dict(
        cpu=str(64 * n_nodes // n_queues),
        memory=f"{512 * n_nodes // n_queues}Gi",
        gpu=total_gpu // n_queues)} for i in range(n_queues)}
    return {"nodes": nodes, "queues": queues, "jobs": {},
            "topologies": {"dc": {"levels": ["zone", "rack"]}}}


def add_job_wave(spec: dict, count: int, gpus: int = 1, gang: int = 1,
                 prefix: str = "job", seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    queues = list(spec["queues"])
    for i in range(count):
        spec["jobs"][f"{prefix}-{i:06d}"] = {
            "queue": queues[int(rng.integers(len(queues)))],
            "min_available": gang,
            "tasks": [{"gpu": gpus, "cpu": "1", "mem": "1Gi"}] * gang,
        }


def run_scenario(scenario: str, n_nodes: int, seed: int = 0) -> dict:
    spec = gen_spec(n_nodes, seed=seed)
    gpu_capacity = n_nodes * 8

    if scenario == "fill":
        add_job_wave(spec, gpu_capacity, gpus=1, prefix="fill", seed=seed)
    elif scenario == "whole-gpu":
        add_job_wave(spec, n_nodes, gpus=8, prefix="whole", seed=seed)
    elif scenario == "distributed":
        add_job_wave(spec, n_nodes // 4, gpus=8, gang=4, prefix="dist",
                     seed=seed)
    elif scenario == "burst":
        add_job_wave(spec, gpu_capacity * 2, gpus=1, prefix="burst",
                     seed=seed)
    elif scenario in ("topology-required", "topology-preferred"):
        # The reference's TAS scale scenarios (kwok_test.go:128-520):
        # rack-sized gangs with a required or preferred rack-level
        # constraint over the dc topology (levels zone > rack).  Demand is
        # ~half the cluster so every gang CAN land in some rack; required
        # must pin each gang to one rack, preferred must still bind all.
        gang = 16
        count = max(1, gpu_capacity // (2 * gang))
        add_job_wave(spec, count, gpus=1, gang=gang, prefix="topo",
                     seed=seed)
        level_key = ("required_topology_level"
                     if scenario == "topology-required"
                     else "preferred_topology_level")
        for j in spec["jobs"].values():
            j["topology"] = "dc"
            j[level_key] = "rack"
    elif scenario == "rank-mpi":
        # Rank-aware MPI gangs (arxiv 2603.22691 / ROADMAP item 4).
        # Topology interleaves node-name order at MIXED distances
        # (block alternates per index, racks stride) so the fill plan's
        # index-ordered node choice hands each gang a set of slots whose
        # ORDER matters: rank placement must measurably tighten mean
        # consecutive-rank hop distance vs the rank-oblivious baseline
        # on the same seed.  Demand is half the cluster so every gang
        # binds in both variants.
        for i, n in enumerate(spec["nodes"].values()):
            n["labels"] = {"block": f"b{i % 2}", "rack": f"r{i % 8}"}
        spec["topologies"] = {"dc": {"levels": ["block", "rack"]}}
        gang = 16
        count = max(1, gpu_capacity // (2 * 2 * gang))
        rng = np.random.default_rng(seed)
        queues = list(spec["queues"])
        for i in range(count):
            spec["jobs"][f"mpi-{i:05d}"] = {
                "queue": queues[int(rng.integers(len(queues)))],
                "min_available": gang,
                "tasks": [{"gpu": 2, "cpu": "1", "mem": "1Gi",
                           "rank": r} for r in range(gang)],
            }
    elif scenario == "reclaim":
        # Fill from one queue, then measure a starved queue reclaiming.
        add_job_wave(spec, gpu_capacity, gpus=1, prefix="hog", seed=seed)
        for j in spec["jobs"].values():
            j["queue"] = "q0"
    elif scenario == "reclaim-contention":
        # Deep-victim-queue contention (BASELINE config #3 / VERDICT r2
        # task #6): ~1k queues, half hogging the whole cluster, half
        # starved with pending work — every reclaimer faces a long
        # ordered victim queue, the worst case for sequential scenario
        # simulation.  Measured twice: prescreen batched vs disabled.
        n_queues = min(1024, max(8, gpu_capacity // 4))
        spec = gen_spec(n_nodes, n_queues=n_queues, seed=seed)
        add_job_wave(spec, gpu_capacity, gpus=1, prefix="hog", seed=seed)
        for i, j in enumerate(spec["jobs"].values()):
            j["queue"] = f"q{i % (n_queues // 2)}"   # hog half the queues
    else:
        raise SystemExit(f"unknown scenario {scenario!r}")

    cluster = build_cluster(spec)
    sched = Scheduler(lambda: cluster, SchedulerConfig())
    t0 = time.perf_counter()
    ssn = sched.run_once()
    first_cycle = time.perf_counter() - t0

    result = {"scenario": scenario, "nodes": n_nodes,
              "jobs": len(spec["jobs"]),
              "first_cycle_s": round(first_cycle, 3),
              "pods_bound": len(ssn.cache.bound)}

    if scenario == "burst":
        # Burst is 2x over-subscribed BY DESIGN: 2*capacity one-GPU jobs
        # against n_nodes*8 GPU slots (CPU would allow n_nodes*64, so
        # GPU is the binding axis).  Exactly capacity binds; the other
        # half is the pending backlog whose re-attempt cost
        # steady_cycle_s measures.  Recording the math here keeps a
        # "3200/6400 bound" row from reading as a placement bug
        # (VERDICT Weak #4).
        result["expected_bound"] = gpu_capacity
        result["capacity_note"] = (
            f"capacity-bound: {n_nodes} nodes x 8 GPUs = {gpu_capacity} "
            f"slots vs {len(spec['jobs'])} one-GPU jobs (2x demand)")

    if scenario.startswith("topology-"):
        # Constraint audit: how many gangs landed entirely inside one
        # rack (for required this must be ALL placed gangs).
        node_rack = {name: n["labels"]["rack"]
                     for name, n in spec["nodes"].items()}
        single_rack = placed = 0
        for pg in cluster.podgroups.values():
            nodes_used = {t.node_name for t in pg.pods.values()
                          if t.node_name}
            if not nodes_used:
                continue
            placed += 1
            if len({node_rack[n] for n in nodes_used}) == 1:
                single_rack += 1
        result["gangs_placed"] = placed
        result["gangs_single_rack"] = single_rack

    if scenario == "rank-mpi":
        # Measured rank adjacency, A/B on the same seed: the default run
        # above is rank-aware; re-run the identical spec rank-oblivious
        # and compare mean consecutive-rank hop distance.
        aware_hop, aware_gangs = _gang_mean_hop(cluster, spec)
        base_cluster = build_cluster(spec)
        base_ssn = Scheduler(
            lambda: base_cluster,
            SchedulerConfig(rank_aware_placement=False)).run_once()
        base_hop, base_gangs = _gang_mean_hop(base_cluster, spec)
        result.update({
            "gangs_placed": aware_gangs,
            "mean_hop_rank_aware": round(aware_hop, 4),
            "mean_hop_oblivious": round(base_hop, 4),
            "pods_bound_oblivious": len(base_ssn.cache.bound),
        })

    if scenario == "reclaim":
        # The fill wave (all in q0) is now allocated; inject a starved
        # queue's jobs into the live cluster and measure the reclaim cycle.
        from ..api.podgroup_info import PodGroupInfo
        from ..api.pod_info import PodInfo
        from ..api.resources import ResourceRequirements
        for i in range(8):
            pg = PodGroupInfo(f"starved-{i}", f"starved-{i}",
                              queue_id="q1")
            pg.add_task(PodInfo(
                uid=f"starved-{i}-0", name=f"starved-{i}-0",
                res_req=ResourceRequirements.from_spec("1", "1Gi", 4)))
            cluster.podgroups[pg.uid] = pg
        t1 = time.perf_counter()
        ssn2 = sched.run_once()
        result["reclaim_cycle_s"] = round(time.perf_counter() - t1, 3)
        result["evictions"] = len(ssn2.cache.evicted)
    elif scenario == "reclaim-contention":
        # Inject pending 2-GPU jobs from the starved queue half, then
        # measure the reclaim cycle twice on clones of the same packed
        # cluster: batched prefix prescreen vs fully sequential
        # simulation (scenario_prescreen_max=0).
        from ..api.podgroup_info import PodGroupInfo
        from ..api.pod_info import PodInfo
        from ..api.resources import ResourceRequirements
        # Deep-prefix reclaimers: each starved queue (deserved raised to
        # 32) asks for a 32-GPU wave against 1-GPU victims, so the
        # sequential solver simulates (and fails) ~31 growing prefixes
        # per job — the shape the batched prescreen collapses into one
        # device call.  Two timed runs per variant, min taken, to cancel
        # jit-compile warmup (first run pays compiles).
        n_queues = len(spec["queues"])
        deep = 32
        for i in range(8):
            qid = f"q{n_queues // 2 + i}"
            spec["queues"][qid]["deserved"]["gpu"] = deep
            cluster.queues[qid].quota.deserved[-1] = float(deep)
            pg = PodGroupInfo(f"starved-{i}", f"starved-{i}", queue_id=qid,
                              min_available=deep)
            for k in range(deep):
                pg.add_task(PodInfo(
                    uid=f"starved-{i}-{k}", name=f"starved-{i}-{k}",
                    res_req=ResourceRequirements.from_spec("1", "1Gi", 1)))
            cluster.podgroups[pg.uid] = pg
        timings = {}
        variants = (
            # (label, prescreen_after, batched_confirm)
            ("batched", 2, True),        # prescreen + one-call confirm
            ("prescreen-only", 2, False),
            ("sequential", 10 ** 9, False),  # round-1 baseline
        )
        from ..utils.metrics import METRICS
        for label, prescreen_after, batched in variants:
            elapsed = None
            # Run 1 is an untimed warmup (jit compiles for this state's
            # shapes); run 2 is the measurement.
            for timed in (False, True):
                trial = cluster.clone()
                sched_t = Scheduler(
                    lambda c=trial: c,
                    SchedulerConfig(
                        scenario_prescreen_after=prescreen_after,
                        batched_scenario_confirm=batched,
                        max_scenarios_per_job=64,
                        max_victims_considered=64))
                calls0 = METRICS.counters.get("device_kernel_calls", 0)
                t1 = time.perf_counter()
                ssn_t = sched_t.run_once()
                if timed:
                    elapsed = time.perf_counter() - t1
                    result[f"evictions_{label}"] = len(ssn_t.cache.evicted)
                    # Device round trips: the hardware-independent cost —
                    # on the tunneled TPU each is a ~70ms RTT, so call
                    # count is what the batching actually buys.
                    result[f"device_calls_{label}"] = int(
                        METRICS.counters.get("device_kernel_calls", 0)
                        - calls0)
            timings[label] = elapsed
        result["reclaim_cycle_s"] = round(timings["batched"], 3)
        result["reclaim_prescreen_only_s"] = round(
            timings["prescreen-only"], 3)
        result["reclaim_sequential_s"] = round(timings["sequential"], 3)
        result["prescreen_speedup"] = round(
            timings["sequential"] / max(timings["batched"], 1e-9), 2)
        result["queues"] = n_queues
    else:
        # Two cycles, report the best: the first steady cycle can still
        # pay a one-off kernel compile for the post-placement backlog
        # shape; steady state is by definition past warmup.
        steady = []
        for _ in range(2):
            t1 = time.perf_counter()
            sched.run_once()
            steady.append(time.perf_counter() - t1)
        result["steady_cycle_s"] = round(min(steady), 3)
    return result


def _gang_mean_hop(cluster, spec: dict) -> tuple[float, int]:
    """(mean over gangs of mean consecutive-rank hop distance, number
    of placed ranked gangs) — the scale ring's adjacency metric."""
    from ..ops import rankplace as rp
    from ..ops.topology import build_tree
    node_names = list(cluster.node_order)
    labels = {n: spec["nodes"][n].get("labels", {}) for n in node_names}
    levels = list(next(iter(spec["topologies"].values()))["levels"])
    tree = build_tree("dc", levels, node_names, labels)
    order = rp.build_topo_order(tree, len(node_names))
    idx = {n: i for i, n in enumerate(node_names)}
    hops, gangs = [], 0
    for pg in cluster.podgroups.values():
        tasks = [t for t in pg.pods.values()
                 if t.node_name and t.rank >= 0]
        if len(tasks) < 2:
            continue
        gangs += 1
        tasks.sort(key=lambda t: t.rank)
        arr = np.array([idx[t.node_name] for t in tasks], np.int32)
        hops.append(rp.mean_hop(arr, order))
    return (float(np.mean(hops)) if hops else 0.0), gangs


def run_system_scenario(n_nodes: int, n_pods: int) -> dict:
    """Full-fleet variant: pods flow through admission, grouping,
    scheduling, and binding over the in-memory API (the KWOK ring's
    real-control-plane analog)."""
    from ..controllers import System, SystemConfig, make_pod

    system = System(SystemConfig())
    api = system.api
    t0 = time.perf_counter()
    for i in range(n_nodes):
        api.create({"kind": "Node",
                    "metadata": {"name": f"node-{i:05d}"},
                    "spec": {},
                    "status": {"allocatable": {
                        "cpu": "64", "memory": "512Gi",
                        "nvidia.com/gpu": 8, "pods": 110}}})
    api.create({"kind": "Queue", "metadata": {"name": "q"}, "spec": {}})
    for i in range(n_pods):
        api.create(make_pod(f"pod-{i:06d}", queue="q", gpu=2))
    setup_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    system.run_cycle()
    cycle_s = time.perf_counter() - t0
    bound = len([p for p in api.list("Pod")
                 if p["spec"].get("nodeName")])
    return {"scenario": "system-fill", "nodes": n_nodes, "pods": n_pods,
            "setup_s": round(setup_s, 2), "cycle_s": round(cycle_s, 2),
            "pods_bound": bound}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=500)
    ap.add_argument("--scenario", default="fill",
                    choices=("fill", "whole-gpu", "distributed", "burst",
                             "reclaim", "reclaim-contention",
                             "topology-required", "topology-preferred",
                             "rank-mpi", "system-fill"))
    ap.add_argument("--pods", type=int, default=0,
                    help="pod count for system-fill (default 2x nodes)")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.scenario == "system-fill":
        print(json.dumps(run_system_scenario(
            args.nodes, args.pods or args.nodes * 2)))
        return
    print(json.dumps(run_scenario(args.scenario, args.nodes, args.seed)))


if __name__ == "__main__":
    main()
