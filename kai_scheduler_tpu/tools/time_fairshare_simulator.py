"""Time-based fair-share simulator: multi-cycle allocation with usage decay.

Mirrors cmd/time-based-fairshare-simulator (main.go + README): simulate a
cluster over many cycles, recording per-queue allocations into the usage
DB so the k-value penalty shifts shares over time; emit per-cycle CSV of
each queue's fair share and allocation.

Two harnesses:

- ``run`` — the original offline loop: one Scheduler over a static
  ClusterInfo, per-cycle CSV of shares/allocations;
- ``run_system_trace`` — the e2e ring (the reference's ``timeaware``
  e2e family): a FULL ``System`` (apiserver, admission, podgrouper,
  binder, usage tensor) driven over a simulated multi-hour trace with
  an injected clock.  Phase 1 lets the ``hog`` queue monopolize the
  cluster for at least one half-life; phase 2 has ``hog`` and
  ``victim`` contend for every freed slot, counting BOUND PODS per
  queue — the assertion is on real placements, not on share numbers.
  Optionally restarts the scheduler mid-trace against the usage
  checkpoint log (the commit-log pattern, DESIGN §13) to prove the
  penalty survives the process.

Usage:
  python -m kai_scheduler_tpu.tools.time_fairshare_simulator \
      --cycles 50 --out shares.csv
  python -m kai_scheduler_tpu.tools.time_fairshare_simulator --e2e
"""

from __future__ import annotations

import argparse
import csv
import sys

from ..api import resources as rs
from ..framework import SchedulerConfig
from ..scheduler import Scheduler
from ..utils.usagedb import InMemoryUsageDB, UsageParams


def default_scenario() -> dict:
    """Two equal queues, demand forever: with usage decay the shares should
    oscillate toward long-run equality even when one queue started first."""
    nodes = {f"n{i}": {"gpu": 8, "cpu": "32", "mem": "256Gi"}
             for i in range(4)}
    return {
        "nodes": nodes,
        "queues": {
            "q_a": {"deserved": dict(cpu="64", memory="512Gi", gpu=16)},
            "q_b": {"deserved": dict(cpu="64", memory="512Gi", gpu=16)},
        },
        "jobs": {
            f"a{i}": {"queue": "q_a", "tasks": [{"gpu": 4}]}
            for i in range(8)
        } | {
            f"b{i}": {"queue": "q_b", "tasks": [{"gpu": 4}]}
            for i in range(8)
        },
    }


def run(cycles: int, period: float = 60.0, k_value: float = 1.0,
        half_life: float = 600.0, scenario: dict | None = None,
        writer=None) -> list:
    from ..utils import cluster_spec as fx

    spec = scenario or default_scenario()
    cluster = fx.build_cluster(spec)
    capacity = cluster.total_allocatable()
    usagedb = InMemoryUsageDB(
        UsageParams(half_life_period_seconds=half_life,
                    window_size_seconds=period * cycles),
        cluster_capacity=capacity)
    clock = {"now": 0.0}
    cluster.now = 0.0

    config = SchedulerConfig(k_value=k_value)
    sched = Scheduler(lambda: cluster, config,
                      usage_provider=lambda: usagedb.queue_usage(
                          clock["now"]))
    rows = []
    for cycle in range(cycles):
        ssn = sched.run_once()
        for qid, attrs in ssn.proportion.queues.items():
            usagedb.record(clock["now"], qid, attrs.allocated,
                           duration=period)
            row = {"cycle": cycle, "time": clock["now"], "queue": qid,
                   "fair_share_gpu": attrs.fair_share[rs.RES_GPU],
                   "allocated_gpu": attrs.allocated[rs.RES_GPU],
                   "usage_gpu": attrs.usage[rs.RES_GPU]}
            rows.append(row)
            if writer:
                writer.writerow(row)
        clock["now"] += period
        cluster.now = clock["now"]
    return rows


def run_system_trace(phase1_cycles: int = 15, phase2_cycles: int = 20,
                     period: float = 60.0, half_life: float = 600.0,
                     nodes: int = 2, gpus_per_node: int = 8,
                     job_gpus: int = 2, job_lifetime_cycles: int = 2,
                     usage_log_path: str | None = None,
                     restart_at: int | None = None,
                     usage_db: str | None = "memory://") -> dict:
    """The e2e ``timeaware`` ring: a full System over a simulated trace.

    Phase 1 (``phase1_cycles`` x ``period`` seconds — size it to cover
    at least one half-life): only ``hog`` submits, saturating the
    cluster; every job completes (its pod is deleted) after
    ``job_lifetime_cycles``, so hog keeps re-binding and accrues usage.
    Phase 2: both queues submit one wave per cycle, demand exceeding
    the freed capacity; the usage penalty must make the over-user YIELD
    — counted on bound pods per queue.  ``restart_at`` (a phase-2 cycle
    index) tears the System down and rebuilds it against
    ``usage_log_path``, proving the usage tensor survives a restart.
    ``usage_db=None`` runs the same trace usage-blind (the A/B
    baseline: both queues then bind roughly equally)."""
    from ..controllers import System, SystemConfig, make_pod
    from ..utils.usagedb import UsageParams

    clock = {"now": 0.0}
    params = UsageParams(half_life_period_seconds=half_life,
                         window_size_seconds=period
                         * (phase1_cycles + phase2_cycles) * 4,
                         staleness_period_seconds=period * 1000)

    def build_system():
        system = System(SystemConfig(
            usage_db=usage_db, usage_params=params,
            usage_log_path=usage_log_path,
            now_fn=lambda: clock["now"]))
        for i in range(nodes):
            system.api.create({
                "kind": "Node", "metadata": {"name": f"n{i}"},
                "spec": {},
                "status": {"allocatable": {
                    "cpu": "64", "memory": "512Gi",
                    "nvidia.com/gpu": gpus_per_node, "pods": 110}}})
        for q in ("hog", "victim"):
            system.api.create({"kind": "Queue", "metadata": {"name": q},
                               "spec": {"deserved": {"gpu": 2}}})
        return system

    system = build_system()
    capacity_jobs = nodes * gpus_per_node // job_gpus
    seq = {"n": 0}
    live: list[tuple[int, str, str]] = []   # (bound_cycle, name, queue)
    bound_seen: set[str] = set()
    counts = {"hog": 0, "victim": 0}
    rows = []

    def submit(queue: str, n: int) -> None:
        for _ in range(n):
            seq["n"] += 1
            system.api.create(make_pod(f"job-{seq['n']:06d}",
                                       queue=queue, gpu=job_gpus))

    def reap_and_count(cycle: int, phase: str) -> None:
        for pod in system.api.list("Pod"):
            name = pod["metadata"]["name"]
            node = pod["spec"].get("nodeName")
            if node and name not in bound_seen:
                bound_seen.add(name)
                queue = pod["metadata"]["labels"].get(
                    "kai.scheduler/queue", "")
                live.append((cycle, name, queue))
                if phase == "contend":
                    counts[queue] += 1
        done = [(c, n, q) for (c, n, q) in live
                if cycle - c >= job_lifetime_cycles]
        for c, name, q in done:
            live.remove((c, name, q))
            try:
                system.api.delete("Pod", name)
            except Exception:
                pass

    cycle = 0
    for _ in range(phase1_cycles):
        submit("hog", max(0, capacity_jobs + 2 - sum(
            1 for p in system.api.list("Pod")
            if not p["spec"].get("nodeName"))))
        system.run_cycle()
        reap_and_count(cycle, "hog")
        clock["now"] += period
        cycle += 1

    usage_mid = dict(system.usage_db.queue_usage(clock["now"])) \
        if system.usage_db else {}
    restarted = False
    wave = max(2, capacity_jobs // 2)
    for i in range(phase2_cycles):
        if restart_at is not None and i == restart_at:
            # Scheduler restart mid-trace: the usage checkpoint log is
            # the ONLY state carried over.
            system.stop_pipeline()
            system = build_system()
            live.clear()
            restarted = True
        submit("hog", wave)
        submit("victim", wave)
        system.run_cycle()
        reap_and_count(cycle, "contend")
        rows.append({"cycle": cycle, "hog_bound": counts["hog"],
                     "victim_bound": counts["victim"]})
        clock["now"] += period
        cycle += 1

    usage_end = dict(system.usage_db.queue_usage(clock["now"])) \
        if system.usage_db else {}
    return {
        "hog_bound": counts["hog"], "victim_bound": counts["victim"],
        "usage_mid": {q: v.tolist() for q, v in usage_mid.items()},
        "usage_end": {q: v.tolist() for q, v in usage_end.items()},
        "restarted": restarted,
        "capacity_jobs": capacity_jobs,
        "rows": rows,
        "system": system,
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--cycles", type=int, default=20)
    ap.add_argument("--period", type=float, default=60.0)
    ap.add_argument("--k-value", type=float, default=1.0)
    ap.add_argument("--half-life", type=float, default=600.0)
    ap.add_argument("--out", default="-")
    ap.add_argument("--e2e", action="store_true",
                    help="run the full-System timeaware trace ring "
                         "instead of the offline share loop")
    args = ap.parse_args(argv)

    if args.e2e:
        import json
        res = run_system_trace(period=args.period,
                               half_life=args.half_life)
        res.pop("system", None)
        print(json.dumps(res, indent=2))
        return

    out = sys.stdout if args.out == "-" else open(args.out, "w", newline="")
    writer = csv.DictWriter(out, fieldnames=[
        "cycle", "time", "queue", "fair_share_gpu", "allocated_gpu",
        "usage_gpu"])
    writer.writeheader()
    run(args.cycles, args.period, args.k_value, args.half_life,
        writer=writer)
    if out is not sys.stdout:
        out.close()


if __name__ == "__main__":
    main()
