"""Time-based fair-share simulator: multi-cycle allocation with usage decay.

Mirrors cmd/time-based-fairshare-simulator (main.go + README): simulate a
cluster over many cycles, recording per-queue allocations into the usage
DB so the k-value penalty shifts shares over time; emit per-cycle CSV of
each queue's fair share and allocation.

Usage:
  python -m kai_scheduler_tpu.tools.time_fairshare_simulator \
      --cycles 50 --out shares.csv
"""

from __future__ import annotations

import argparse
import csv
import sys

from ..api import resources as rs
from ..framework import SchedulerConfig
from ..scheduler import Scheduler
from ..utils.usagedb import InMemoryUsageDB, UsageParams


def default_scenario() -> dict:
    """Two equal queues, demand forever: with usage decay the shares should
    oscillate toward long-run equality even when one queue started first."""
    nodes = {f"n{i}": {"gpu": 8, "cpu": "32", "mem": "256Gi"}
             for i in range(4)}
    return {
        "nodes": nodes,
        "queues": {
            "q_a": {"deserved": dict(cpu="64", memory="512Gi", gpu=16)},
            "q_b": {"deserved": dict(cpu="64", memory="512Gi", gpu=16)},
        },
        "jobs": {
            f"a{i}": {"queue": "q_a", "tasks": [{"gpu": 4}]}
            for i in range(8)
        } | {
            f"b{i}": {"queue": "q_b", "tasks": [{"gpu": 4}]}
            for i in range(8)
        },
    }


def run(cycles: int, period: float = 60.0, k_value: float = 1.0,
        half_life: float = 600.0, scenario: dict | None = None,
        writer=None) -> list:
    from ..utils import cluster_spec as fx

    spec = scenario or default_scenario()
    cluster = fx.build_cluster(spec)
    capacity = cluster.total_allocatable()
    usagedb = InMemoryUsageDB(
        UsageParams(half_life_period_seconds=half_life,
                    window_size_seconds=period * cycles),
        cluster_capacity=capacity)
    clock = {"now": 0.0}
    cluster.now = 0.0

    config = SchedulerConfig(k_value=k_value)
    sched = Scheduler(lambda: cluster, config,
                      usage_provider=lambda: usagedb.queue_usage(
                          clock["now"]))
    rows = []
    for cycle in range(cycles):
        ssn = sched.run_once()
        for qid, attrs in ssn.proportion.queues.items():
            usagedb.record(clock["now"], qid, attrs.allocated,
                           duration=period)
            row = {"cycle": cycle, "time": clock["now"], "queue": qid,
                   "fair_share_gpu": attrs.fair_share[rs.RES_GPU],
                   "allocated_gpu": attrs.allocated[rs.RES_GPU],
                   "usage_gpu": attrs.usage[rs.RES_GPU]}
            rows.append(row)
            if writer:
                writer.writerow(row)
        clock["now"] += period
        cluster.now = clock["now"]
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--cycles", type=int, default=20)
    ap.add_argument("--period", type=float, default=60.0)
    ap.add_argument("--k-value", type=float, default=1.0)
    ap.add_argument("--half-life", type=float, default=600.0)
    ap.add_argument("--out", default="-")
    args = ap.parse_args(argv)

    out = sys.stdout if args.out == "-" else open(args.out, "w", newline="")
    writer = csv.DictWriter(out, fieldnames=[
        "cycle", "time", "queue", "fair_share_gpu", "allocated_gpu",
        "usage_gpu"])
    writer.writeheader()
    run(args.cycles, args.period, args.k_value, args.half_life,
        writer=writer)
    if out is not sys.stdout:
        out.close()


if __name__ == "__main__":
    main()
