"""Grouped gang allocation: scan over task GROUPS, not tasks.

The exact kernel (ops/allocate.py) pays a fixed while-loop step cost per
task (~50us/step on TPU, dominating cycle latency: 2048 tasks ~ 100ms).
Real gangs are overwhelmingly runs of IDENTICAL tasks (same request,
selector, tolerations) — the same observation behind the reference's
scheduling-signature representors (job_info.go:547,
minimal_job_comparison.go).  This kernel scores once per identical-task
run, computes an analytic *fill plan*, bulk-updates node state, and emits
the plan as at most ``max_group`` compact (node, count, pipelined)
segments — so the scan length is the number of GROUPS, cutting step count
by the mean gang size.

Equivalence to the sequential greedy (tested against the exact kernel):
- under bin-pack, greedy fills the best-scoring node to capacity before
  moving on, and filling one node never reorders the rest (their free
  amounts are untouched; relative bin-pack order between two untouched
  nodes depends only on their free amounts, whatever the min/max span
  does), so the greedy sequence equals "sort by initial score, fill in
  order";
- each node contributes TWO fill items — an idle-capacity item keyed by
  its full score (availability included) and a releasing-capacity item
  keyed by score minus the availability boost — and ONE fill runs over
  the interleaved 2N items.  This reproduces the exact kernel's
  interleaving of tiers: a topology/nominated-boosted pipeline candidate
  (extra >= 10000 > availability 100) correctly beats an unboosted
  fit-now node, while within one extra level every fit-now item still
  beats every pipeline item.  A node's releasing item can only be taken
  after its idle item (strictly smaller key, same node), so the static
  capacity split (floor over idle vs floor over idle+releasing minus the
  former) is exact;
- per-node capacity = floor(min_r free_r / req_r) bounded by pod room;
- gang failure (demand exceeds total capacity) rolls the job back at the
  next job boundary, exactly like the per-task kernel.

Spread strategy round-robins as nodes fill and must use the exact kernel.

The per-step row + fill implementation is a static three-rung ladder
(docs/DESIGN.md §3.2b): TPU-Pallas node-tile row kernel -> fused-jnp
single-pass row with the masked-sum radix-descent fill -> the legacy
feasibility_row/score_row/histogram composition.  All rungs are
bit-identical in placements (tests/test_fused_parity.py,
tools/kernel_parity.py); the wrapper resolves the rung per backend/shape
(env pin: KAI_FUSED_ALLOC) and counts it in
``allocate_fused_taken_total``.
"""

from __future__ import annotations

import contextlib
import functools
import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .allocate import NEG, AllocationResult
from .predicates import feasibility_caps_row, feasibility_row
from .scoring import AVAILABILITY, BINPACK, score_row, score_row_selected

# Fused-path selection (docs/DESIGN.md fused-kernel section).  The ladder
# is TPU-Pallas -> fused-jnp -> legacy: ``auto`` resolves per backend and
# shape; KAI_FUSED_ALLOC pins a rung (parity suites pin ``legacy`` to diff
# the ladder against the original formulation).
FUSED_MODES = ("auto", "pallas", "jnp", "legacy")
_FUSED_ENV = "KAI_FUSED_ALLOC"

# Digit width (bits) of the fused fill's radix descent.  Each level costs
# one in-prefix mask pass plus (2^W - 1) masked-sum reductions that XLA
# multi-output-fuses over one read of the keys; W=2 balances level count
# (16 for u32) against per-level reduction fan-out on both CPU and TPU.
SELECT_DIGIT_BITS = 2

# Stats of the most recent wrapper dispatch (mode/groups/nodes/
# releasing_empty): the traced call sites read these to stamp the
# ``allocate_fused`` span on the cycle thread (the wrapper itself may run
# on the device guard's worker thread, where cycle spans no-op).
LAST_DISPATCH: dict = {}


@contextlib.contextmanager
def fused_dispatch_span(**attrs):
    """Cycle-thread ``allocate_fused`` span around a guarded grouped
    dispatch: yields, then stamps the guard verdict (fallback/timeout/
    breaker — the contract every kernel-kind span carries) plus the
    wrapper's resolved-rung stats from ``LAST_DISPATCH``.  One
    definition for the session fast path and the bulk action, so the
    span contract cannot drift one-sided."""
    from ..utils.deviceguard import device_guard
    from ..utils.tracing import TRACER
    guard = device_guard()
    fb0, to0 = guard.fallback_calls, guard.timeouts
    with TRACER.span("allocate_fused", kind="kernel", **attrs) as sp:
        yield
        sp.set(fallback=guard.fallback_calls > fb0,
               timed_out=guard.timeouts > to0,
               breaker=guard.breaker.state, **LAST_DISPATCH)


def group_tasks(task_req: np.ndarray, task_job: np.ndarray,
                task_selector: np.ndarray, task_tolerations: np.ndarray,
                task_mergeable: np.ndarray | None = None):
    """Host-side prep: run-length groups over identical adjacent tasks.

    ``task_mergeable`` ([T] bool): tasks whose jobs place INDEPENDENTLY
    (single-task chunks with trivial gang semantics) — identical adjacent
    mergeable tasks group together ACROSS job boundaries, collapsing e.g.
    a burst of 20k identical one-pod jobs into one scan step.

    Returns (group_of_task [T], group_req [G,R], group_sel [G,L],
    group_tol [G,Tl], group_count [G], group_job [G], group_indep [G]).
    """
    t = task_req.shape[0]
    if t == 0:
        return (np.zeros(0, np.int32), np.zeros((0, task_req.shape[1])),
                np.zeros((0, task_selector.shape[1]), np.int32),
                np.zeros((0, task_tolerations.shape[1]), np.int32),
                np.zeros(0), np.zeros(0, np.int32), np.zeros(0, bool))
    if task_mergeable is None:
        task_mergeable = np.zeros(t, bool)
    change = np.zeros(t, bool)
    change[0] = True
    job_break = task_job[1:] != task_job[:-1]
    job_break &= ~(task_mergeable[1:] & task_mergeable[:-1])
    change[1:] = (
        job_break
        | (task_req[1:] != task_req[:-1]).any(axis=1)
        | (task_selector[1:] != task_selector[:-1]).any(axis=1)
        | (task_tolerations[1:] != task_tolerations[:-1]).any(axis=1))
    group_of_task = (np.cumsum(change) - 1).astype(np.int32)
    starts = np.flatnonzero(change)
    counts = np.diff(np.append(starts, t)).astype(np.float64)
    return (group_of_task, task_req[starts], task_selector[starts],
            task_tolerations[starts], counts,
            task_job[starts].astype(np.int32), task_mergeable[starts])


def _compact(take, key, max_group: int):
    """Gather the nonzero fill segments into [max_group] slots in
    ascending node-index order (score ordering is applied AFTER the scan,
    as one batched sort over all groups — see _order_segments).

    Slot s holds the s-th node with a nonzero take, found by binary
    search over the running nonzero count.  This is gather-only: the
    scatter formulation (.at[slot].set over the full node axis) lowered
    to per-element stores and dominated large-cluster cycle latency
    (~1.2ms per call at 98k nodes), and a per-step argsort would sit on
    the sequential scan's critical path."""
    flag = take > 0
    csum = jnp.cumsum(flag.astype(jnp.int32))
    total = csum[-1]
    nodes = jnp.searchsorted(
        csum, jnp.arange(1, max_group + 1, dtype=jnp.int32)).astype(
        jnp.int32)
    valid = jnp.arange(max_group) < jnp.minimum(total, max_group)
    nodes = jnp.where(valid, nodes, -1)
    counts = jnp.where(valid, take[jnp.clip(nodes, 0)],
                       jnp.zeros((), take.dtype))
    seg_key = jnp.where(valid, key[jnp.clip(nodes, 0)],
                        jnp.zeros((), key.dtype))
    return nodes, counts, seg_key


def _order_segments(seg_nodes, seg_counts, seg_pipe, seg_keys):
    """One batched sort over [G, K]: within each group, segments order
    descending by score key (availability folded in, so fit-now items of
    a tier precede its pipeline items and boosted pipeline items precede
    unboosted fit-now ones) with the ascending-item-index tie-break (the
    input order is ascending interleaved item index and the sort is
    stable), empty slots last — reproducing the exact kernel's placement
    sequence.  Batched across groups, this runs once per kernel call
    instead of once per scan step."""
    empty = jnp.where(seg_counts > 0, jnp.uint32(0), jnp.uint32(1))
    _, _, seg_nodes, seg_counts, seg_pipe = jax.lax.sort(
        (empty, ~seg_keys, seg_nodes, seg_counts,
         seg_pipe.astype(jnp.uint32)),
        dimension=-1, num_keys=2, is_stable=True)
    return seg_nodes, seg_counts, seg_pipe > 0


def _score_keys(score, force_f32: bool = False):
    """Order-preserving unsigned-integer keys for float scores: key(a) >
    key(b) iff a > b.  (levels, utype) size the radix select below.

    On TPU the float64 path downcasts to float32 first: XLA's x64-rewrite
    pass cannot lower a u64 bitcast-convert on TPU (crashes at compile),
    and score ORDER at f32 precision is what the hardware natively
    supports — CPU runs (the x64 parity suite) keep the exact u64 path.

    ``force_f32`` SIMULATES the TPU downcast on any backend: the
    precision-split property suite (tests/test_score_precision.py) pins
    it to prove f32 keys only ever COLLAPSE f64 ties (downcast is
    monotone), never invert an ordering — the tier-1 guardian for the
    bench's TPU-vs-CPU-x64 parity child that otherwise needs a live
    tunnel.
    """
    # kailint: disable=KAI001 — force_f32 mirrors a static_argname flag
    if not force_f32 and score.dtype == jnp.float64 \
            and jax.default_backend() != "tpu":
        bits = jax.lax.bitcast_convert_type(score, jnp.uint64)
        key = jnp.where(bits >> jnp.uint64(63) == 1, ~bits,
                        bits | jnp.uint64(1 << 63))
        return key, 8, jnp.uint64
    bits = jax.lax.bitcast_convert_type(score.astype(jnp.float32),
                                        jnp.uint32)
    key = jnp.where(bits >> jnp.uint32(31) == 1, ~bits,
                    bits | jnp.uint32(1 << 31))
    return key, 4, jnp.uint32


def _histogram(capw, digit, bins):
    """Capacity histogram over radix digits WITHOUT materializing a
    one-hot: the broadcast-compare feeds straight into the axis-0 sum, so
    XLA's reduce fusion reads ``capw``/``digit`` once per lane tile
    instead of writing+reading an [N, bins] f32 one-hot through HBM (the
    previous matmul formulation's dominant per-step cost at 98k nodes).
    Accumulation stays in ``capw.dtype``.  In f32 a bin's capacity sum
    (and the cumsum over bins) can exceed 2^24 at large shapes — e.g.
    98k nodes with per-node caps clipped to the gang count — so the sums
    themselves are not guaranteed exact there.  The threshold decision
    stays correct because ``need <= count`` keeps the compared region
    (cumulative capacity up to the threshold digit vs the remaining
    need) within the exactly-representable range: the select only reads
    the histogram where the running total is still below ``need``."""
    ar = jnp.arange(bins)
    return jnp.sum(jnp.where(digit[:, None] == ar[None, :],
                             capw[:, None], jnp.zeros((), capw.dtype)),
                   axis=0)


def _fill_by_score(key, levels, utype, cap, count):
    """Exact greedy fill WITHOUT sorting: distribute ``count`` units over
    nodes in descending-score order (ascending index among ties), each
    node bounded by ``cap``.

    The fill is monotone in score, so it is fully described by a threshold
    key: nodes strictly above it take their whole capacity, nodes at it
    split the remainder in index order.  The threshold is found by
    radix-select — per 8-bit digit, a fused capacity histogram (no sort,
    no top_k, no scatter, no materialized one-hot) and a 256-wide scan.
    Replaces the per-step ``lax.top_k`` over the full node axis, which
    lowers to a full sort per scan step and dominated large-cluster cycle
    latency.
    """
    n_bits = levels * 8
    prefix = jnp.zeros((), utype)
    above = jnp.zeros((), cap.dtype)
    for level in range(levels):
        shift = n_bits - 8 * (level + 1)
        digit = ((key >> utype(shift)) & utype(0xFF)).astype(jnp.int32)
        if level == 0:
            capw = cap
        else:
            in_prefix = (key >> utype(n_bits - 8 * level)) == prefix
            capw = jnp.where(in_prefix, cap, 0.0)
        hist = _histogram(capw, digit, 256)
        ge = jnp.cumsum(hist[::-1])[::-1]          # capacity(digit >= d)
        gt = ge - hist                             # capacity(digit >  d)
        need = count - above                       # invariant: need > 0
        crossing = (gt < need) & (need <= ge)
        # Unique crossing digit when total capacity suffices; else fall to
        # digit 0 (everything ends up full-taken, clipped by cap).
        d_star = jnp.where(crossing.any(), jnp.argmax(crossing),
                           0).astype(jnp.int32)
        above = above + gt[d_star]
        prefix = (prefix << utype(8)) | d_star.astype(utype)
    take_full = jnp.where(key > prefix, cap, 0.0)
    eqcap = jnp.where(key == prefix, cap, 0.0)
    rem = jnp.maximum(count - above, 0.0)
    pref = jnp.cumsum(eqcap)
    take_eq = jnp.clip(rem - (pref - eqcap), 0.0, eqcap)
    # count <= 0 (gated/fully-satisfied): the no-crossing fallback above
    # would otherwise full-take everything.
    return jnp.where(count > 0, take_full + take_eq, 0.0)


def _fill_by_score_descent(key, levels, utype, cap, count):
    """Exact greedy fill with the same take semantics as
    ``_fill_by_score``, built from fused masked-sum reductions instead of
    the 256-wide capacity histogram.

    The histogram formulation pays O(items x 256) broadcast-compare work
    per level; on CPU (and for the Pallas row outputs on TPU) the same
    threshold digit falls out of 2^W masked capacity sums per W-bit
    level — XLA multi-output-fuses them over a single read of
    (key, cap) — so the whole select is O(items x levels) with no
    scatter, no sort, no materialized one-hot.  Every per-digit sum is
    computed FRESH from the current in-prefix mask (never derived by
    subtracting a carried total, which would drag early >2^24-scale f32
    rounding error into the deep levels where the in-prefix set — and
    the legacy histogram's sums — have shrunk back to exact range), so
    the compared region stays exact for the same reason documented on
    ``_histogram``.
    """
    w = SELECT_DIGIT_BITS
    n_bits = levels * 8
    while n_bits % w:
        w -= 1
    n_levels = n_bits // w
    mask = utype((1 << w) - 1)

    def level_body(level, state):
        # A lax loop, not an unrolled Python one: unrolling 16-32 levels
        # of scalar select machinery ballooned XLA:CPU compile time by
        # >30s at even trivial shapes; the rolled form compiles in
        # milliseconds and the per-level loop overhead is noise next to
        # the masked-sum reductions.
        prefix, above = state
        shift = (jnp.asarray(n_bits, utype)
                 - utype(w) * (level.astype(utype) + utype(1)))
        cur = key >> shift
        # Level 0: cur >> w == 0 == prefix, so every key is in-prefix —
        # no special case (both shifts stay < the key width).
        capw = jnp.where((cur >> utype(w)) == prefix, cap,
                         jnp.zeros((), cap.dtype))
        dig = cur & mask
        h = [jnp.sum(jnp.where(dig == utype(d), capw,
                               jnp.zeros((), cap.dtype)))
             for d in range(1 << w)]
        # ge[d] = capacity(digit >= d); threshold digit d* is the unique
        # crossing gt(d) < need <= ge(d) (first match mirrors the
        # histogram form's argmax; fall to 0 when capacity is short).
        ge = [None] * (1 << w)
        acc = jnp.zeros((), cap.dtype)
        for d in reversed(range(1 << w)):
            acc = acc + h[d]
            ge[d] = acc
        need = count - above
        d_star = jnp.zeros((), utype)
        gt_sel = ge[0] - h[0]
        found = jnp.asarray(False)
        for d in range(1 << w):
            gt = ge[d] - h[d]
            c = (gt < need) & (need <= ge[d]) & ~found
            d_star = jnp.where(c, utype(d), d_star)
            gt_sel = jnp.where(c, gt, gt_sel)
            found = found | c
        d_star = jnp.where(found, d_star, utype(0))
        gt_sel = jnp.where(found, gt_sel, ge[0] - h[0])
        return ((prefix << utype(w)) | d_star, above + gt_sel)

    prefix, above = jax.lax.fori_loop(
        0, n_levels, level_body,
        (jnp.zeros((), utype), jnp.zeros((), cap.dtype)))
    take_full = jnp.where(key > prefix, cap, 0.0)
    eqcap = jnp.where(key == prefix, cap, 0.0)
    rem = jnp.maximum(count - above, 0.0)
    pref = jnp.cumsum(eqcap)
    take_eq = jnp.clip(rem - (pref - eqcap), 0.0, eqcap)
    return jnp.where(count > 0, take_full + take_eq, 0.0)


def _fused_row(node_allocatable, idle, rel, node_labels, node_taints,
               room, req, sel, tol, extra_row, mask_row,
               gpu_strategy: int, cpu_strategy: int,
               allow_pipeline: bool, pipeline_only: bool,
               releasing_empty: bool, pipe_items: bool,
               f32_keys: bool = False):
    """One fused pass over the node state for one group step:
    (key_now, key_pipe | None, cap_now, cap_rel | None, levels, utype).

    Composes the unrolled feasibility+capacity helper
    (predicates.feasibility_caps_row) with the column-selected scorer
    (scoring.score_row_selected) so the whole row is one elementwise DAG
    plus the two binpack min/max reductions — no [N]-wide intermediate
    crosses a fusion boundary more than once.  Formula-identical to the
    legacy step's feasibility_row + score_row + capacity composition.
    """
    fit_now, fit_future, cap_now_f, cap_tot_f = feasibility_caps_row(
        idle, None if releasing_empty else rel,
        node_labels, node_taints, room, req, sel, tol)
    if mask_row is not None:
        fit_now = fit_now & mask_row
        fit_future = fit_future & mask_row
    # The flag params mirror the kernel's static_argnames (the jitted
    # caller pins them); they are Python bools/ints at trace time.
    if pipeline_only:  # kailint: disable=KAI001
        fit_now = jnp.zeros_like(fit_now)
    feasible = fit_now | (fit_future if (allow_pipeline or pipeline_only)
                          else jnp.zeros_like(fit_future))
    if gpu_strategy == cpu_strategy:  # kailint: disable=KAI001
        score = score_row_selected(node_allocatable, idle, req, feasible,
                                   fit_now, gpu_strategy, cpu_strategy)
    else:  # mixed strategies: keep the two-axis canonical form
        score = score_row(node_allocatable, idle, req, feasible, fit_now,
                          gpu_strategy, cpu_strategy)
    if extra_row is not None:
        score = score + extra_row
    score = jnp.where(feasible, score, NEG)
    key_now, levels, utype = _score_keys(score, f32_keys)

    cap_now = jnp.where(fit_now, jnp.minimum(cap_now_f, room), 0.0)
    cap_tot = jnp.where(feasible, jnp.minimum(cap_tot_f, room), 0.0)
    if not pipe_items:  # kailint: disable=KAI001
        return key_now, None, cap_now, None, levels, utype
    score_pipe = score - jnp.where(fit_now, AVAILABILITY, 0.0)
    key_pipe, _, _ = _score_keys(score_pipe, f32_keys)
    return key_now, key_pipe, cap_now, cap_tot, levels, utype


@functools.partial(jax.jit,
                   static_argnames=("max_group", "gpu_strategy",
                                    "cpu_strategy", "allow_pipeline",
                                    "pipeline_only", "single_group_jobs",
                                    "fused_mode", "releasing_empty",
                                    "f32_keys"))
def allocate_groups_kernel(node_allocatable, node_idle, node_releasing,
                           node_labels, node_taints, node_pod_room,
                           group_req, group_sel, group_tol, group_count,
                           group_job, job_allowed, max_group: int,
                           group_indep=None, group_extra=None,
                           group_mask=None,
                           gpu_strategy: int = BINPACK,
                           cpu_strategy: int = BINPACK,
                           allow_pipeline: bool = True,
                           pipeline_only: bool = False,
                           single_group_jobs: bool = False,
                           fused_mode: str = "legacy",
                           releasing_empty: bool = False,
                           f32_keys: bool = False):
    """Scan over groups; per group emit up to max_group fill segments.

    Returns (seg_nodes [G,K], seg_counts [G,K], seg_pipe [G,K] — phase-B
    segments marked pipelined, group_placed [G], job_success [J],
    node_idle', node_releasing').

    ``single_group_jobs``: every job consists of exactly one group, so a
    failed gang never has prior groups to roll back — the checkpoint
    carries are dropped entirely (a failing group's own take is zeroed by
    its capacity gate).  The host wrapper enables this automatically.

    ``group_extra`` ([J,N] additive score row per JOB — topology and
    nominated-node boosts; groups gather their job's row on device) and
    ``group_mask`` ([J,N] bool hard feasibility — inter-pod-affinity/
    upstream-predicate verdicts, node subsets) extend the fill plan to
    heterogeneous-constraint gangs.
    PRECONDITION for exact parity with the per-task kernel: extra values
    are tier constants (multiples of 10, scoring.py) — the binpack term
    spans < 10, so a group's fill can never reorder nodes ACROSS extra
    levels mid-fill, and WITHIN a level the pure-binpack invariance
    argument above applies unchanged.  The session fast path checks this
    before routing (framework/session.py).

    ``fused_mode`` picks the per-step row implementation (static, decided
    by the host wrapper — docs/DESIGN.md fused-kernel section):
    ``legacy`` keeps the original feasibility_row + score_row + histogram
    composition; ``jnp`` runs the fused single-pass row
    (predicates.feasibility_caps_row + scoring.score_row_selected) with
    the masked-sum radix-descent fill; ``pallas`` swaps the row pass for
    the Pallas node-tile kernel (ops/pallas_kernels.group_step_pallas).
    ``releasing_empty`` (fused modes only) declares the releasing pool
    all-zero, which provably collapses the pipeline item tier: fit_future
    == fit_now, cap_rel == 0, so the step skips the pipe keys, the
    interleave, and the releasing update entirely.  The wrapper only sets
    it from a host-verified hint and never under ``pipeline_only`` (a
    pipeline-only fill mutates releasing below zero, invalidating the
    premise mid-scan)."""
    G = group_req.shape[0]
    N = node_allocatable.shape[0]
    K = max_group
    if group_indep is None:
        group_indep = jnp.zeros(G, bool)
    assert fused_mode in ("legacy", "jnp", "pallas"), fused_mode
    # A pipeline-only fill mutates releasing below zero mid-scan, which
    # invalidates the all-zero premise the specialization rests on; the
    # wrapper never combines them, direct callers must not either.
    assert not (releasing_empty and pipeline_only), \
        "releasing_empty is unsound under pipeline_only"
    fused = fused_mode != "legacy"
    # Pipe (phase-B) items exist unless the releasing tier is provably
    # dead; legacy always interleaves them (zero-capacity items are
    # harmless there and keep the original code byte-for-byte).
    pipe_items = (not fused) or pipeline_only \
        or (allow_pipeline and not releasing_empty)
    rel_static = fused and releasing_empty

    class Carry(NamedTuple):
        idle: jnp.ndarray
        rel: jnp.ndarray
        room: jnp.ndarray
        ck_idle: jnp.ndarray
        ck_rel: jnp.ndarray
        ck_room: jnp.ndarray
        cur_job: jnp.ndarray
        cur_ok: jnp.ndarray

    zero = jnp.zeros(())
    init = Carry(node_idle,
                 zero if rel_static else node_releasing,
                 node_pod_room,
                 zero if single_group_jobs else node_idle,
                 zero if (single_group_jobs or rel_static)
                 else node_releasing,
                 zero if single_group_jobs else node_pod_room,
                 jnp.array(-1, jnp.int32), jnp.array(False))

    def step(carry: Carry, g):
        j = group_job[g]
        new_job = j != carry.cur_job
        if single_group_jobs:
            idle, rel, room = carry.idle, carry.rel, carry.room
            ck_idle, ck_rel, ck_room = zero, zero, zero
            ok = job_allowed[j]
        else:
            keep = jnp.where(new_job & ~carry.cur_ok, False, True)
            idle = jnp.where(keep, carry.idle, carry.ck_idle)
            rel = jnp.where(keep, carry.rel, carry.ck_rel)
            room = jnp.where(keep, carry.room, carry.ck_room)
            ck_idle = jnp.where(new_job, idle, carry.ck_idle)
            ck_rel = jnp.where(new_job, rel, carry.ck_rel)
            ck_room = jnp.where(new_job, room, carry.ck_room)
            ok = jnp.where(new_job, job_allowed[j], carry.cur_ok)

        req = group_req[g]
        count = jnp.where(ok, group_count[g], 0.0)

        if fused:
            extra_row = group_extra[j] if group_extra is not None else None
            mask_row = group_mask[j] if group_mask is not None else None
            row_args = (node_allocatable, idle,
                        None if rel_static else rel,
                        node_labels, node_taints, room, req,
                        group_sel[g], group_tol[g], extra_row, mask_row)
            row_kw = dict(gpu_strategy=gpu_strategy,
                          cpu_strategy=cpu_strategy,
                          allow_pipeline=allow_pipeline,
                          pipeline_only=pipeline_only,
                          releasing_empty=rel_static,
                          pipe_items=pipe_items)
            if fused_mode == "pallas" and gpu_strategy == cpu_strategy:
                # (Pallas computes at f32 natively — f32_keys is a no-op
                # there; mixed per-axis strategies keep the two-axis
                # canonical scorer, which only the jnp row implements.)
                from .pallas_kernels import group_step_pallas
                (key_now, key_pipe, cap_now, cap_tot,
                 levels, utype) = group_step_pallas(*row_args, **row_kw)
            else:
                (key_now, key_pipe, cap_now, cap_tot,
                 levels, utype) = _fused_row(*row_args, f32_keys=f32_keys,
                                             **row_kw)
            cap_now = jnp.clip(cap_now, 0.0, count)
            if pipe_items:
                cap_rel = jnp.clip(cap_tot - cap_now, 0.0, count)
                key2 = jnp.stack([key_now, key_pipe], axis=1).reshape(-1)
                cap2 = jnp.stack([cap_now, cap_rel], axis=1).reshape(-1)
            else:
                # Releasing tier provably dead: items ARE nodes — same
                # ascending-index tie-break, half the fill width.
                key2, cap2 = key_now, cap_now
            take2 = jax.lax.cond(
                count > 0,
                lambda: _fill_by_score_descent(key2, levels, utype, cap2,
                                               count),
                lambda: jnp.zeros_like(cap2))
        else:
            fit_now, fit_future = feasibility_row(
                idle, rel, node_labels, node_taints, room, req,
                group_sel[g], group_tol[g])
            if group_mask is not None:
                mask_row = group_mask[j]
                fit_now = fit_now & mask_row
                fit_future = fit_future & mask_row
            if pipeline_only:
                fit_now = jnp.zeros_like(fit_now)
            feasible = fit_now | (fit_future
                                  if (allow_pipeline or pipeline_only)
                                  else jnp.zeros_like(fit_future))
            score = score_row(node_allocatable, idle, req, feasible,
                              fit_now, gpu_strategy, cpu_strategy)
            if group_extra is not None:
                score = score + group_extra[j]
            score = jnp.where(feasible, score, NEG)
            # Pipeline items score without the availability boost (the
            # exact kernel's fit_now term vanishes once a node's idle is
            # spent).
            score_pipe = score - jnp.where(fit_now, AVAILABILITY, 0.0)
            key_now, levels, utype = _score_keys(score, f32_keys)
            key_pipe, _, _ = _score_keys(score_pipe, f32_keys)

            safe_req = jnp.where(req > 0, req, 1.0)
            cap_now_f = jnp.min(jnp.where(
                req[None, :] > 0, jnp.floor(idle / safe_req[None, :]),
                jnp.inf), axis=1)
            cap_tot_f = jnp.min(jnp.where(
                req[None, :] > 0,
                jnp.floor((idle + rel) / safe_req[None, :]), jnp.inf),
                axis=1)
            cap_now = jnp.where(fit_now, jnp.minimum(cap_now_f, room), 0.0)
            cap_tot = jnp.where(feasible, jnp.minimum(cap_tot_f, room),
                                0.0)
            cap_now = jnp.clip(cap_now, 0.0, count)
            cap_rel = jnp.clip(cap_tot - cap_now, 0.0, count)
            if not (allow_pipeline or pipeline_only):
                cap_rel = jnp.zeros_like(cap_rel)

            # ONE exact greedy fill, sort-free, over the interleaved 2N
            # (node, phase) items — item 2n is node n's idle capacity at
            # its full score, item 2n+1 its releasing capacity without
            # the availability boost.  Interleaving keeps equal-key ties
            # resolved by ascending node index, matching the exact
            # kernel's argmax.  The lax.cond skips the radix select
            # entirely for satisfied demands (padded/gated groups) —
            # most of a backlog cycle's step cost.
            key2 = jnp.stack([key_now, key_pipe], axis=1).reshape(-1)
            cap2 = jnp.stack([cap_now, cap_rel], axis=1).reshape(-1)
            take2 = jax.lax.cond(
                count > 0,
                lambda: _fill_by_score(key2, levels, utype, cap2, count),
                lambda: jnp.zeros_like(cap2))

        if pipe_items:
            take_a = take2[0::2]
            take_b = take2[1::2]
        else:
            take_a, take_b = take2, None
        placed = take2.sum()

        if single_group_jobs:
            # A failed gang must leave no trace: zero its takes in-step
            # (there is no later boundary to roll back at).  Independent
            # groups (merged single-task jobs) keep partial placements:
            # each member job succeeds or fails on its own.
            gang_ok = group_indep[g] | (placed >= count)
            take_a = jnp.where(gang_ok, take_a, 0.0)
            take2 = jnp.where(gang_ok, take2, 0.0)
            if take_b is not None:
                take_b = jnp.where(gang_ok, take_b, 0.0)

        idle = idle - take_a[:, None] * req[None, :]
        if not rel_static:
            rel = rel - (take_b if take_b is not None
                         else jnp.zeros_like(take_a))[:, None] * req[None, :]
        room = room - take_a - (take_b if take_b is not None else 0.0)

        # Compact the items once: with pipe items interleaved, item
        # index -> (node, phase); without, items are node indices.
        items, counts2, seg_keys = _compact(take2, key2, K)
        if pipe_items:
            seg_nodes = jnp.where(items >= 0, items >> 1, -1)
            seg_pipe = (items >= 0) & (items & 1 == 1) & (counts2 > 0)
        else:
            seg_nodes = items
            seg_pipe = jnp.zeros(K, bool)
        seg_counts = counts2

        ok = ok & (placed >= count)
        return (Carry(idle, rel, room, ck_idle, ck_rel, ck_room,
                      j.astype(jnp.int32), ok),
                (seg_nodes, seg_counts, seg_pipe, seg_keys, placed))

    carry, (seg_nodes, seg_counts, seg_pipe, seg_keys,
            group_placed) = jax.lax.scan(step, init, jnp.arange(G))
    seg_nodes, seg_counts, seg_pipe = _order_segments(
        seg_nodes, seg_counts, seg_pipe, seg_keys)
    if single_group_jobs:
        idle, rel = carry.idle, carry.rel
    else:
        idle = jnp.where(carry.cur_ok, carry.idle, carry.ck_idle)
        rel = jnp.where(carry.cur_ok, carry.rel, carry.ck_rel)
    if rel_static:
        # The scan never touched releasing (cap_rel proven 0): the input
        # array IS the output, with no per-step carry copies paid.
        rel = node_releasing

    num_jobs = job_allowed.shape[0]
    placed_per_job = jax.ops.segment_sum(group_placed, group_job,
                                         num_segments=num_jobs)
    count_per_job = jax.ops.segment_sum(group_count, group_job,
                                        num_segments=num_jobs)
    job_success = (count_per_job > 0) & (placed_per_job >= count_per_job) \
        & job_allowed
    return (seg_nodes, seg_counts, seg_pipe, group_placed, job_success,
            idle, rel)


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


@functools.partial(jax.jit,
                   static_argnames=("max_group", "t_pad", "gpu_strategy",
                                    "cpu_strategy", "allow_pipeline",
                                    "pipeline_only", "single_group_jobs",
                                    "fused_mode", "releasing_empty",
                                    "f32_keys"))
def _allocate_groups_packed(node_allocatable, node_idle, node_releasing,
                            node_labels, node_taints, node_pod_room,
                            group_req, group_sel, group_tol, group_count,
                            group_job, job_allowed, max_group: int,
                            t_pad: int, group_indep=None, **kw):
    """Kernel + DEVICE-SIDE per-task expansion + single-buffer packing.

    A remote device pays a full RTT per fetched buffer, so everything the
    host needs returns as ONE int32 array of length t_pad + J:
      [0:t_pad]   per-task encoding: -1 unplaced, node for allocated,
                  -(node+2) for pipelined;
      [t_pad:]    per-job success flags.
    Expanding segments to tasks on device replaces both the [G,K]x3
    segment fetch (12.6MB at the north-star shape) and the host-side
    Python per-group expansion loop with one [T] fetch.
    """
    G = group_req.shape[0]
    if group_indep is None:
        group_indep = jnp.zeros(G, bool)
    # G mirrors group_req's leading axis, an operand of this very call:
    # the caller already bucketed it, and the default group_indep can
    # mint no signature the kernel doesn't already key on.
    (seg_nodes, seg_counts, seg_pipe, _group_placed, job_success,
     idle, rel) = allocate_groups_kernel(  # kaijit: disable=KJT001
        node_allocatable, node_idle, node_releasing, node_labels,
        node_taints, node_pod_room, group_req, group_sel, group_tol,
        group_count, group_job, job_allowed, max_group,
        group_indep=group_indep, **kw)
    # A group expands only if it is independent (partial placements keep
    # task order: first jobs of a merged run win) or its gang succeeded.
    gate = group_indep | job_success[group_job]
    counts = jnp.where(gate[:, None], seg_counts, 0).astype(jnp.int32)
    enc = jnp.where(seg_pipe, -(seg_nodes + 2), seg_nodes)
    # Sentinel column per group: the unplaced tail of each group maps to
    # -1, keeping every group's tasks aligned at their original offsets;
    # one trailing sentinel absorbs the pad to t_pad.
    sentinel = (group_count.astype(jnp.int32)
                - counts.sum(axis=1))[:, None]
    flat_enc = jnp.concatenate([
        jnp.concatenate([enc, jnp.full((G, 1), -1, enc.dtype)],
                        axis=1).ravel(),
        jnp.array([-1], enc.dtype)])
    flat_counts = jnp.concatenate([
        jnp.concatenate([counts, sentinel], axis=1).ravel(),
        (t_pad - group_count.sum().astype(jnp.int32))[None]])
    per_task = jnp.repeat(flat_enc, flat_counts,
                          total_repeat_length=t_pad)
    packed = jnp.concatenate([per_task.astype(jnp.int32),
                              job_success.astype(jnp.int32)])
    return packed, idle, rel


def _resolve_fused_mode(requested: str | None, n_nodes: int) -> str:
    """Resolve the fallback ladder TPU-Pallas -> fused-jnp -> legacy.

    Explicit request (session config / tests) wins, then the
    KAI_FUSED_ALLOC env pin, then ``auto``: the Pallas node-tile kernel
    on a TPU backend whose node bucket tiles evenly, the fused jnp
    formulation everywhere else.  ``legacy`` is only ever an explicit
    choice — it exists for the parity suites and as the operator's
    escape hatch, not as an automatic fallback target."""
    mode = (requested or os.environ.get(_FUSED_ENV) or "auto").strip()
    if mode not in FUSED_MODES:
        # An unrecognized pin (case typo mid-incident) must be LOUD, not
        # silently coerced back onto the rung the operator tried to
        # escape.
        from ..utils.logging import LOG
        from ..utils.metrics import METRICS
        LOG.warning("allocate_grouped: unrecognized %s=%r (valid: %s); "
                    "using auto", _FUSED_ENV, mode, "|".join(FUSED_MODES))
        METRICS.inc("allocate_fused_invalid_mode_total")
        mode = "auto"
    if mode == "auto":
        if jax.default_backend() == "tpu":
            from .pallas_kernels import NODE_TILE, pallas_available
            if pallas_available() and n_nodes >= NODE_TILE \
                    and n_nodes % NODE_TILE == 0:
                return "pallas"
        return "jnp"
    if mode == "pallas":
        # An explicitly pinned Pallas rung still needs a tileable node
        # bucket and an importable Pallas; downgrade one rung (loudly,
        # via the downgrade counter) instead of crashing mid-dispatch.
        from .pallas_kernels import NODE_TILE, pallas_available
        tile = min(NODE_TILE, max(n_nodes, 1))
        if not (pallas_available() and n_nodes and n_nodes % tile == 0):
            from ..utils.metrics import METRICS
            METRICS.inc("allocate_fused_downgrade_total")
            return "jnp"
    return mode


def allocate_grouped(node_arrays, task_req, task_job, task_selector,
                     task_tolerations, job_allowed,
                     gpu_strategy: int = BINPACK,
                     cpu_strategy: int = BINPACK,
                     allow_pipeline: bool = True,
                     pipeline_only: bool = False,
                     independent_jobs=None,
                     extra_scores=None,
                     node_mask=None,
                     fused_mode: str | None = None,
                     has_releasing: bool | None = None,
                     f32_keys: bool | None = None) -> AllocationResult:
    """Host wrapper: group prep -> group-scan kernel (with on-device
    per-task expansion).

    Drop-in equivalent of ops.allocate.allocate_jobs_kernel for bin-pack
    strategies.  ``independent_jobs`` ([J] bool): single-task jobs whose
    placement is independent — identical adjacent ones merge into one
    group (one scan step for a whole burst wave), each member succeeding
    or failing on its own.

    ``extra_scores``: [J,N] additive per-JOB score rows (every task of a
    job shares one row — the common shape of topology/nominated boosts);
    values must be tier constants (multiples of 10) for exact parity —
    see allocate_groups_kernel.  ``node_mask``: [J,N] bool per-job hard
    feasibility rows.  Jobs with either disable group merging across job
    boundaries (rows differ) but still fill in one step per group.

    ``fused_mode``: pallas | jnp | legacy | auto (default: the
    KAI_FUSED_ALLOC env pin, else auto — see ``_resolve_fused_mode``).
    ``has_releasing``: host-verified hint that the releasing pool has any
    nonzero entry; callers holding host mirrors (the session via the
    arena state cache) pass it so the no-releasing fused specialization
    engages without fetching resident device state.  ``None`` checks the
    array directly off-TPU and conservatively assumes releasing capacity
    on TPU (a hint fetch there would pay the tunnel round trip the arena
    exists to avoid).
    """
    np_req = np.asarray(task_req)
    np_job = np.asarray(task_job)
    np_sel = np.asarray(task_selector)
    np_tol = np.asarray(task_tolerations)
    allowed_np = np.asarray(job_allowed)
    mergeable = None
    if independent_jobs is not None and extra_scores is None \
            and node_mask is None:
        # (Per-job extra/mask rows disable cross-job merging: a merged
        # group can only carry one row.)
        indep_np = np.asarray(independent_jobs)
        # Independence only holds for single-task jobs: partial placement
        # of a gang would silently break its atomicity.
        task_counts = np.bincount(np_job, minlength=len(indep_np))
        assert not (indep_np & (task_counts != 1)).any(), \
            "independent_jobs may only flag single-task jobs"
        # Merging may not cross an allowed/gated boundary: the kernel
        # gates a whole group by its first job's flag.
        mergeable = indep_np[np_job] & allowed_np[np_job]
    (group_of_task, g_req, g_sel, g_tol, g_count,
     g_job, g_indep) = group_tasks(np_req, np_job, np_sel, np_tol,
                                   mergeable)
    # Homogeneous gangs: one group per job lets the kernel drop its
    # checkpoint carries entirely.  Merged groups alias several jobs to
    # one group_job; that is only sound in this no-checkpoint mode, so
    # fall back to unmerged grouping otherwise.
    single = len(g_job) == len(set(g_job.tolist()))
    if not single and mergeable is not None and mergeable.any():
        (group_of_task, g_req, g_sel, g_tol, g_count,
         g_job, g_indep) = group_tasks(np_req, np_job, np_sel, np_tol)
        single = len(g_job) == len(set(g_job.tolist()))
    max_group = _next_pow2(int(g_count.max()) if len(g_count) else 1)

    # Pad the ragged group/job/task axes to power-of-two buckets: a steady
    # backlog whose pending count drifts by a few jobs per cycle must not
    # recompile the kernel every cycle (each distinct (G, J, T) is a fresh
    # XLA compilation — seconds per cycle at burst scale).  Padded groups
    # carry count 0 and point at padded jobs gated to False; padded jobs
    # keep group_job values distinct so single-group mode is preserved.
    n_real_groups = len(g_count)
    n_real_jobs = len(allowed_np)
    T = np_req.shape[0]
    t_pad = _next_pow2(max(T, 1))
    g_pad = _next_pow2(max(n_real_groups, 1)) - n_real_groups
    n_jobs_padded = _next_pow2(max(n_real_jobs + g_pad, 1))
    job_allowed_padded = np.zeros(n_jobs_padded, bool)
    job_allowed_padded[:n_real_jobs] = allowed_np
    if g_pad:
        g_req = np.concatenate([g_req, np.zeros((g_pad, g_req.shape[1]))])
        g_sel = np.concatenate(
            [g_sel, np.full((g_pad, g_sel.shape[1]), -1, g_sel.dtype)])
        g_tol = np.concatenate(
            [g_tol, np.full((g_pad, g_tol.shape[1]), -1, g_tol.dtype)])
        g_count = np.concatenate([g_count, np.zeros(g_pad)])
        g_job = np.concatenate([
            g_job, (n_real_jobs + np.arange(g_pad)).astype(np.int32)])
        g_indep = np.concatenate([g_indep, np.zeros(g_pad, bool)])
    kw = {}
    if extra_scores is not None or node_mask is not None:
        # Per-JOB rows, padded to the job axis; groups gather their job's
        # row on device (no [G,N] host expansion).  f32 is exact for tier
        # constants (multiples of 10 below 2^24).
        n_nodes = int(node_arrays[0].shape[0])
        if extra_scores is not None:
            j_extra = np.zeros((n_jobs_padded, n_nodes), np.float32)
            j_extra[:n_real_jobs] = np.asarray(extra_scores)
            kw["group_extra"] = jnp.asarray(j_extra)
        if node_mask is not None:
            j_mask = np.ones((n_jobs_padded, n_nodes), bool)
            j_mask[:n_real_jobs] = np.asarray(node_mask)
            kw["group_mask"] = jnp.asarray(j_mask)

    # Shape metadata only — never np.asarray a possibly-device-resident
    # tensor here (that is a full host fetch on the tunneled TPU).
    n_nodes_padded = int(node_arrays[0].shape[0])
    mode = _resolve_fused_mode(fused_mode, n_nodes_padded)
    releasing_empty = False
    if mode != "legacy" and not pipeline_only:
        if has_releasing is None:
            # Off-TPU the releasing array is host-adjacent (CPU backend)
            # so the hint is one cheap scan; on TPU assume releasing
            # capacity rather than fetch resident arena state for a hint.
            has_releasing = True if jax.default_backend() == "tpu" \
                else bool(np.asarray(node_arrays[2]).any())
        releasing_empty = not has_releasing
    if f32_keys is None:
        # KAI_F32_SCORE_KEYS=1 simulates the TPU key downcast on any
        # backend (the precision-split suite's end-to-end hook).
        f32_keys = os.environ.get("KAI_F32_SCORE_KEYS") == "1"

    from ..utils.metrics import METRICS
    if mode != "legacy":
        METRICS.inc("allocate_fused_taken_total", mode=mode)
    # The guard may run this wrapper on its watchdog worker thread, where
    # cycle spans deliberately no-op — so the resolved rung is published
    # here and the CALL SITES (session fast path, bulk action) emit the
    # ``allocate_fused`` span on the cycle thread from these stats.
    LAST_DISPATCH.update(mode=mode, groups=n_real_groups,
                         nodes=n_nodes_padded,
                         releasing_empty=releasing_empty)
    packed, idle, rel = _allocate_groups_packed(
        *node_arrays, jnp.asarray(g_req), jnp.asarray(g_sel),
        jnp.asarray(g_tol), jnp.asarray(g_count), jnp.asarray(g_job),
        jnp.asarray(job_allowed_padded), max_group=max_group,
        t_pad=t_pad, group_indep=jnp.asarray(g_indep),
        gpu_strategy=gpu_strategy, cpu_strategy=cpu_strategy,
        allow_pipeline=allow_pipeline, pipeline_only=pipeline_only,
        single_group_jobs=single, fused_mode=mode,
        releasing_empty=releasing_empty, f32_keys=f32_keys, **kw)
    packed = np.asarray(packed)  # ONE device->host fetch
    enc = packed[:T]
    placements = np.where(enc >= -1, enc, -enc - 2).astype(np.int32)
    pipelined = enc < -1
    success = packed[t_pad:t_pad + n_real_jobs] > 0
    # Per-job success for merged independent jobs comes from their own
    # task's placement (the kernel's segment accounting aliases them to
    # the run's first job).  Mergeable jobs are single-task, so their
    # np_job values are unique: one vectorized assignment.
    if mergeable is not None and mergeable.any():
        success[np_job[mergeable]] = placements[mergeable] >= 0
    # All three outputs are host arrays derived from the ONE packed
    # fetch above — returning success as numpy keeps consumers from
    # paying an upload+fetch round trip to read it back.
    return AllocationResult(placements, pipelined, success, idle, rel)
