"""Device-arena scatter kernel: row-delta updates of resident node state.

The mutable node-state tensors (``idle``/``releasing``/``room``) live on
the device across cycles (framework/arena.py).  When K rows change —
statements committing placements, watch deltas between cycles — shipping
a full ``[N,R]`` re-upload pays the transfer floor for the whole cluster;
this kernel applies just the ``[K]`` row indices + ``[K,R]`` values as one
jitted scatter, so the transfer scales with the delta, not the fleet.

Callers pad K to a pow2 bucket (padding repeats a real row with its own
current value — an idempotent write) so the kernel compiles a handful of
shapes, not one per delta size.  Dispatch is host-side via
``Session.dispatch_kernel`` (watchdog/breaker/CPU-fallback; KAI004).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


# kaijit: resident-state=idle,releasing,room
# The value buffers are donated (KJT006): they are rebuilt host-side via
# jnp.asarray on EVERY dispatch (framework/arena.py), so a deviceguard
# retry re-creates them and donation is retry-safe; the resident arrays
# must NOT be donated — the functional old-state-on-failure contract
# and the retry both re-read them.
@functools.partial(jax.jit, donate_argnames=("idle_vals",
                                             "releasing_vals",
                                             "room_vals"))
def apply_deltas_kernel(idle, releasing, room, rows, idle_vals,
                        releasing_vals, room_vals):
    """Scatter row updates into the resident state arrays.

    idle/releasing: ``[N,R]``; room: ``[N]``; rows: ``[K]`` int; the value
    arrays carry the rows' new contents.  Returns the updated
    (idle, releasing, room) triple — functional, so a failed dispatch
    leaves the previous resident arrays untouched.
    """
    rows = rows.astype(jnp.int32)
    # Pin value dtypes to the resident arrays': a width drift between the
    # host mirrors and device state (x64 tests vs 32-bit production, or a
    # future bf16 residency) must scatter in the RESIDENT width instead
    # of promoting the whole [N,R] state on every delta — the promoted
    # result would silently evict the cached buffers each cycle.
    return (idle.at[rows].set(idle_vals.astype(idle.dtype)),
            releasing.at[rows].set(releasing_vals.astype(releasing.dtype)),
            room.at[rows].set(room_vals.astype(room.dtype)))
