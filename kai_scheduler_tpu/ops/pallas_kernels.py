"""Pallas TPU kernels for the per-task hot row ops.

The gang-allocation inner loop evaluates, per candidate task, a fused
feasibility + capacity + bin-pack-score pass over every node.  XLA already
fuses the jnp formulation well; this Pallas version keeps the whole pass in
one VMEM-resident kernel over node tiles — one HBM read of the node state
per evaluation, no intermediate materialization — and serves as the
hand-tuned escape hatch for the largest node counts.

Semantics match ops.predicates.feasibility_row + the capacity math of
ops.allocate_grouped (parity-tested); the public entry falls back to the
jnp path on non-TPU backends or when shapes don't tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

NODE_TILE = 512
NEG = -1e18


def _row_kernel(req_ref, sel_ref, tol_ref, idle_ref, rel_ref, labels_ref,
                taints_ref, room_ref, alloc_ref,
                fit_now_ref, fit_fut_ref, cap_now_ref, cap_tot_ref):
    """One node tile: feasibility masks + whole-task capacities.

    Shapes per tile: idle/rel/alloc [TILE, R]; labels [TILE, L];
    taints [TILE, Tt]; room [TILE]; req [R]; sel [L]; tol [Tl].
    """
    req = req_ref[...]            # [1, R]
    sel = sel_ref[...]            # [1, L]
    tol = tol_ref[...]            # [1, Tl]
    idle = idle_ref[...]          # [TILE, R]
    rel = rel_ref[...]
    labels = labels_ref[...]      # [TILE, L]
    taints = taints_ref[...]      # [TILE, Tt]
    room = room_ref[...]          # [TILE, 1]

    sel_ok = jnp.all((sel == -1) | (sel == labels), axis=-1,
                     keepdims=True)                    # [TILE,1]
    tolerated = jnp.any(taints[:, :, None] == tol[0][None, None, :],
                        axis=-1)                       # [TILE,Tt]
    taint_ok = jnp.all((taints == -1) | tolerated, axis=-1,
                       keepdims=True)
    hard = sel_ok & taint_ok & (room >= 1.0)

    fits_idle = jnp.all(req <= idle + 1e-9, axis=-1, keepdims=True)
    fits_total = jnp.all(req <= idle + rel + 1e-9, axis=-1, keepdims=True)
    fit_now = hard & fits_idle
    fit_fut = hard & fits_total

    safe_req = jnp.where(req > 0, req, 1.0)
    per_res_now = jnp.where(req > 0, jnp.floor(idle / safe_req), jnp.inf)
    per_res_tot = jnp.where(req > 0, jnp.floor((idle + rel) / safe_req),
                            jnp.inf)
    cap_now = jnp.minimum(jnp.min(per_res_now, axis=-1, keepdims=True),
                          room)
    cap_tot = jnp.minimum(jnp.min(per_res_tot, axis=-1, keepdims=True),
                          room)

    fit_now_ref[...] = fit_now.astype(jnp.float32)
    fit_fut_ref[...] = fit_fut.astype(jnp.float32)
    cap_now_ref[...] = jnp.where(fit_now, cap_now, 0.0).astype(jnp.float32)
    cap_tot_ref[...] = jnp.where(fit_fut, cap_tot, 0.0).astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def task_row_pallas(req, sel, tol, node_idle, node_releasing, node_labels,
                    node_taints, node_room, node_allocatable,
                    interpret: bool | None = None):
    """Fused per-task row pass: (fit_now, fit_future, cap_now, cap_tot)
    each [N] — the Pallas version of feasibility_row + capacity math.

    ``interpret`` defaults to True off-TPU (the Pallas CPU interpreter,
    used by the test suite); on TPU the kernel compiles to Mosaic."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = node_idle.shape[0]
    tile = min(NODE_TILE, n)
    if n % tile != 0:
        raise ValueError(f"node count {n} must tile by {tile}")
    grid = (n // tile,)
    r = node_idle.shape[1]
    L = node_labels.shape[1]
    tt = node_taints.shape[1]

    def node_block(shape_cols):
        return pl.BlockSpec((tile, shape_cols), lambda i: (i, 0))

    out_shape = [jax.ShapeDtypeStruct((n, 1), jnp.float32)] * 4
    fit_now, fit_fut, cap_now, cap_tot = pl.pallas_call(
        _row_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, r), lambda i: (0, 0)),      # req
            pl.BlockSpec((1, L), lambda i: (0, 0)),      # sel
            pl.BlockSpec((1, tol.shape[0]), lambda i: (0, 0)),  # tol
            node_block(r),                                # idle
            node_block(r),                                # releasing
            node_block(L),                                # labels
            node_block(tt),                               # taints
            node_block(1),                                # room
            node_block(r),                                # allocatable
        ],
        out_specs=[node_block(1)] * 4,
        out_shape=out_shape,
        interpret=interpret,
    )(req[None, :].astype(jnp.float32), sel[None, :].astype(jnp.int32),
      tol[None, :].astype(jnp.int32),
      node_idle.astype(jnp.float32), node_releasing.astype(jnp.float32),
      node_labels.astype(jnp.int32), node_taints.astype(jnp.int32),
      node_room.astype(jnp.float32)[:, None],
      node_allocatable.astype(jnp.float32))
    return (fit_now[:, 0] > 0.5, fit_fut[:, 0] > 0.5,
            cap_now[:, 0], cap_tot[:, 0])


def pallas_available() -> bool:
    """Pallas TPU kernels need a real TPU backend (the CPU interpreter
    path works too, for tests)."""
    try:
        from jax.experimental import pallas  # noqa: F401
        return True
    except ImportError:  # pragma: no cover
        return False


def task_row_reference(req, sel, tol, node_idle, node_releasing,
                       node_labels, node_taints, node_room):
    """jnp reference for parity tests (mirrors feasibility_row + the
    grouped kernel's capacity computation)."""
    from .predicates import feasibility_row
    fit_now, fit_fut = feasibility_row(
        node_idle, node_releasing, node_labels, node_taints, node_room,
        req, sel, tol)
    safe_req = jnp.where(req > 0, req, 1.0)
    cap_now = jnp.min(jnp.where(req[None, :] > 0,
                                jnp.floor(node_idle / safe_req[None, :]),
                                jnp.inf), axis=1)
    cap_tot = jnp.min(jnp.where(
        req[None, :] > 0,
        jnp.floor((node_idle + node_releasing) / safe_req[None, :]),
        jnp.inf), axis=1)
    cap_now = jnp.where(fit_now, jnp.minimum(cap_now, node_room), 0.0)
    cap_tot = jnp.where(fit_fut, jnp.minimum(cap_tot, node_room), 0.0)
    return fit_now, fit_fut, cap_now, cap_tot
