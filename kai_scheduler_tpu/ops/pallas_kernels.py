"""Pallas TPU kernels for the allocation hot row ops.

Two generations of kernels live here:

- ``task_row_pallas`` — the original per-TASK row pass (feasibility +
  capacity for one task against all nodes), kept as the escape hatch for
  the exact per-task kernel's largest shapes;
- ``group_step_pallas`` — the fused per-GROUP-STEP row pass the grouped
  fill-plan kernel (ops/allocate_grouped, fused_mode="pallas") runs
  inside its scan.  One ``pallas_call`` with a (phase, node-tile) grid
  sweeps the resident node state twice, entirely in VMEM per tile:
  phase 0 accumulates the bin-pack min/max over the task's valid nodes
  into SMEM scratch; phase 1 emits the fill keys (sign-flipped f32 score
  bitcasts, ready for the radix-descent fill) and the idle/total
  whole-task capacities.  That is TWO HBM reads of the node tensors per
  group step and zero materialized [N]-wide intermediates, versus the
  ~dozen reduction-separated passes of the unfused composition.

Semantics match ops.predicates.feasibility_caps_row +
ops.scoring.score_row_selected at f32 (parity-tested in interpret mode);
the host wrapper's mode resolution falls back to the fused-jnp path on
non-TPU backends or when the node bucket doesn't tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

NODE_TILE = 512
NEG = -1e18


def _row_kernel(req_ref, sel_ref, tol_ref, idle_ref, rel_ref, labels_ref,
                taints_ref, room_ref, alloc_ref,
                fit_now_ref, fit_fut_ref, cap_now_ref, cap_tot_ref):
    """One node tile: feasibility masks + whole-task capacities.

    Shapes per tile: idle/rel/alloc [TILE, R]; labels [TILE, L];
    taints [TILE, Tt]; room [TILE]; req [R]; sel [L]; tol [Tl].
    """
    req = req_ref[...]            # [1, R]
    sel = sel_ref[...]            # [1, L]
    tol = tol_ref[...]            # [1, Tl]
    idle = idle_ref[...]          # [TILE, R]
    rel = rel_ref[...]
    labels = labels_ref[...]      # [TILE, L]
    taints = taints_ref[...]      # [TILE, Tt]
    room = room_ref[...]          # [TILE, 1]

    sel_ok = jnp.all((sel == -1) | (sel == labels), axis=-1,
                     keepdims=True)                    # [TILE,1]
    tolerated = jnp.any(taints[:, :, None] == tol[0][None, None, :],
                        axis=-1)                       # [TILE,Tt]
    taint_ok = jnp.all((taints == -1) | tolerated, axis=-1,
                       keepdims=True)
    hard = sel_ok & taint_ok & (room >= 1.0)

    fits_idle = jnp.all(req <= idle + 1e-9, axis=-1, keepdims=True)
    fits_total = jnp.all(req <= idle + rel + 1e-9, axis=-1, keepdims=True)
    fit_now = hard & fits_idle
    fit_fut = hard & fits_total

    safe_req = jnp.where(req > 0, req, 1.0)
    per_res_now = jnp.where(req > 0, jnp.floor(idle / safe_req), jnp.inf)
    per_res_tot = jnp.where(req > 0, jnp.floor((idle + rel) / safe_req),
                            jnp.inf)
    cap_now = jnp.minimum(jnp.min(per_res_now, axis=-1, keepdims=True),
                          room)
    cap_tot = jnp.minimum(jnp.min(per_res_tot, axis=-1, keepdims=True),
                          room)

    fit_now_ref[...] = fit_now.astype(jnp.float32)
    fit_fut_ref[...] = fit_fut.astype(jnp.float32)
    cap_now_ref[...] = jnp.where(fit_now, cap_now, 0.0).astype(jnp.float32)
    cap_tot_ref[...] = jnp.where(fit_fut, cap_tot, 0.0).astype(jnp.float32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def task_row_pallas(req, sel, tol, node_idle, node_releasing, node_labels,
                    node_taints, node_room, node_allocatable,
                    interpret: bool | None = None):
    """Fused per-task row pass: (fit_now, fit_future, cap_now, cap_tot)
    each [N] — the Pallas version of feasibility_row + capacity math.

    ``interpret`` defaults to True off-TPU (the Pallas CPU interpreter,
    used by the test suite); on TPU the kernel compiles to Mosaic."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = node_idle.shape[0]
    tile = min(NODE_TILE, n)
    if n % tile != 0:
        raise ValueError(f"node count {n} must tile by {tile}")
    grid = (n // tile,)
    r = node_idle.shape[1]
    L = node_labels.shape[1]
    tt = node_taints.shape[1]

    def node_block(shape_cols):
        return pl.BlockSpec((tile, shape_cols), lambda i: (i, 0))

    out_shape = [jax.ShapeDtypeStruct((n, 1), jnp.float32)] * 4
    fit_now, fit_fut, cap_now, cap_tot = pl.pallas_call(
        _row_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, r), lambda i: (0, 0)),      # req
            pl.BlockSpec((1, L), lambda i: (0, 0)),      # sel
            pl.BlockSpec((1, tol.shape[0]), lambda i: (0, 0)),  # tol
            node_block(r),                                # idle
            node_block(r),                                # releasing
            node_block(L),                                # labels
            node_block(tt),                               # taints
            node_block(1),                                # room
            node_block(r),                                # allocatable
        ],
        out_specs=[node_block(1)] * 4,
        out_shape=out_shape,
        interpret=interpret,
    )(req[None, :].astype(jnp.float32), sel[None, :].astype(jnp.int32),
      tol[None, :].astype(jnp.int32),
      node_idle.astype(jnp.float32), node_releasing.astype(jnp.float32),
      node_labels.astype(jnp.int32), node_taints.astype(jnp.int32),
      node_room.astype(jnp.float32)[:, None],
      node_allocatable.astype(jnp.float32))
    return (fit_now[:, 0] > 0.5, fit_fut[:, 0] > 0.5,
            cap_now[:, 0], cap_tot[:, 0])


def _tile_row_terms(req, sel, tol, idle, rel, labels, taints, room,
                    mask, releasing_empty: bool):
    """Shared per-tile feasibility + capacity terms (f32, unrolled R).

    Mirrors predicates.feasibility_caps_row on one VMEM-resident tile:
    req/sel/tol are [1, X] rows, node state is [TILE, X].  Returns
    (fit_now, fit_future, cap_now_f, cap_tot_f), each [TILE, 1]."""
    from .predicates import EPS, NO_LABEL, NO_TAINT
    sel_ok = jnp.all((sel == NO_LABEL) | (sel == labels), axis=-1,
                     keepdims=True)
    tolerated = jnp.any(taints[:, :, None] == tol[0][None, None, :],
                        axis=-1)
    taint_ok = jnp.all((taints == NO_TAINT) | tolerated, axis=-1,
                       keepdims=True)
    hard = sel_ok & taint_ok & (room >= 1.0)
    if mask is not None:
        hard = hard & (mask > 0.5)

    r_dims = idle.shape[1]
    fits_idle = hard
    fits_total = hard
    cap_now_f = None
    cap_tot_f = None
    for r in range(r_dims):
        rq = req[0, r]
        safe = jnp.where(rq > 0, rq, 1.0)
        col = idle[:, r:r + 1]
        fits_idle = fits_idle & (rq <= col + EPS)
        ratio = jnp.where(rq > 0, jnp.floor(col / safe), jnp.inf)
        cap_now_f = ratio if cap_now_f is None \
            else jnp.minimum(cap_now_f, ratio)
        if not releasing_empty:
            tot = col + rel[:, r:r + 1]
            fits_total = fits_total & (rq <= tot + EPS)
            ratio_t = jnp.where(rq > 0, jnp.floor(tot / safe), jnp.inf)
            cap_tot_f = ratio_t if cap_tot_f is None \
                else jnp.minimum(cap_tot_f, ratio_t)
    if releasing_empty:
        return fits_idle, fits_idle, cap_now_f, cap_now_f
    return fits_idle, fits_total, cap_now_f, cap_tot_f


def _f32_key(score):
    """Order-preserving u32 key for an f32 score (per-lane form of
    ops.allocate_grouped._score_keys' f32 branch)."""
    bits = jax.lax.bitcast_convert_type(score, jnp.uint32)
    return jnp.where(bits >> jnp.uint32(31) == 1, ~bits,
                     bits | jnp.uint32(1 << 31))


def group_step_pallas(node_allocatable, idle, rel, node_labels,
                      node_taints, room, req, sel, tol, extra_row,
                      mask_row, gpu_strategy: int, cpu_strategy: int,
                      allow_pipeline: bool, pipeline_only: bool,
                      releasing_empty: bool, pipe_items: bool,
                      interpret: bool | None = None):
    """Fused per-group-step row pass over node tiles: returns
    (key_now, key_pipe | None, cap_now, cap_tot | None, levels, utype)
    exactly like ops.allocate_grouped._fused_row, computed at f32.

    Grid (2, n_tiles): phase 0 reduces the selected resource column's
    valid min/max into SMEM scratch; phase 1 recomputes the tile terms
    from VMEM and writes keys + capacities.  ``interpret`` defaults to
    True off-TPU (the test suite's parity path); on TPU the kernel
    compiles to Mosaic."""
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    from ..api.resources import RES_CPU, RES_GPU
    from .scoring import (AVAILABILITY, MAX_HIGH_DENSITY, RESOURCE_TYPE,
                          SPREAD)

    n = idle.shape[0]
    tile = min(NODE_TILE, n)
    if n % tile != 0:
        raise ValueError(f"node count {n} must tile by {tile}")
    n_tiles = n // tile
    r = idle.shape[1]
    L = node_labels.shape[1]
    tt = node_taints.shape[1]
    have_rel = not releasing_empty
    have_extra = extra_row is not None
    have_mask = mask_row is not None

    def kernel(*refs):
        it = iter(refs)
        req_ref, sel_ref, tol_ref = next(it), next(it), next(it)
        alloc_ref, idle_ref = next(it), next(it)
        rel_ref = next(it) if have_rel else None
        labels_ref, taints_ref, room_ref = next(it), next(it), next(it)
        extra_ref = next(it) if have_extra else None
        mask_ref = next(it) if have_mask else None
        key_now_ref = next(it)
        cap_now_ref = next(it)
        key_pipe_ref = next(it) if pipe_items else None
        cap_tot_ref = next(it) if pipe_items else None
        minmax = next(it)  # SMEM scratch [2]

        phase = pl.program_id(0)
        j = pl.program_id(1)

        reqv = req_ref[...]
        idlev = idle_ref[...]
        relv = rel_ref[...] if have_rel else None
        roomv = room_ref[...]
        allocv = alloc_ref[...]
        maskv = mask_ref[...] if have_mask else None

        fit_now, fit_future, cap_now_f, cap_tot_f = _tile_row_terms(
            reqv, sel_ref[...], tol_ref[...], idlev, relv,
            labels_ref[...], taints_ref[...], roomv, maskv,
            releasing_empty)
        if pipeline_only:
            fit_now = jnp.zeros_like(fit_now)
        feasible = fit_now | (fit_future
                              if (allow_pipeline or pipeline_only)
                              else jnp.zeros_like(fit_future))

        is_gpu_job = reqv[0, RES_GPU] > 0.0
        free = jnp.where(is_gpu_job, idlev[:, RES_GPU:RES_GPU + 1],
                         idlev[:, RES_CPU:RES_CPU + 1])
        axcap = jnp.where(is_gpu_job, allocv[:, RES_GPU:RES_GPU + 1],
                          allocv[:, RES_CPU:RES_CPU + 1])
        has_res = axcap > 0.0
        valid = feasible & has_res

        @pl.when(phase == 0)
        def _accumulate():
            tile_min = jnp.min(jnp.where(valid, free, jnp.inf))
            tile_max = jnp.max(jnp.where(valid, free, -jnp.inf))

            @pl.when(j == 0)
            def _init():
                minmax[0] = tile_min
                minmax[1] = tile_max

            @pl.when(j != 0)
            def _fold():
                minmax[0] = jnp.minimum(minmax[0], tile_min)
                minmax[1] = jnp.maximum(minmax[1], tile_max)

        @pl.when(phase == 1)
        def _emit():
            if gpu_strategy == SPREAD:  # == cpu_strategy (wrapper gate)
                placement = jnp.where(
                    has_res, free / jnp.where(has_res, axcap, 1.0), 0.0)
            else:
                min_free = minmax[0]
                max_free = minmax[1]
                span = max_free - min_free
                flat = span <= 0.0
                placement = MAX_HIGH_DENSITY * (
                    1.0 - (free - min_free) / jnp.where(flat, 1.0, span))
                placement = jnp.where(flat, MAX_HIGH_DENSITY, placement)
                placement = jnp.where(has_res, placement, 0.0)
            node_has_gpu = allocv[:, RES_GPU:RES_GPU + 1] > 0.0
            rtype = jnp.where(
                jnp.where(is_gpu_job, node_has_gpu, ~node_has_gpu),
                RESOURCE_TYPE, 0.0)
            score = placement + rtype \
                + jnp.where(fit_now, AVAILABILITY, 0.0)
            if have_extra:
                score = score + extra_ref[...]
            score = jnp.where(feasible, score, NEG)
            key_now_ref[...] = _f32_key(score)
            cap_now_ref[...] = jnp.where(
                fit_now, jnp.minimum(cap_now_f, roomv), 0.0)
            if pipe_items:
                score_pipe = score - jnp.where(fit_now, AVAILABILITY, 0.0)
                key_pipe_ref[...] = _f32_key(score_pipe)
                cap_tot_ref[...] = jnp.where(
                    feasible, jnp.minimum(cap_tot_f, roomv), 0.0)

        # Phase 0 leaves the output blocks untouched; write zeros so the
        # inter-visit flush is deterministic (phase 1 overwrites).
        @pl.when(phase == 0)
        def _zero_outputs():
            key_now_ref[...] = jnp.zeros_like(key_now_ref)
            cap_now_ref[...] = jnp.zeros_like(cap_now_ref)
            if pipe_items:
                key_pipe_ref[...] = jnp.zeros_like(key_pipe_ref)
                cap_tot_ref[...] = jnp.zeros_like(cap_tot_ref)

    def node_block(cols):
        return pl.BlockSpec((tile, cols), lambda p, j: (j, 0))

    def bcast_block(cols):
        return pl.BlockSpec((1, cols), lambda p, j: (0, 0))

    in_specs = [bcast_block(r), bcast_block(L), bcast_block(tol.shape[0]),
                node_block(r), node_block(r)]
    args = [req[None, :].astype(jnp.float32),
            sel[None, :].astype(jnp.int32),
            tol[None, :].astype(jnp.int32),
            node_allocatable.astype(jnp.float32),
            idle.astype(jnp.float32)]
    if have_rel:
        in_specs.append(node_block(r))
        args.append(rel.astype(jnp.float32))
    in_specs += [node_block(L), node_block(tt), node_block(1)]
    args += [node_labels.astype(jnp.int32), node_taints.astype(jnp.int32),
             room.astype(jnp.float32)[:, None]]
    if have_extra:
        in_specs.append(node_block(1))
        args.append(extra_row.astype(jnp.float32)[:, None])
    if have_mask:
        in_specs.append(node_block(1))
        args.append(mask_row.astype(jnp.float32)[:, None])

    n_outs = 4 if pipe_items else 2
    out_shape = ([jax.ShapeDtypeStruct((n, 1), jnp.uint32),
                  jax.ShapeDtypeStruct((n, 1), jnp.float32)]
                 + ([jax.ShapeDtypeStruct((n, 1), jnp.uint32),
                     jax.ShapeDtypeStruct((n, 1), jnp.float32)]
                    if pipe_items else []))
    from jax.experimental.pallas import tpu as pltpu
    scratch = [pltpu.SMEM((2,), jnp.float32)]

    outs = pl.pallas_call(
        kernel,
        grid=(2, n_tiles),
        in_specs=in_specs,
        out_specs=[node_block(1)] * n_outs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)
    key_now = outs[0][:, 0]
    cap_now = outs[1][:, 0]
    key_pipe = outs[2][:, 0] if pipe_items else None
    cap_tot = outs[3][:, 0] if pipe_items else None
    return key_now, key_pipe, cap_now, cap_tot, 4, jnp.uint32


def pallas_available() -> bool:
    """Pallas TPU kernels need a real TPU backend (the CPU interpreter
    path works too, for tests)."""
    try:
        from jax.experimental import pallas  # noqa: F401
        return True
    except ImportError:  # pragma: no cover
        return False


def task_row_reference(req, sel, tol, node_idle, node_releasing,
                       node_labels, node_taints, node_room):
    """jnp reference for parity tests (mirrors feasibility_row + the
    grouped kernel's capacity computation)."""
    from .predicates import feasibility_row
    fit_now, fit_fut = feasibility_row(
        node_idle, node_releasing, node_labels, node_taints, node_room,
        req, sel, tol)
    safe_req = jnp.where(req > 0, req, 1.0)
    cap_now = jnp.min(jnp.where(req[None, :] > 0,
                                jnp.floor(node_idle / safe_req[None, :]),
                                jnp.inf), axis=1)
    cap_tot = jnp.min(jnp.where(
        req[None, :] > 0,
        jnp.floor((node_idle + node_releasing) / safe_req[None, :]),
        jnp.inf), axis=1)
    cap_now = jnp.where(fit_now, jnp.minimum(cap_now, node_room), 0.0)
    cap_tot = jnp.where(fit_fut, jnp.minimum(cap_tot, node_room), 0.0)
    return fit_now, fit_fut, cap_now, cap_tot
