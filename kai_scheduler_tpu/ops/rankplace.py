"""Rank-aware gang placement: rank -> node assignment WITHIN a gang.

Rank-Aware Resource Scheduling for Tightly-Coupled MPI Workloads on
Kubernetes (arxiv 2603.22691) measures whole-percentage job-runtime wins
from keeping consecutive MPI ranks topology-adjacent: rank r and rank
r+1 exchange the most traffic (halo exchanges, ring all-reduce), so the
mean "hop distance" between consecutive ranks' nodes is the latency the
collective actually pays.

The fill-plan kernels (ops/allocate_grouped.py) decide WHICH node slots
a gang occupies; this module decides WHICH RANK lands on which of those
slots.  Because the slot multiset is fixed, the assignment can never
change feasibility or capacity accounting — it is a pure permutation of
interchangeable tasks (the caller proves interchangeability; the
topology plugin re-checks it).

Algorithm: hierarchical-order assignment.  Nodes get a *topology rank*
— their position in the lexicographic order of their domain-id path
(top level first, node index last) — and the gang's slots are stably
sorted by it; ranks 0..T-1 then map to slots in that order.  For a tree
metric this is optimal: any ordering that keeps each subtree's slots
contiguous crosses every domain boundary exactly once, which is the
minimum number of crossings any rank sequence can achieve, and the hop
metric below counts exactly those crossings.  Determinism: the sort is
stable with the slot index as the final tie-break, so the same snapshot
produces the same assignment, bit for bit.

Two implementations, bit-identical (tests/test_rankplace.py sweeps
randomized instances under KAI_FAULT_SEED):

- ``rank_place_kernel``: one jitted dispatch — a stable ``lax.sort`` of
  (topology-rank, slot-index) pairs plus the per-level hop fold — the
  in-kernel scoring home the fused per-group-step ladder feeds;
- ``rank_place_np``: the host reference (``np.lexsort`` is the same
  stable sort), kept verbatim as the legacy rung for bit-parity A/B and
  as the small-gang fast path (a 4-wide gang is cheaper on host than a
  dispatch).

Hop metric: hop(a, b) = 0 for the same node, else 1 + the number of
topology levels whose domains differ (a missing label counts as
differing — an unlabeled node is adjacent to nothing).  Same rack = 1,
same block different rack = 2, different block = 3, and so on — the
tree distance in boundary crossings.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .topology import ROOT_LEVEL, TopologyTree

# Mode pin: "kernel" | "host" | "auto" (auto = kernel for gangs of at
# least _KERNEL_MIN_GANG slots, host below — both paths bit-identical,
# the threshold is purely a dispatch-overhead choice).
_MODE_ENV = "KAI_RANKPLACE"
_KERNEL_MIN_GANG = 32


@dataclass
class TopoOrder:
    """Per-snapshot topology ordering of the packed node axis.

    ``topo_rank[i]``: node i's position in the hierarchical DFS order
    (unlabeled nodes and padding rows sort last, in index order).
    ``level_segs``: [L, N_pad] int32 domain id per level (top level
    first), -1 where the node lacks the label chain — the hop metric's
    operand.  Both derive purely from the TopologyTree, so they are
    built once per session and reused across gangs.
    """
    topo_rank: np.ndarray          # [N_pad] int32
    level_segs: np.ndarray         # [L, N_pad] int32
    num_levels: int


def build_topo_order(tree: TopologyTree, n_pad: int) -> TopoOrder:
    """Topology ordering for one tree over the packed node axis."""
    n = tree.node_domain[ROOT_LEVEL].shape[0]
    levels = [lv for lv in tree.levels if lv in tree.node_domain]
    segs = np.full((max(len(levels), 1), n_pad), -1, np.int32)
    if not levels:
        segs = segs[:0]
    for li, lv in enumerate(levels):
        segs[li, :n] = tree.node_domain[lv]
    # Lexicographic hierarchical order: top level primary, deeper levels
    # refine, node index breaks ties (np.lexsort: LAST key is primary).
    # Unlabeled domains (-1) map past every real id so they sort last
    # within their prefix; padding rows sort after all real nodes.
    keys = []
    for li in range(len(levels) - 1, -1, -1):
        col = segs[li, :n]
        keys.append(np.where(col < 0, np.int64(2 ** 31 - 1),
                             col.astype(np.int64)))
    # lexsort is a composition of stable sorts: nodes sharing a full
    # domain path keep ascending index order without an explicit key.
    order = np.lexsort(tuple(keys)) if keys else np.arange(n)
    topo_rank = np.empty(n_pad, np.int32)
    topo_rank[order] = np.arange(n, dtype=np.int32)
    topo_rank[n:] = np.arange(n, n_pad, dtype=np.int32)
    return TopoOrder(topo_rank, segs, len(levels))


def _hops_np(nodes_by_rank: np.ndarray, level_segs: np.ndarray
             ) -> np.ndarray:
    """[T-1] hop distances between consecutive ranks' nodes."""
    a, b = nodes_by_rank[:-1], nodes_by_rank[1:]
    if a.size == 0:
        return np.zeros(0, np.int32)
    same = a == b
    if level_segs.shape[0] == 0:
        diff = np.zeros(a.shape[0], np.int32)
    else:
        sa, sb = level_segs[:, a], level_segs[:, b]
        diff = ((sa != sb) | (sa < 0) | (sb < 0)).sum(
            axis=0).astype(np.int32)
    return np.where(same, 0, 1 + diff).astype(np.int32)


def rank_place_np(slot_nodes: np.ndarray, topo_rank: np.ndarray,
                  level_segs: np.ndarray
                  ) -> tuple[np.ndarray, np.ndarray]:
    """Host reference (the legacy parity rung).

    ``slot_nodes``: [T] packed node index per gang slot.  Returns
    (perm [T] int32 — slot index for rank position k, hops [T-1] int32
    between consecutive ranks AFTER assignment).
    """
    t = slot_nodes.shape[0]
    perm = np.lexsort((np.arange(t), topo_rank[slot_nodes])).astype(
        np.int32)
    return perm, _hops_np(slot_nodes[perm], level_segs)


@jax.jit
def rank_place_kernel(slot_nodes, valid, topo_rank, level_segs):
    """One jitted dispatch: stable sort by (topology rank, slot index)
    plus the hop fold.  Formula-identical to ``rank_place_np`` — a
    stable single-key sort with the index as the value IS lexsort with
    the index tie-break.

    ``valid`` masks padding slots (the caller pads the gang axis to a
    pow2 bucket so fleets of varied gang sizes share compilations, the
    convention every hot-path kernel here follows): padding keys map
    past every real topology rank (< N_pad < 2^31), so the stable sort
    parks them after all real slots and the first ``sum(valid)`` rows
    of the output equal the unpadded result exactly."""
    t = slot_nodes.shape[0]
    key = jnp.where(valid, topo_rank[slot_nodes],
                    jnp.int32(2 ** 31 - 1))
    idx = jnp.arange(t, dtype=jnp.int32)
    _, perm = jax.lax.sort((key, idx), dimension=0, is_stable=True,
                           num_keys=1)
    nodes_sorted = slot_nodes[perm]
    a, b = nodes_sorted[:-1], nodes_sorted[1:]
    same = a == b
    if level_segs.shape[0] == 0:
        diff = jnp.zeros(a.shape, jnp.int32)
    else:
        sa, sb = level_segs[:, a], level_segs[:, b]
        diff = ((sa != sb) | (sa < 0) | (sb < 0)).sum(
            axis=0).astype(jnp.int32)
    hops = jnp.where(same, 0, 1 + diff).astype(jnp.int32)
    return perm, hops


def rank_place_padded(slot_nodes: np.ndarray, topo_rank, level_segs
                      ) -> tuple[np.ndarray, np.ndarray]:
    """Kernel rung with pow2 gang-axis bucketing: pads, dispatches,
    slices — returns exactly ``rank_place_np``'s (perm [T], hops
    [T-1]).  This is the thunk the plugin hands to dispatch_kernel."""
    t = slot_nodes.shape[0]
    t_pad = _KERNEL_MIN_GANG
    while t_pad < t:
        t_pad *= 2
    padded = np.zeros(t_pad, np.int32)
    padded[:t] = slot_nodes
    valid = np.zeros(t_pad, bool)
    valid[:t] = True
    perm, hops = rank_place_kernel(
        jnp.asarray(padded), jnp.asarray(valid),
        jnp.asarray(topo_rank), jnp.asarray(level_segs))
    return perm[:t], hops[:max(t - 1, 0)]


def resolve_mode(requested: str | None, gang_size: int) -> str:
    """kernel | host, honoring the KAI_RANKPLACE pin."""
    mode = (requested or os.environ.get(_MODE_ENV) or "auto").strip()
    if mode not in ("kernel", "host"):
        mode = "kernel" if gang_size >= _KERNEL_MIN_GANG else "host"
    return mode


def mean_hop(nodes_by_rank: np.ndarray, order: TopoOrder) -> float:
    """Measured mean consecutive-rank hop distance of one assignment —
    the scale-ring scenario's adjacency metric (and the number the
    rank-oblivious baseline is compared on)."""
    hops = _hops_np(np.asarray(nodes_by_rank), order.level_segs)
    return float(hops.mean()) if hops.size else 0.0
