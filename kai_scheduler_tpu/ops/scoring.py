"""Node scoring: the tasks × nodes score matrix as one fused computation.

Replaces the reference's goroutine-per-node NodeOrderFn fan-out
(pkg/scheduler/framework/session.go:234-265 OrderedNodesByTask) with a dense
[T, N] score tensor.  Score terms and their magnitudes mirror
pkg/scheduler/plugins/scores/scores.go so plugin precedence is preserved:

  binpack/spread         <= 9       (MaxHighDensity, nodeplacement/pack.go:46)
  resourcetype           10         (resourcetype/resource_type.go)
  availability           100        (nodeavailability/nodeavailability.go:31)
  gpu sharing            1000
  topology               10000
  k8s plugin scores      100000
  nominated node         1000000

Terms sum; the allocator picks argmax over feasible nodes (ties -> lowest
node index, matching the deterministic first-best iteration order).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..api.resources import RES_CPU, RES_GPU

MAX_HIGH_DENSITY = 9.0
RESOURCE_TYPE = 10.0
AVAILABILITY = 100.0
GPU_SHARING = 1000.0
TOPOLOGY = 10000.0
K8S_PLUGINS = 100000.0
NOMINATED_NODE = 1000000.0

BINPACK = 0
SPREAD = 1


@functools.partial(jax.jit, static_argnames=("gpu_strategy", "cpu_strategy"))
def placement_scores(node_allocatable, node_idle, task_req, fit_mask,
                     gpu_strategy: int = BINPACK,
                     cpu_strategy: int = BINPACK):
    """Bin-pack / spread score per task x node (nodeplacement plugin).

    Bin-pack (pack.go:46-66): over the task's *fitting* nodes that have the
    job's dominant resource, scale free amount to [0, MaxHighDensity], higher
    score for fuller nodes.  Spread (spread.go:16-37): free/capacity.  The
    strategy applies per job resource type: GPU jobs score on the GPU axis,
    CPU-only jobs on the CPU axis.
    """
    is_gpu_job = task_req[:, RES_GPU] > 0.0  # [T]

    def axis_scores(res: int, strategy: int):
        free = node_idle[:, res]            # [N]
        cap = node_allocatable[:, res]      # [N]
        has_res = cap > 0.0
        valid = fit_mask & has_res[None, :]          # [T,N]
        if strategy == SPREAD:
            return jnp.where(has_res, free / jnp.where(has_res, cap, 1.0),
                             0.0)[None, :] * jnp.ones(
                                 (task_req.shape[0], 1))
        big = jnp.inf
        min_free = jnp.min(jnp.where(valid, free[None, :], big), axis=1)
        max_free = jnp.max(jnp.where(valid, free[None, :], -big), axis=1)
        span = max_free - min_free
        flat = span <= 0.0  # all fitting nodes equal -> everyone max score
        score = MAX_HIGH_DENSITY * (
            1.0 - (free[None, :] - min_free[:, None])
            / jnp.where(flat, 1.0, span)[:, None])
        score = jnp.where(flat[:, None], MAX_HIGH_DENSITY, score)
        return jnp.where(has_res[None, :], score, 0.0)

    gpu_scores = axis_scores(RES_GPU, gpu_strategy)
    cpu_scores = axis_scores(RES_CPU, cpu_strategy)
    return jnp.where(is_gpu_job[:, None], gpu_scores, cpu_scores)


@jax.jit
def resource_type_scores(node_allocatable, task_req):
    """CPU-only jobs prefer CPU-only nodes; GPU jobs prefer GPU nodes
    (resourcetype plugin).  [T,N]."""
    node_has_gpu = node_allocatable[:, RES_GPU] > 0.0   # [N]
    is_gpu_job = task_req[:, RES_GPU] > 0.0             # [T]
    match = jnp.where(is_gpu_job[:, None], node_has_gpu[None, :],
                      ~node_has_gpu[None, :])
    return jnp.where(match, RESOURCE_TYPE, 0.0)


@jax.jit
def availability_scores(fit_now):
    """Nodes that can host the task right now beat pipelining candidates
    (nodeavailability plugin).  [T,N]."""
    return jnp.where(fit_now, AVAILABILITY, 0.0)


@jax.jit
def nominated_scores(task_nominated_node, num_nodes):
    """Sticky boost for a previously nominated node (nominatednode plugin).
    task_nominated_node: [T] int32 node index or -1."""
    idx = jnp.arange(num_nodes)[None, :]
    return jnp.where(task_nominated_node[:, None] == idx, NOMINATED_NODE, 0.0)


@functools.partial(jax.jit, static_argnames=("gpu_strategy", "cpu_strategy"))
def score_matrix(node_allocatable, node_idle, task_req, fit_now, fit_future,
                 topology_scores=None, task_nominated_node=None,
                 gpu_strategy: int = BINPACK, cpu_strategy: int = BINPACK):
    """Composed [T,N] score: the device-side analog of summing every
    registered NodeOrderFn (framework/session_plugins.go dispatchers)."""
    score = placement_scores(node_allocatable, node_idle, task_req,
                             fit_now | fit_future,
                             gpu_strategy=gpu_strategy,
                             cpu_strategy=cpu_strategy)
    score = score + resource_type_scores(node_allocatable, task_req)
    score = score + availability_scores(fit_now)
    if topology_scores is not None:
        score = score + topology_scores
    if task_nominated_node is not None:
        score = score + nominated_scores(task_nominated_node,
                                         node_allocatable.shape[0])
    return score
