"""Node scoring: the tasks × nodes score matrix as one fused computation.

Replaces the reference's goroutine-per-node NodeOrderFn fan-out
(pkg/scheduler/framework/session.go:234-265 OrderedNodesByTask) with a dense
[T, N] score tensor.  Score terms and their magnitudes mirror
pkg/scheduler/plugins/scores/scores.go so plugin precedence is preserved:

  binpack/spread         <= 9       (MaxHighDensity, nodeplacement/pack.go:46)
  resourcetype           10         (resourcetype plugin)
  availability           100        (nodeavailability/nodeavailability.go:31)
  gpu sharing            1000
  topology               10000
  k8s plugin scores      100000
  nominated node         1000000

``score_row`` is the canonical single-task implementation; the gang
allocation kernel steps it per task against mutating node state, and the
batch [T, N] form is its vmap — one definition, no drift between the gang
path and the fractional host path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..api.resources import RES_CPU, RES_GPU

MAX_HIGH_DENSITY = 9.0
RESOURCE_TYPE = 10.0
AVAILABILITY = 100.0
GPU_SHARING = 1000.0
TOPOLOGY = 10000.0
K8S_PLUGINS = 100000.0
NOMINATED_NODE = 1000000.0

BINPACK = 0
SPREAD = 1


def score_row(allocatable, idle, req, fit_any, fit_now,
              gpu_strategy: int, cpu_strategy: int, minmax=None):
    """One task's [N] score: binpack/spread (per the job's dominant resource
    type) + resourcetype match + availability boost.

    Bin-pack (pack.go:46-66): over the task's *fitting* nodes that have the
    resource, scale free amount to [0, MaxHighDensity], higher score for
    fuller nodes; all-equal -> everyone gets the max.  Spread
    (spread.go:16-37): free/capacity.

    ``minmax``: optional [2,R] (min_free, max_free) over the task's valid
    nodes — the multi-chip kernel passes collective-reduced global values so
    each node shard scores against the same scale (parallel/sharded.py).
    """
    is_gpu_job = req[RES_GPU] > 0.0

    def axis_score(res, strategy):
        free = idle[:, res]
        cap = allocatable[:, res]
        has_res = cap > 0.0
        if strategy == SPREAD:
            return jnp.where(has_res, free / jnp.where(has_res, cap, 1.0),
                             0.0)
        if minmax is not None:
            min_free, max_free = minmax[0, res], minmax[1, res]
        else:
            valid = fit_any & has_res
            min_free = jnp.min(jnp.where(valid, free, jnp.inf))
            max_free = jnp.max(jnp.where(valid, free, -jnp.inf))
        span = max_free - min_free
        flat = span <= 0.0
        score = MAX_HIGH_DENSITY * (
            1.0 - (free - min_free) / jnp.where(flat, 1.0, span))
        score = jnp.where(flat, MAX_HIGH_DENSITY, score)
        return jnp.where(has_res, score, 0.0)

    placement = jnp.where(is_gpu_job,
                          axis_score(RES_GPU, gpu_strategy),
                          axis_score(RES_CPU, cpu_strategy))
    node_has_gpu = allocatable[:, RES_GPU] > 0.0
    rtype = jnp.where(jnp.where(is_gpu_job, node_has_gpu, ~node_has_gpu),
                      RESOURCE_TYPE, 0.0)
    avail = jnp.where(fit_now, AVAILABILITY, 0.0)
    return placement + rtype + avail


def score_row_selected(allocatable, idle, req, fit_any, fit_now,
                       gpu_strategy: int, cpu_strategy: int, minmax=None):
    """Value-identical reformulation of ``score_row`` that SELECTS the
    scored resource column first (one [N] where) and runs the
    binpack/spread arithmetic once, instead of evaluating both the GPU
    and the CPU axis and where-merging at the end — ``is_gpu_job`` is a
    traced scalar, so the two-branch form pays for both axes on every
    scan step.

    Exactness: every step (masked min/max, span, the scaled-density
    formula) is elementwise or an exact reduction over the selected
    column, so selecting before computing equals computing both branches
    and selecting after.  Only valid when both strategies agree (the
    strategy choice is static Python); the caller falls back to
    ``score_row`` otherwise.
    """
    assert gpu_strategy == cpu_strategy, \
        "column-selected scoring needs one strategy for both axes"
    strategy = gpu_strategy
    is_gpu_job = req[RES_GPU] > 0.0
    free = jnp.where(is_gpu_job, idle[:, RES_GPU], idle[:, RES_CPU])
    cap = jnp.where(is_gpu_job, allocatable[:, RES_GPU],
                    allocatable[:, RES_CPU])
    has_res = cap > 0.0
    if strategy == SPREAD:
        placement = jnp.where(has_res,
                              free / jnp.where(has_res, cap, 1.0), 0.0)
    else:
        if minmax is not None:
            min_free = jnp.where(is_gpu_job, minmax[0, RES_GPU],
                                 minmax[0, RES_CPU])
            max_free = jnp.where(is_gpu_job, minmax[1, RES_GPU],
                                 minmax[1, RES_CPU])
        else:
            valid = fit_any & has_res
            min_free = jnp.min(jnp.where(valid, free, jnp.inf))
            max_free = jnp.max(jnp.where(valid, free, -jnp.inf))
        span = max_free - min_free
        flat = span <= 0.0
        placement = MAX_HIGH_DENSITY * (
            1.0 - (free - min_free) / jnp.where(flat, 1.0, span))
        placement = jnp.where(flat, MAX_HIGH_DENSITY, placement)
        placement = jnp.where(has_res, placement, 0.0)
    node_has_gpu = allocatable[:, RES_GPU] > 0.0
    rtype = jnp.where(jnp.where(is_gpu_job, node_has_gpu, ~node_has_gpu),
                      RESOURCE_TYPE, 0.0)
    avail = jnp.where(fit_now, AVAILABILITY, 0.0)
    return placement + rtype + avail


@functools.partial(jax.jit, static_argnames=("gpu_strategy", "cpu_strategy"))
def placement_scores(node_allocatable, node_idle, task_req, fit_mask,
                     gpu_strategy: int = BINPACK,
                     cpu_strategy: int = BINPACK):
    """[T,N] binpack/spread-only term (no rtype/availability): vmap of the
    placement part of score_row."""
    full = jax.vmap(lambda req, fit: score_row(
        node_allocatable, node_idle, req, fit, jnp.zeros_like(fit),
        gpu_strategy, cpu_strategy))(task_req, fit_mask)
    rtype = resource_type_scores(node_allocatable, task_req)
    return full - rtype


@jax.jit
def resource_type_scores(node_allocatable, task_req):
    """CPU-only jobs prefer CPU-only nodes; GPU jobs prefer GPU nodes
    (resourcetype plugin).  [T,N]."""
    node_has_gpu = node_allocatable[:, RES_GPU] > 0.0   # [N]
    is_gpu_job = task_req[:, RES_GPU] > 0.0             # [T]
    match = jnp.where(is_gpu_job[:, None], node_has_gpu[None, :],
                      ~node_has_gpu[None, :])
    return jnp.where(match, RESOURCE_TYPE, 0.0)


@jax.jit
def availability_scores(fit_now):
    """Nodes that can host the task right now beat pipelining candidates
    (nodeavailability plugin).  [T,N]."""
    return jnp.where(fit_now, AVAILABILITY, 0.0)


@jax.jit
def nominated_scores(task_nominated_node, num_nodes):
    """Sticky boost for a previously nominated node (nominatednode plugin).
    task_nominated_node: [T] int32 node index or -1."""
    idx = jnp.arange(num_nodes)[None, :]
    return jnp.where(task_nominated_node[:, None] == idx, NOMINATED_NODE, 0.0)


@functools.partial(jax.jit, static_argnames=("gpu_strategy", "cpu_strategy"))
def score_matrix(node_allocatable, node_idle, task_req, fit_now, fit_future,
                 topology_scores=None, task_nominated_node=None,
                 gpu_strategy: int = BINPACK, cpu_strategy: int = BINPACK):
    """Composed [T,N] score: vmap of score_row plus optional extra terms —
    the device-side analog of summing every registered NodeOrderFn
    (framework/session_plugins.go dispatchers)."""
    score = jax.vmap(lambda req, fa, fn: score_row(
        node_allocatable, node_idle, req, fa, fn,
        gpu_strategy, cpu_strategy))(task_req, fit_now | fit_future, fit_now)
    if topology_scores is not None:
        score = score + topology_scores
    if task_nominated_node is not None:
        score = score + nominated_scores(task_nominated_node,
                                         node_allocatable.shape[0])
    return score
