"""Hierarchical DRF fair-share division.

Re-implements the behavior of the reference's proportion plugin division
algorithm (pkg/scheduler/plugins/proportion/resource_division/
resource_division.go:26-357 and proportion.go:403-440):

1. *Deserved phase*: every queue first receives min(deserved, requestable)
   (UNLIMITED deserved counts as the whole pool).
2. *Over-quota phase*: the remainder is divided within priority bands
   (higher priority first).  Within a band, repeated proportional rounds by
   usage-penalized over-quota weight ``w' = max(0, W' + k*(W' - U'))``
   (:245), each grant floored to whole units (:292); fractional remainders
   are then distributed one unit at a time, largest remainder first (:264).
3. *Hierarchy*: each parent's fair share becomes the pool divided among its
   children (proportion.go:410-425).

Two implementations, property-tested against each other:
- ``set_resources_share_np``: sequential numpy reference, one queue group.
- ``fair_share_levels``: jitted JAX kernel.  Queue groups (siblings under one
  parent) become segment ids so every level of the hierarchy is one
  vectorized division over all groups at once; priority bands are a static
  unroll; the round loop is a ``lax.while_loop`` fixed point.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

UNLIMITED = -1.0
EPS = 1e-9
# Fractional remainders are quantized before largest-remainder ranking so
# that float-accumulation noise can't flip near-ties between the sequential
# reference and the vectorized kernel (the tiebreak rank then decides).
FRAC_DECIMALS = 9


# ---------------------------------------------------------------------------
# numpy reference (single group of sibling queues, all resources)
# ---------------------------------------------------------------------------

def _requestable(request, limit):
    return np.where(limit == UNLIMITED, request, np.minimum(limit, request))


def set_resources_share_np(total: np.ndarray, k_value: float,
                           deserved: np.ndarray, limit: np.ndarray,
                           over_quota_weight: np.ndarray,
                           request: np.ndarray, usage: np.ndarray,
                           priority: np.ndarray,
                           tiebreak_rank: np.ndarray | None = None
                           ) -> np.ndarray:
    """Sequential reference for one sibling group.

    Shapes: total [R]; per-queue arrays [Q,R] except priority [Q].
    Returns fair_share [Q,R].
    """
    q, r = deserved.shape
    if tiebreak_rank is None:
        tiebreak_rank = np.arange(q)
    fair = np.zeros((q, r))
    for res in range(r):
        fair[:, res] = _set_resource_share_np(
            float(total[res]), k_value, deserved[:, res], limit[:, res],
            over_quota_weight[:, res], request[:, res], usage[:, res],
            priority, tiebreak_rank)
    return fair


def _set_resource_share_np(total, k, deserved, limit, oqw, request, usage,
                           priority, tiebreak_rank):
    q = deserved.shape[0]
    requestable = _requestable(request, limit)
    # Phase 1: deserved-first (resource_division.go:92-109).
    eff_deserved = np.where(deserved == UNLIMITED, total, deserved)
    fair = np.minimum(eff_deserved, requestable)
    remaining = total - fair.sum()
    if remaining <= 0:
        return fair

    # Phase 2: over-quota by priority band (:111-144).
    bands = sorted(set(priority.tolist()), reverse=True)
    rem_frac = {b: np.zeros(q) for b in bands}  # remainder map per band
    for band in bands:
        in_band = priority == band
        while True:
            unsat = in_band & (requestable - fair > EPS)
            tw = oqw[unsat].sum()
            if tw <= 0:
                break
            n_w = np.where(unsat, oqw / tw, 0.0)
            share_w = np.where(unsat, np.maximum(0.0, n_w + k * (n_w - usage)),
                               0.0)
            sw = share_w.sum()
            if sw <= 0:
                break
            amount_this_round = remaining
            another_round = False
            for i in range(q):
                if not unsat[i] or oqw[i] == 0:
                    continue
                fair_i = amount_this_round * share_w[i] / sw
                rem_req = requestable[i] - fair[i]
                if rem_req <= fair_i:
                    give = rem_req
                    rem_frac[band][i] = 0.0
                else:
                    give = np.floor(fair_i)
                    rem_frac[band][i] = fair_i - give
                if give > 0:
                    fair[i] += give
                    remaining -= give
                another_round = another_round or rem_req < fair_i
            if not another_round or remaining <= EPS:
                break
        if remaining <= EPS:
            break

    # Phase 3: largest-remainder units, priority band order (:126-141,264-281).
    for band in bands:
        if remaining <= EPS:
            break
        entries = [(i, round(rem_frac[band][i], FRAC_DECIMALS))
                   for i in range(q) if rem_frac[band][i] > 0]
        entries.sort(key=lambda e: (-e[1], tiebreak_rank[e[0]]))
        for i, _ in entries:
            if remaining <= EPS:
                break
            give = min(1.0, remaining)
            fair[i] += give
            remaining -= give
    return fair


# ---------------------------------------------------------------------------
# JAX kernel: segment (multi-group) division, one hierarchy level
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class LevelSpec:
    """Static structure of one hierarchy level (trace-time constants)."""
    num_groups: int
    num_bands: int
    max_rounds: int = 64


def _segment_sum(x, seg, num_groups):
    return jax.ops.segment_sum(x, seg, num_segments=num_groups)


@functools.partial(jax.jit, static_argnames=("spec",))
def divide_groups_jax(spec: LevelSpec, group_total, group_of_queue,
                      band_of_queue, deserved, limit, oqw, request, usage,
                      tiebreak_rank, k_value):
    """One level of fair-share: divide each group's total among its queues.

    Shapes: group_total [G,R]; group_of_queue/band_of_queue/tiebreak [Q];
    per-queue arrays [Q,R].  Returns fair [Q,R].

    Vectorization of the sequential reference: all sums become segment sums
    over the group axis, priority bands unroll statically, and the
    proportional rounds run as a while_loop until no group/resource wants
    another round.  Order-independence of each round (grants are computed
    from round-start state) makes this exactly equal to the sequential
    algorithm.
    """
    G, Q = spec.num_groups, group_of_queue.shape[0]
    R = deserved.shape[1]
    seg = group_of_queue

    requestable = jnp.where(limit == UNLIMITED, request,
                            jnp.minimum(limit, request))
    my_total = group_total[seg]  # [Q,R]
    eff_deserved = jnp.where(deserved == UNLIMITED, my_total, deserved)
    fair0 = jnp.minimum(eff_deserved, requestable)
    remaining0 = jnp.maximum(group_total - _segment_sum(fair0, seg, G), 0.0)

    def run_band(band, fair, remaining, rem_frac_all):
        in_band = (band_of_queue == band)[:, None]  # [Q,1]

        def cond(carry):
            fair, remaining, rem_frac, go, i = carry
            return go & (i < spec.max_rounds)

        def body(carry):
            fair, remaining, rem_frac, _, i = carry
            unsat = in_band & (requestable - fair > EPS)
            tw = _segment_sum(jnp.where(unsat, oqw, 0.0), seg, G)  # [G,R]
            n_w = jnp.where(unsat & (tw[seg] > 0), oqw / jnp.where(
                tw[seg] > 0, tw[seg], 1.0), 0.0)
            share_w = jnp.where(unsat,
                                jnp.maximum(0.0, n_w + k_value * (n_w - usage)),
                                0.0)
            sw = _segment_sum(share_w, seg, G)  # [G,R]
            active = unsat & (share_w > 0) & (sw[seg] > 0)
            fair_q = jnp.where(active,
                               remaining[seg] * share_w
                               / jnp.where(sw[seg] > 0, sw[seg], 1.0), 0.0)
            rem_req = requestable - fair
            satisfied_now = rem_req <= fair_q
            give = jnp.where(active,
                             jnp.where(satisfied_now, rem_req,
                                       jnp.floor(fair_q)), 0.0)
            new_frac = jnp.where(active,
                                 jnp.where(satisfied_now, 0.0,
                                           fair_q - jnp.floor(fair_q)),
                                 rem_frac)
            fair = fair + give
            remaining = jnp.maximum(
                remaining - _segment_sum(give, seg, G), 0.0)
            another = (active & (rem_req < fair_q)) & (remaining[seg] > EPS)
            go = jnp.any(another)
            return fair, remaining, new_frac, go, i + 1

        fair, remaining, rem_frac, _, _ = jax.lax.while_loop(
            cond, body,
            (fair, remaining, rem_frac_all, jnp.array(True), jnp.array(0)))
        return fair, remaining, rem_frac

    # Static unroll over priority bands (band ids are dense 0..num_bands-1,
    # 0 = highest priority — computed by the host-side prep).
    rem_fracs = []
    fair, remaining = fair0, remaining0
    for band in range(spec.num_bands):
        fair, remaining, rem_frac = run_band(
            band, fair, remaining, jnp.zeros_like(fair0))
        rem_fracs.append(rem_frac)

    # Largest-remainder unit distribution, per band, per group, per resource.
    def distribute(fair, remaining, rem_frac):
        # rank within (group, resource) by (-frac, tiebreak); non-members
        # (frac == 0) sort last and receive nothing.
        member = rem_frac > 0.0  # [Q,R]

        def per_resource(fair_r, remaining_r, frac_r, member_r):
            frac_r = jnp.round(frac_r, FRAC_DECIMALS)
            # Sort by group, then -frac, then tiebreak.
            order = jnp.lexsort((tiebreak_rank, -frac_r,
                                 jnp.where(member_r, 0, 1), seg))
            sorted_seg = seg[order]
            pos = jnp.arange(Q)
            # Rank within group = position - first position of the group.
            is_start = jnp.concatenate([
                jnp.array([True]), sorted_seg[1:] != sorted_seg[:-1]])
            group_start = jnp.where(is_start, pos, 0)
            group_start = jax.lax.associative_scan(jnp.maximum, group_start)
            rank_sorted = pos - group_start
            rank = jnp.zeros(Q, jnp.int32).at[order].set(
                rank_sorted.astype(jnp.int32))
            amount = jnp.where(
                member_r,
                jnp.clip(remaining_r[seg] - rank.astype(fair_r.dtype),
                         0.0, 1.0),
                0.0)
            fair_r = fair_r + amount
            remaining_r = jnp.maximum(
                remaining_r - _segment_sum(amount, seg, G), 0.0)
            return fair_r, remaining_r

        outs = [per_resource(fair[:, r], remaining[:, r], rem_frac[:, r],
                             member[:, r]) for r in range(R)]
        fair = jnp.stack([o[0] for o in outs], axis=1)
        remaining = jnp.stack([o[1] for o in outs], axis=1)
        return fair, remaining

    for band in range(spec.num_bands):
        fair, remaining = distribute(fair, remaining, rem_fracs[band])
    return fair


# ---------------------------------------------------------------------------
# Hierarchy orchestration (host-side prep + per-level kernel calls)
# ---------------------------------------------------------------------------

@dataclass
class QueueHierarchy:
    """Host-side prep of the queue forest for the level-by-level kernel."""
    levels: list            # list of np.ndarray of queue indices per depth
    parent: np.ndarray      # [Q] int, -1 for roots
    band_of_queue: np.ndarray   # [Q] dense band index per level (global bands)
    num_bands: int
    tiebreak_rank: np.ndarray   # [Q]

    @classmethod
    def build(cls, parent: np.ndarray, priority: np.ndarray,
              creation: np.ndarray, uids: list[str] | None = None
              ) -> "QueueHierarchy":
        q = parent.shape[0]
        depth = np.zeros(q, np.int32)
        for i in range(q):
            d, p = 0, parent[i]
            while p >= 0:
                d += 1
                p = parent[p]
            depth[i] = d
        levels = [np.where(depth == d)[0]
                  for d in range(int(depth.max()) + 1 if q else 0)]
        # Dense band ids: 0 = highest priority.
        uniq = np.unique(priority)[::-1]
        band = np.searchsorted(-uniq, -priority)
        order = sorted(range(q), key=lambda i: (creation[i],
                                                uids[i] if uids else str(i)))
        rank = np.zeros(q, np.int64)
        for r_, i in enumerate(order):
            rank[i] = r_
        return cls(levels, parent.astype(np.int64), band.astype(np.int32),
                   len(uniq) if q else 1, rank)


def fair_share_levels(total: np.ndarray, k_value: float,
                      hierarchy: QueueHierarchy,
                      deserved: np.ndarray, limit: np.ndarray,
                      oqw: np.ndarray, request: np.ndarray,
                      usage: np.ndarray) -> np.ndarray:
    """Full hierarchical fair share: one kernel call per depth level.

    ``request`` must already be rolled up the parent chain (roll_up_requests).
    Returns fair share [Q,R] for every queue, leaf and interior alike.
    """
    q, r = deserved.shape
    fair = np.zeros((q, r))
    if q == 0:
        return fair
    for depth, idxs in enumerate(hierarchy.levels):
        if len(idxs) == 0:
            continue
        if depth == 0:
            group_of = np.zeros(len(idxs), np.int32)
            group_totals = total[None, :]
        else:
            parents = hierarchy.parent[idxs]
            uniq_parents, group_of = np.unique(parents, return_inverse=True)
            group_totals = fair[uniq_parents]
        spec = LevelSpec(num_groups=group_totals.shape[0],
                         num_bands=hierarchy.num_bands)
        out = divide_groups_jax(
            spec, jnp.asarray(group_totals), jnp.asarray(group_of),
            jnp.asarray(hierarchy.band_of_queue[idxs]),
            jnp.asarray(deserved[idxs]), jnp.asarray(limit[idxs]),
            jnp.asarray(oqw[idxs]), jnp.asarray(request[idxs]),
            jnp.asarray(usage[idxs]),
            jnp.asarray(hierarchy.tiebreak_rank[idxs]),
            k_value)
        fair[idxs] = np.asarray(out)
    return fair


def roll_up_requests(parent: np.ndarray, leaf_values: np.ndarray
                     ) -> np.ndarray:
    """Aggregate per-leaf quantities up the parent chain
    (proportion.go:378-401: Request/Allocated accumulate on every ancestor)."""
    q = parent.shape[0]
    # Deepest-first so each child's (already complete) subtotal flows up.
    accum = leaf_values.copy()
    for i in sorted(range(q), key=lambda i: -_depth_of(parent, i)):
        p = parent[i]
        if p >= 0:
            accum[p] += accum[i]
    return accum


def _depth_of(parent: np.ndarray, i: int) -> int:
    d, p = 0, parent[i]
    while p >= 0:
        d += 1
        p = parent[p]
    return d


# ---------------------------------------------------------------------------
# DRF dominant share (queue_resource_share.go:142-162)
# ---------------------------------------------------------------------------

NO_FAIR_SHARE_DRF_MULTIPLIER = 1000.0


def dominant_share(allocated: np.ndarray, allocatable: np.ndarray,
                   total: np.ndarray) -> np.ndarray:
    """max over resources of allocated/allocatable; zero allocatable with
    allocation gets the penalty multiplier.  [Q,R],[Q,R],[R] -> [Q]."""
    xp = jnp if isinstance(allocated, jnp.ndarray) else np
    alloc_share = xp.where(allocatable == UNLIMITED,
                           xp.broadcast_to(total, allocated.shape),
                           allocatable)
    value = xp.where(alloc_share > 0, allocated / xp.where(
        alloc_share > 0, alloc_share, 1.0),
        allocated * NO_FAIR_SHARE_DRF_MULTIPLIER)
    return value.max(axis=1)


def allocatable_share(deserved: np.ndarray, fair: np.ndarray,
                      limit: np.ndarray) -> np.ndarray:
    """GetAllocatableShare (resource_share.go:52-62): max(deserved, fair)
    capped at limit; UNLIMITED deserved -> limit."""
    xp = jnp if isinstance(deserved, jnp.ndarray) else np
    base = xp.maximum(deserved, fair)
    capped = xp.where(limit == UNLIMITED, base, xp.minimum(limit, base))
    return xp.where(deserved == UNLIMITED, limit, capped)
