"""Hierarchical DRF fair-share division.

Re-implements the behavior of the reference's proportion plugin division
algorithm (pkg/scheduler/plugins/proportion/resource_division/
resource_division.go:26-357 and proportion.go:403-440):

1. *Deserved phase*: every queue first receives min(deserved, requestable)
   (UNLIMITED deserved counts as the whole pool).
2. *Over-quota phase*: the remainder is divided within priority bands
   (higher priority first).  Within a band, repeated proportional rounds by
   usage-penalized over-quota weight ``w' = max(0, W' + k*(W' - U'))``
   (:245), each grant floored to whole units (:292); fractional remainders
   are then distributed one unit at a time, largest remainder first (:264).
3. *Hierarchy*: each parent's fair share becomes the pool divided among its
   children (proportion.go:410-425).

Three implementations, property-tested against each other:
- ``set_resources_share_np``: sequential numpy reference, one queue group.
- ``fair_share_levels``: jitted JAX kernel, ONE DISPATCH PER LEVEL.  Queue
  groups (siblings under one parent) become segment ids so every level of
  the hierarchy is one vectorized division over all groups at once;
  priority bands are a static unroll; the round loop is a
  ``lax.while_loop`` fixed point.
- ``fair_share_forest``: the whole forest as ONE jitted dispatch
  (docs/DESIGN.md §2b).  Levels pack into a dense ``[L, Qmax]`` layout
  (global queue indices, -1 padding), sibling groups stay segment ids with
  one shared padding dump group, priority bands fold into a
  ``lax.fori_loop``, and the level recursion (parent fair share feeds the
  children's pool) unrolls statically inside the single jit.  The host
  prep (``prepared_forest``) is cached across cycles keyed on the queue
  set + weights, so a steady 10k-queue cluster pays one dispatch and
  O(hash) host work per cycle.
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

UNLIMITED = -1.0
EPS = 1e-9
# Fractional remainders are quantized before largest-remainder ranking so
# that float-accumulation noise can't flip near-ties between the sequential
# reference and the vectorized kernel (the tiebreak rank then decides).
FRAC_DECIMALS = 9


# ---------------------------------------------------------------------------
# numpy reference (single group of sibling queues, all resources)
# ---------------------------------------------------------------------------

def _requestable(request, limit):
    return np.where(limit == UNLIMITED, request, np.minimum(limit, request))


def set_resources_share_np(total: np.ndarray, k_value: float,
                           deserved: np.ndarray, limit: np.ndarray,
                           over_quota_weight: np.ndarray,
                           request: np.ndarray, usage: np.ndarray,
                           priority: np.ndarray,
                           tiebreak_rank: np.ndarray | None = None
                           ) -> np.ndarray:
    """Sequential reference for one sibling group.

    Shapes: total [R]; per-queue arrays [Q,R] except priority [Q].
    Returns fair_share [Q,R].
    """
    q, r = deserved.shape
    if tiebreak_rank is None:
        tiebreak_rank = np.arange(q)
    fair = np.zeros((q, r))
    for res in range(r):
        fair[:, res] = _set_resource_share_np(
            float(total[res]), k_value, deserved[:, res], limit[:, res],
            over_quota_weight[:, res], request[:, res], usage[:, res],
            priority, tiebreak_rank)
    return fair


def _set_resource_share_np(total, k, deserved, limit, oqw, request, usage,
                           priority, tiebreak_rank):
    q = deserved.shape[0]
    requestable = _requestable(request, limit)
    # Phase 1: deserved-first (resource_division.go:92-109).
    eff_deserved = np.where(deserved == UNLIMITED, total, deserved)
    fair = np.minimum(eff_deserved, requestable)
    remaining = total - fair.sum()
    if remaining <= 0:
        return fair

    # Phase 2: over-quota by priority band (:111-144).
    bands = sorted(set(priority.tolist()), reverse=True)
    rem_frac = {b: np.zeros(q) for b in bands}  # remainder map per band
    for band in bands:
        in_band = priority == band
        while True:
            unsat = in_band & (requestable - fair > EPS)
            tw = oqw[unsat].sum()
            if tw <= 0:
                break
            n_w = np.where(unsat, oqw / tw, 0.0)
            share_w = np.where(unsat, np.maximum(0.0, n_w + k * (n_w - usage)),
                               0.0)
            sw = share_w.sum()
            if sw <= 0:
                break
            amount_this_round = remaining
            another_round = False
            for i in range(q):
                if not unsat[i] or oqw[i] == 0:
                    continue
                fair_i = amount_this_round * share_w[i] / sw
                rem_req = requestable[i] - fair[i]
                if rem_req <= fair_i:
                    give = rem_req
                    rem_frac[band][i] = 0.0
                else:
                    give = np.floor(fair_i)
                    rem_frac[band][i] = fair_i - give
                if give > 0:
                    fair[i] += give
                    remaining -= give
                another_round = another_round or rem_req < fair_i
            if not another_round or remaining <= EPS:
                break
        if remaining <= EPS:
            break

    # Phase 3: largest-remainder units, priority band order (:126-141,264-281).
    for band in bands:
        if remaining <= EPS:
            break
        entries = [(i, round(rem_frac[band][i], FRAC_DECIMALS))
                   for i in range(q) if rem_frac[band][i] > 0]
        entries.sort(key=lambda e: (-e[1], tiebreak_rank[e[0]]))
        for i, _ in entries:
            if remaining <= EPS:
                break
            give = min(1.0, remaining)
            fair[i] += give
            remaining -= give
    return fair


# ---------------------------------------------------------------------------
# JAX kernel: segment (multi-group) division, one hierarchy level
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class LevelSpec:
    """Static structure of one hierarchy level (trace-time constants)."""
    num_groups: int
    num_bands: int
    max_rounds: int = 64


def _segment_sum(x, seg, num_groups):
    return jax.ops.segment_sum(x, seg, num_segments=num_groups)


@functools.partial(jax.jit, static_argnames=("spec",))
def divide_groups_jax(spec: LevelSpec, group_total, group_of_queue,
                      band_of_queue, deserved, limit, oqw, request, usage,
                      tiebreak_rank, k_value):
    """One level of fair-share: divide each group's total among its queues.

    Shapes: group_total [G,R]; group_of_queue/band_of_queue/tiebreak [Q];
    per-queue arrays [Q,R].  Returns fair [Q,R].

    Vectorization of the sequential reference: all sums become segment sums
    over the group axis, priority bands unroll statically, and the
    proportional rounds run as a while_loop until no group/resource wants
    another round.  Order-independence of each round (grants are computed
    from round-start state) makes this exactly equal to the sequential
    algorithm.
    """
    G, Q = spec.num_groups, group_of_queue.shape[0]
    R = deserved.shape[1]
    seg = group_of_queue

    requestable = jnp.where(limit == UNLIMITED, request,
                            jnp.minimum(limit, request))
    my_total = group_total[seg]  # [Q,R]
    eff_deserved = jnp.where(deserved == UNLIMITED, my_total, deserved)
    fair0 = jnp.minimum(eff_deserved, requestable)
    remaining0 = jnp.maximum(group_total - _segment_sum(fair0, seg, G), 0.0)

    def run_band(band, fair, remaining, rem_frac_all):
        in_band = (band_of_queue == band)[:, None]  # [Q,1]

        def cond(carry):
            fair, remaining, rem_frac, go, i = carry
            return go & (i < spec.max_rounds)

        def body(carry):
            fair, remaining, rem_frac, _, i = carry
            unsat = in_band & (requestable - fair > EPS)
            tw = _segment_sum(jnp.where(unsat, oqw, 0.0), seg, G)  # [G,R]
            n_w = jnp.where(unsat & (tw[seg] > 0), oqw / jnp.where(
                tw[seg] > 0, tw[seg], 1.0), 0.0)
            share_w = jnp.where(unsat,
                                jnp.maximum(0.0, n_w + k_value * (n_w - usage)),
                                0.0)
            sw = _segment_sum(share_w, seg, G)  # [G,R]
            active = unsat & (share_w > 0) & (sw[seg] > 0)
            fair_q = jnp.where(active,
                               remaining[seg] * share_w
                               / jnp.where(sw[seg] > 0, sw[seg], 1.0), 0.0)
            rem_req = requestable - fair
            satisfied_now = rem_req <= fair_q
            give = jnp.where(active,
                             jnp.where(satisfied_now, rem_req,
                                       jnp.floor(fair_q)), 0.0)
            new_frac = jnp.where(active,
                                 jnp.where(satisfied_now, 0.0,
                                           fair_q - jnp.floor(fair_q)),
                                 rem_frac)
            fair = fair + give
            remaining = jnp.maximum(
                remaining - _segment_sum(give, seg, G), 0.0)
            another = (active & (rem_req < fair_q)) & (remaining[seg] > EPS)
            go = jnp.any(another)
            return fair, remaining, new_frac, go, i + 1

        fair, remaining, rem_frac, _, _ = jax.lax.while_loop(
            cond, body,
            (fair, remaining, rem_frac_all, jnp.array(True), jnp.array(0)))
        return fair, remaining, rem_frac

    # Static unroll over priority bands (band ids are dense 0..num_bands-1,
    # 0 = highest priority — computed by the host-side prep).
    rem_fracs = []
    fair, remaining = fair0, remaining0
    for band in range(spec.num_bands):
        fair, remaining, rem_frac = run_band(
            band, fair, remaining, jnp.zeros_like(fair0))
        rem_fracs.append(rem_frac)

    # Largest-remainder unit distribution, per band, per group, per resource.
    def distribute(fair, remaining, rem_frac):
        # rank within (group, resource) by (-frac, tiebreak); non-members
        # (frac == 0) sort last and receive nothing.
        member = rem_frac > 0.0  # [Q,R]

        def per_resource(fair_r, remaining_r, frac_r, member_r):
            frac_r = jnp.round(frac_r, FRAC_DECIMALS)
            # Sort by group, then -frac, then tiebreak.
            order = jnp.lexsort((tiebreak_rank, -frac_r,
                                 jnp.where(member_r, 0, 1), seg))
            sorted_seg = seg[order]
            pos = jnp.arange(Q)
            # Rank within group = position - first position of the group.
            is_start = jnp.concatenate([
                jnp.array([True]), sorted_seg[1:] != sorted_seg[:-1]])
            group_start = jnp.where(is_start, pos, 0)
            group_start = jax.lax.associative_scan(jnp.maximum, group_start)
            rank_sorted = pos - group_start
            rank = jnp.zeros(Q, jnp.int32).at[order].set(
                rank_sorted.astype(jnp.int32))
            amount = jnp.where(
                member_r,
                jnp.clip(remaining_r[seg] - rank.astype(fair_r.dtype),
                         0.0, 1.0),
                0.0)
            fair_r = fair_r + amount
            remaining_r = jnp.maximum(
                remaining_r - _segment_sum(amount, seg, G), 0.0)
            return fair_r, remaining_r

        outs = [per_resource(fair[:, r], remaining[:, r], rem_frac[:, r],
                             member[:, r]) for r in range(R)]
        fair = jnp.stack([o[0] for o in outs], axis=1)
        remaining = jnp.stack([o[1] for o in outs], axis=1)
        return fair, remaining

    for band in range(spec.num_bands):
        fair, remaining = distribute(fair, remaining, rem_fracs[band])
    return fair


# ---------------------------------------------------------------------------
# Hierarchy orchestration (host-side prep + per-level kernel calls)
# ---------------------------------------------------------------------------

@dataclass
class QueueHierarchy:
    """Host-side prep of the queue forest for the level-by-level kernel."""
    levels: list            # list of np.ndarray of queue indices per depth
    parent: np.ndarray      # [Q] int, -1 for roots
    band_of_queue: np.ndarray   # [Q] dense band index per level (global bands)
    num_bands: int
    tiebreak_rank: np.ndarray   # [Q]

    @classmethod
    def build(cls, parent: np.ndarray, priority: np.ndarray,
              creation: np.ndarray, uids: list[str] | None = None
              ) -> "QueueHierarchy":
        q = parent.shape[0]
        depth = np.zeros(q, np.int32)
        for i in range(q):
            d, p = 0, parent[i]
            while p >= 0:
                d += 1
                p = parent[p]
            depth[i] = d
        levels = [np.where(depth == d)[0]
                  for d in range(int(depth.max()) + 1 if q else 0)]
        # Dense band ids: 0 = highest priority.
        uniq = np.unique(priority)[::-1]
        band = np.searchsorted(-uniq, -priority)
        order = sorted(range(q), key=lambda i: (creation[i],
                                                uids[i] if uids else str(i)))
        rank = np.zeros(q, np.int64)
        for r_, i in enumerate(order):
            rank[i] = r_
        return cls(levels, parent.astype(np.int64), band.astype(np.int32),
                   len(uniq) if q else 1, rank)


def fair_share_levels(total: np.ndarray, k_value: float,
                      hierarchy: QueueHierarchy,
                      deserved: np.ndarray, limit: np.ndarray,
                      oqw: np.ndarray, request: np.ndarray,
                      usage: np.ndarray) -> np.ndarray:
    """Full hierarchical fair share: one kernel call per depth level.

    ``request`` must already be rolled up the parent chain (roll_up_requests).
    Returns fair share [Q,R] for every queue, leaf and interior alike.
    """
    q, r = deserved.shape
    fair = np.zeros((q, r))
    if q == 0:
        return fair
    from ..utils.metrics import METRICS
    for depth, idxs in enumerate(hierarchy.levels):
        if len(idxs) == 0:
            continue
        METRICS.inc("fairshare_dispatch_total")
        if depth == 0:
            group_of = np.zeros(len(idxs), np.int32)
            group_totals = total[None, :]
        else:
            parents = hierarchy.parent[idxs]
            uniq_parents, group_of = np.unique(parents, return_inverse=True)
            group_totals = fair[uniq_parents]
        spec = LevelSpec(num_groups=group_totals.shape[0],
                         num_bands=hierarchy.num_bands)
        # kaijit: disable=KJT001 — level widths follow the QUEUE
        # hierarchy (control-plane config: reconfig events, not
        # per-cycle live pod counts), so exact shapes here trade a
        # rare reconfig retrace for minimal per-level kernels; the
        # per-cycle hot path uses the bucketed forest entry points.
        out = divide_groups_jax(
            spec, jnp.asarray(group_totals), jnp.asarray(group_of),
            jnp.asarray(hierarchy.band_of_queue[idxs]),
            jnp.asarray(deserved[idxs]), jnp.asarray(limit[idxs]),
            jnp.asarray(oqw[idxs]), jnp.asarray(request[idxs]),
            jnp.asarray(usage[idxs]),
            jnp.asarray(hierarchy.tiebreak_rank[idxs]),
            k_value)
        fair[idxs] = np.asarray(out)
    return fair


# ---------------------------------------------------------------------------
# Queue-forest kernel: the WHOLE hierarchy in one jitted dispatch
# ---------------------------------------------------------------------------

# group_parent sentinel: a group whose pool is the cluster total (roots).
ROOT_GROUP = -1


@dataclass(frozen=True)
class ForestSpec:
    """Static structure of the whole queue forest (trace-time constants).

    ``level_dims[l] = (G_l, S_l)``: level l packs into a dense
    ``[G_l, S_l]`` sibling-group matrix (groups x max-siblings, slot -1
    padding).  Per-level tight dims keep the fused kernel's work at the
    per-level path's operand sizes instead of paying the deepest level's
    width at every depth.  ``level_bands[l]`` lists the dense band ids
    actually present among level l's queues: the band fold iterates only
    those (a band with no member queues is a no-op in the reference
    sweep — zero grants, zero remainders — so skipping it is exact)."""
    level_dims: tuple
    level_bands: tuple
    num_bands: int
    num_queues: int
    max_rounds: int = 64

    @property
    def num_levels(self) -> int:
        return len(self.level_dims)

    @property
    def padded_slots(self) -> int:
        return sum(g * s for g, s in self.level_dims)


@dataclass
class QueueForest:
    """Dense level-batched layout of one queue forest.

    Per level l (device-resident, uploaded once at build; the prep cache
    keeps them alive across cycles):
    - ``level_qidx[l]`` [G_l, S_l]: global queue index per slot, -1 pad;
    - ``level_parent[l]`` [G_l]: global queue index whose fair share is
      the group's pool, or ROOT_GROUP for the cluster total.
    Group order is ascending unique parent index and slot order within a
    group is ascending queue index — the same operand order the
    per-level path's segment reductions see (bit-parity, DESIGN §2b).
    """
    level_qidx: tuple
    level_parent: tuple


def build_forest(hierarchy: QueueHierarchy
                 ) -> tuple[ForestSpec, QueueForest]:
    """Pack a QueueHierarchy into the dense per-level group matrices."""
    num_q = hierarchy.parent.shape[0]
    dims, band_ids, qidx_arrays, parent_arrays = [], [], [], []
    for depth, idxs in enumerate(hierarchy.levels):
        if depth == 0 or len(idxs) == 0:
            parents = np.full(len(idxs), ROOT_GROUP, np.int64)
        else:
            parents = hierarchy.parent[idxs]
        present = np.unique(hierarchy.band_of_queue[idxs]) if len(idxs) \
            else np.zeros(1, np.int64)
        band_ids.append(tuple(int(b) for b in present))
        gp, g_of = np.unique(parents, return_inverse=True)
        G = max(1, len(gp))
        sizes = np.bincount(g_of, minlength=G).astype(np.int64)
        S = max(1, int(sizes.max()) if sizes.size else 1)
        qidx = np.full((G, S), -1, np.int32)
        # Slot = position within the group, in ascending queue order
        # (idxs ascending; np.unique's inverse preserves that order).
        slot = np.zeros(len(idxs), np.int64)
        seen = np.zeros(G, np.int64)
        for i, g in enumerate(g_of):
            slot[i] = seen[g]
            seen[g] += 1
        qidx[g_of, slot] = idxs
        dims.append((G, S))
        qidx_arrays.append(jnp.asarray(qidx))
        parent_arrays.append(jnp.asarray(
            (gp if len(gp) else np.array([ROOT_GROUP])).astype(np.int32)))
    if not dims:
        dims = [(1, 1)]
        band_ids = [(0,)]
        qidx_arrays = [jnp.full((1, 1), -1, jnp.int32)]
        parent_arrays = [jnp.full((1,), ROOT_GROUP, jnp.int32)]
    spec = ForestSpec(level_dims=tuple(dims), level_bands=tuple(band_ids),
                      num_bands=hierarchy.num_bands, num_queues=num_q)
    forest = QueueForest(tuple(qidx_arrays), tuple(parent_arrays))
    return spec, forest


def _divide_level_dense(spec: ForestSpec, bands: tuple, pool, band_q,
                        deserved, limit, oqw, request, usage,
                        tiebreak_rank, k_value):
    """One level's division over the dense [G, S, R] group layout.

    The same fixed-point math as ``divide_groups_jax`` with the segment
    machinery dissolved: segment sums become axis-1 row reductions (the
    accumulation visits siblings in the same ascending order), segment
    gathers become [G, 1, R] broadcasts, and the in-group
    largest-remainder ranking becomes a per-row lexsort.  No scatter or
    gather appears anywhere in the round loop — the CPU/TPU cost of the
    old per-level kernel was dominated by 3 scatter-adds per round.
    Priority bands fold into a ``fori_loop`` carrying a [B, G, S, R]
    remainder stack.  Padding slots carry all-zero inputs: requestable
    0 keeps them unsatisfied-never-active through every phase, and
    trailing +0.0 terms cannot change a row reduction's value."""
    G, S = pool.shape[0], deserved.shape[1]
    R = deserved.shape[2]

    requestable = jnp.where(limit == UNLIMITED, request,
                            jnp.minimum(limit, request))
    my_total = pool[:, None, :]  # [G,1,R] broadcast
    eff_deserved = jnp.where(deserved == UNLIMITED,
                             jnp.broadcast_to(my_total, deserved.shape),
                             deserved)
    fair0 = jnp.minimum(eff_deserved, requestable)
    remaining0 = jnp.maximum(pool - fair0.sum(axis=1), 0.0)  # [G,R]

    def run_band(band, fair, remaining, rem_frac0):
        in_band = (band_q == band)[:, :, None]  # [G,S,1]

        def cond(carry):
            fair, remaining, rem_frac, go, i = carry
            return go & (i < spec.max_rounds)

        def body(carry):
            fair, remaining, rem_frac, _, i = carry
            unsat = in_band & (requestable - fair > EPS)
            tw = jnp.where(unsat, oqw, 0.0).sum(axis=1)  # [G,R]
            tw_b = tw[:, None, :]
            n_w = jnp.where(unsat & (tw_b > 0), oqw / jnp.where(
                tw_b > 0, tw_b, 1.0), 0.0)
            share_w = jnp.where(unsat,
                                jnp.maximum(0.0,
                                            n_w + k_value * (n_w - usage)),
                                0.0)
            sw = share_w.sum(axis=1)[:, None, :]  # [G,1,R]
            active = unsat & (share_w > 0) & (sw > 0)
            fair_q = jnp.where(active,
                               remaining[:, None, :] * share_w
                               / jnp.where(sw > 0, sw, 1.0), 0.0)
            rem_req = requestable - fair
            satisfied_now = rem_req <= fair_q
            give = jnp.where(active,
                             jnp.where(satisfied_now, rem_req,
                                       jnp.floor(fair_q)), 0.0)
            new_frac = jnp.where(active,
                                 jnp.where(satisfied_now, 0.0,
                                           fair_q - jnp.floor(fair_q)),
                                 rem_frac)
            fair = fair + give
            remaining = jnp.maximum(remaining - give.sum(axis=1), 0.0)
            another = (active & (rem_req < fair_q)) \
                & (remaining[:, None, :] > EPS)
            go = jnp.any(another)
            return fair, remaining, new_frac, go, i + 1

        fair, remaining, rem_frac, _, _ = jax.lax.while_loop(
            cond, body,
            (fair, remaining, rem_frac0, jnp.array(True), jnp.array(0)))
        return fair, remaining, rem_frac

    # Band fold: a fori_loop over the band ids actually present at this
    # level (dense, descending-priority order), not 0..num_bands-1 — an
    # absent band's sweep grants nothing and leaves no remainders, so
    # skipping it is exactly the reference's no-op.
    band_vec = jnp.asarray(bands, jnp.int32)
    n_bands = len(bands)

    def band_body(bi, carry):
        fair, remaining, rem_frac_all = carry
        fair, remaining, rem_frac = run_band(
            band_vec[bi], fair, remaining, jnp.zeros_like(fair))
        rem_frac_all = rem_frac_all.at[bi].set(rem_frac)
        return fair, remaining, rem_frac_all

    fair, remaining, rem_frac_all = jax.lax.fori_loop(
        0, n_bands, band_body,
        (fair0, remaining0, jnp.zeros((n_bands, G, S, R))))

    def distribute(fair, remaining, rem_frac):
        member = rem_frac > 0.0  # [G,S,R]

        def per_resource(fair_r, remaining_r, frac_r, member_r):
            # [G,S] each; remaining_r [G].
            frac_r = jnp.round(frac_r, FRAC_DECIMALS)
            order = jnp.lexsort((tiebreak_rank, -frac_r,
                                 jnp.where(member_r, 0, 1)), axis=-1)
            # order is a per-row permutation; its argsort is the inverse
            # permutation = each slot's in-group largest-remainder rank.
            rank = jnp.argsort(order, axis=-1)
            amount = jnp.where(
                member_r,
                jnp.clip(remaining_r[:, None] - rank.astype(fair_r.dtype),
                         0.0, 1.0),
                0.0)
            fair_r = fair_r + amount
            remaining_r = jnp.maximum(
                remaining_r - amount.sum(axis=1), 0.0)
            return fair_r, remaining_r

        outs = [per_resource(fair[:, :, r], remaining[:, r],
                             rem_frac[:, :, r], member[:, :, r])
                for r in range(R)]
        fair = jnp.stack([o[0] for o in outs], axis=2)
        remaining = jnp.stack([o[1] for o in outs], axis=1)
        return fair, remaining

    def dist_body(bi, carry):
        fair, remaining = carry
        return distribute(fair, remaining, rem_frac_all[bi])

    fair, remaining = jax.lax.fori_loop(0, n_bands, dist_body,
                                        (fair, remaining))
    return fair


@functools.partial(jax.jit, static_argnames=("spec",))
def fair_share_forest_jax(spec: ForestSpec, level_qidx, level_parent,
                          band_of, deserved, limit, oqw,
                          request, usage, tiebreak_rank, total, k_value):
    """The whole hierarchical division as one jitted program.

    Per-queue arrays are the global (unpadded) [Q,R] stacks; padding
    happens here by appending one zero row every padded slot gathers
    (zero request/weight/deserved makes a padding slot inert in every
    phase).  Levels unroll statically at their own [G_l, S_l] shapes:
    level l's group pools gather the fair shares level l-1 just wrote,
    which is exactly the per-level recursion of ``fair_share_levels``
    fused into one dispatch."""
    Q = spec.num_queues
    R = deserved.shape[1]
    zrow = jnp.zeros((1, R), deserved.dtype)
    des_p = jnp.concatenate([deserved, zrow])
    lim_p = jnp.concatenate([limit, zrow])
    oqw_p = jnp.concatenate([oqw, zrow])
    req_p = jnp.concatenate([request, zrow])
    use_p = jnp.concatenate([usage, zrow])
    band_p = jnp.concatenate([band_of, jnp.zeros(1, band_of.dtype)])
    tie_p = jnp.concatenate(
        [tiebreak_rank, jnp.full((1,), Q, tiebreak_rank.dtype)])

    fair_all = jnp.zeros((Q + 1, R))
    for level in range(spec.num_levels):
        qidx = level_qidx[level]               # [G,S]
        valid = qidx >= 0
        qi = jnp.where(valid, qidx, Q)         # padding reads the zero row
        gp = level_parent[level]               # [G]
        pool = jnp.where((gp >= 0)[:, None],
                         fair_all[jnp.clip(gp, 0, Q)],
                         jnp.broadcast_to(total, (gp.shape[0], R)))
        out = _divide_level_dense(
            spec, spec.level_bands[level], pool, band_p[qi], des_p[qi],
            lim_p[qi], oqw_p[qi], req_p[qi], use_p[qi], tie_p[qi],
            k_value)
        # Padding slots all write the zero row at index Q (identical
        # values, so duplicate-index scatter order cannot matter).
        fair_all = fair_all.at[qi.reshape(-1)].set(
            jnp.where(valid[:, :, None], out, 0.0).reshape(-1, R))
    return fair_all[:Q]


@dataclass
class ForestPrep:
    """Arena-resident host prep for one queue forest: the built
    hierarchy, the dense layout, and the device-resident slow-moving
    tensors (weights and the hierarchy's band/tiebreak vectors) that are
    part of the cache key and therefore constant for the cache entry's
    lifetime.  Only ``request``/``usage`` move cycle to cycle."""
    hierarchy: QueueHierarchy
    spec: ForestSpec
    forest: QueueForest
    deserved: jnp.ndarray
    limit: jnp.ndarray
    oqw: jnp.ndarray
    band_of: jnp.ndarray
    tiebreak: jnp.ndarray


def fair_share_forest(total: np.ndarray, k_value: float, prep: ForestPrep,
                      request: np.ndarray, usage: np.ndarray
                      ) -> np.ndarray:
    """Full hierarchical fair share in ONE kernel dispatch.

    Same contract as ``fair_share_levels`` (``request`` rolled up the
    parent chain; returns [Q,R] for every queue) — property-tested
    bit-identical against it on randomized forests."""
    q = request.shape[0]
    if q == 0:
        return np.zeros((q, request.shape[1] if request.ndim == 2
                         else 0))
    from ..utils.metrics import METRICS
    METRICS.inc("fairshare_dispatch_total")
    out = fair_share_forest_jax(
        prep.spec, prep.forest.level_qidx, prep.forest.level_parent,
        prep.band_of, prep.deserved, prep.limit, prep.oqw,
        jnp.asarray(request), jnp.asarray(usage), prep.tiebreak,
        jnp.asarray(total), k_value)
    return np.asarray(out)


# Host-prep memo: (queue set, priorities, creations, weights) -> built
# hierarchy + forest layout + resident weight tensors.  A steady cluster
# re-divides every cycle with unchanged structure; rebuilding the
# O(Q·depth) hierarchy prep and re-uploading the layout and weights each
# time was pure waste.  Bounded LRU: churn between a few shapes (chaos
# suites, sharded pools) stays cached.  _FOREST_LOCK serializes the
# cache AND the guard-watch init: concurrent sharded schedulers call
# prepared_forest from their own cycle threads (chaos_matrix --shards),
# and an unlocked OrderedDict corrupts under interleaved
# get/move_to_end/popitem.
_FOREST_CACHE: OrderedDict = OrderedDict()
_FOREST_CACHE_MAX = 8
_FOREST_LOCK = threading.Lock()
_GUARD_WATCH = None


def prepared_forest(parent: np.ndarray, priority: np.ndarray,
                    creation: np.ndarray, uids: list[str],
                    deserved: np.ndarray, limit: np.ndarray,
                    oqw: np.ndarray, out_info: dict | None = None
                    ) -> ForestPrep:
    """Build (or reuse) the host prep for one queue forest.

    The cache key is the full queue-set identity (uids, parents,
    priorities, creation stamps) plus the quota weights, so any change
    to the forest shape or weights rebuilds while steady cycles pay one
    hash (``fairshare_prep_reuse_total``).  A device-guard transition
    (breaker flip or closed-breaker fallback) drops the cache: the
    resident weight tensors may sit on the dead side of the fallback
    boundary, same hazard the arena invalidates on.

    ``out_info`` (optional dict) receives ``{"reused": bool}`` for THIS
    call — a per-call verdict the global counter cannot give once
    concurrent shards share the cache."""
    global _GUARD_WATCH
    import hashlib

    from ..framework.arena import GuardWatch
    from ..utils.deviceguard import device_guard
    from ..utils.metrics import METRICS
    h = hashlib.blake2b(digest_size=16)
    for arr in (parent, priority, creation, deserved, limit, oqw):
        h.update(np.ascontiguousarray(arr).tobytes())
    h.update("\x00".join(uids).encode())
    key = h.digest()
    with _FOREST_LOCK:
        if _GUARD_WATCH is None:
            _GUARD_WATCH = GuardWatch()
        if _GUARD_WATCH.transitioned(device_guard()):
            _FOREST_CACHE.clear()
        hit = _FOREST_CACHE.get(key)
        if out_info is not None:
            out_info["reused"] = hit is not None
        if hit is not None:
            _FOREST_CACHE.move_to_end(key)
            METRICS.inc("fairshare_prep_reuse_total")
            return hit
        # Build under the lock: concurrent shards share one queue set,
        # so racing threads would build the same entry twice; the loser
        # of an unlocked race would also evict the winner's live entry.
        hierarchy = QueueHierarchy.build(parent, priority, creation, uids)
        spec, forest = build_forest(hierarchy)
        prep = ForestPrep(hierarchy, spec, forest, jnp.asarray(deserved),
                          jnp.asarray(limit), jnp.asarray(oqw),
                          jnp.asarray(hierarchy.band_of_queue),
                          jnp.asarray(hierarchy.tiebreak_rank))
        _FOREST_CACHE[key] = prep
        while len(_FOREST_CACHE) > _FOREST_CACHE_MAX:
            _FOREST_CACHE.popitem(last=False)
        return prep


def roll_up_requests(parent: np.ndarray, leaf_values: np.ndarray
                     ) -> np.ndarray:
    """Aggregate per-leaf quantities up the parent chain
    (proportion.go:378-401: Request/Allocated accumulate on every ancestor)."""
    q = parent.shape[0]
    # Deepest-first so each child's (already complete) subtotal flows up.
    accum = leaf_values.copy()
    for i in sorted(range(q), key=lambda i: -_depth_of(parent, i)):
        p = parent[i]
        if p >= 0:
            accum[p] += accum[i]
    return accum


def _depth_of(parent: np.ndarray, i: int) -> int:
    d, p = 0, parent[i]
    while p >= 0:
        d += 1
        p = parent[p]
    return d


# ---------------------------------------------------------------------------
# DRF dominant share (queue_resource_share.go:142-162)
# ---------------------------------------------------------------------------

NO_FAIR_SHARE_DRF_MULTIPLIER = 1000.0


def dominant_share(allocated: np.ndarray, allocatable: np.ndarray,
                   total: np.ndarray) -> np.ndarray:
    """max over resources of allocated/allocatable; zero allocatable with
    allocation gets the penalty multiplier.  [Q,R],[Q,R],[R] -> [Q]."""
    xp = jnp if isinstance(allocated, jnp.ndarray) else np
    alloc_share = xp.where(allocatable == UNLIMITED,
                           xp.broadcast_to(total, allocated.shape),
                           allocatable)
    value = xp.where(alloc_share > 0, allocated / xp.where(
        alloc_share > 0, alloc_share, 1.0),
        allocated * NO_FAIR_SHARE_DRF_MULTIPLIER)
    return value.max(axis=1)


def allocatable_share(deserved: np.ndarray, fair: np.ndarray,
                      limit: np.ndarray) -> np.ndarray:
    """GetAllocatableShare (resource_share.go:52-62): max(deserved, fair)
    capped at limit; UNLIMITED deserved -> limit."""
    xp = jnp if isinstance(deserved, jnp.ndarray) else np
    base = xp.maximum(deserved, fair)
    capped = xp.where(limit == UNLIMITED, base, xp.minimum(limit, base))
    return xp.where(deserved == UNLIMITED, limit, capped)
