"""Per-queue historical-usage decay: ONE tensor update per cycle.

The time-aware fairness subsystem (utils/usagedb.py, DESIGN §13) keeps
the whole fleet's historical usage as a single ``[Q, R]`` decayed
integral.  Each cycle folds in that cycle's allocation sample with the
half-life factor applied to everything older:

    usage' = where(keep, usage * decay, 0) + alloc

where ``decay = 0.5^(dt / half_life)`` for the elapsed time since the
previous fold and ``keep`` masks queues whose last sample still lies
inside the sliding window (a queue that fell out of the window restarts
from zero — the tensor analog of the sample-deque popleft).

This replaces the per-queue host loop the original ``InMemoryUsageDB``
stub paid (O(queues x samples) Python per fetch) with one jitted
dispatch per cycle — the queue-forest kernel's argument (DESIGN §2b)
applied to the usage axis.  ``tools/fleet_budget.py`` pins the dispatch
count structurally: a silent fall-back to a per-queue loop multiplies
``usage_decay_dispatch_total`` by Q and trips the gate.

``usage_decay_np`` is the host reference: the same elementwise IEEE
expression, asserted bit-identical in tests/test_usagedb.py (the
CPU-backend jit compiles to the same scalar ops).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def usage_decay_kernel(usage, alloc, keep, decay):
    """One decayed fold: [Q,R] usage, [Q,R] alloc sample, [Q] bool keep
    (inside-window mask), scalar decay factor."""
    return jnp.where(keep[:, None], usage * decay, 0.0) + alloc


def usage_decay_np(usage: np.ndarray, alloc: np.ndarray,
                   keep: np.ndarray, decay: float) -> np.ndarray:
    """Host reference — formula-identical to the kernel."""
    return np.where(keep[:, None], usage * decay, 0.0) + alloc
