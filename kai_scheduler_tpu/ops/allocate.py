"""Gang allocation kernel: the per-cycle hot loop as one jitted scan.

The reference allocates task-by-task, job-by-job, each placement mutating
node state before the next score, with checkpoint/rollback around each gang
(pkg/scheduler/actions/common/allocate.go:20-163,
framework/statement.go:44-61).  This kernel reproduces those semantics
exactly as a ``lax.scan`` over the flattened task sequence:

- carry = (idle, releasing, pod_room, per-job checkpoint of each, current
  job id, current job ok-flag);
- a job boundary commits (keeps) or rolls back (restores checkpoint) the
  previous gang, mirroring Statement.Checkpoint/Rollback;
- each step evaluates THIS task's predicate row and score row against the
  *current* mutated state — the same greedy sequence the Go code walks, but
  with the node loop fully vectorized on the MXU-friendly [N,R] tensors;
- a task that fits nowhere fails its whole gang: remaining tasks are
  skipped and the gang's placements are discarded (gang all-or-nothing).

Tasks must arrive grouped by job (non-decreasing ``task_job``), ordered by
the host-side job/task ordering plugins — order is policy, placement is
mechanism; only the mechanism runs on device.

Pipelining: a task that fits only on idle+releasing resources claims the
releasing pool (status Pipelined host-side); allocated tasks claim idle.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .predicates import feasibility_row
from .scoring import BINPACK, score_row

EPS = 1e-9
NEG = -1e18


class AllocationResult(NamedTuple):
    placements: jnp.ndarray    # [T] int32 node index, -1 = unplaced
    pipelined: jnp.ndarray     # [T] bool, True = placed onto releasing pool
    job_success: jnp.ndarray   # [J] bool — gang fully placed
    node_idle: jnp.ndarray     # [N,R] post-allocation idle
    node_releasing: jnp.ndarray  # [N,R] post-allocation releasing pool
    # [T + T + J] int32: placements ++ pipelined ++ job_success fused on
    # device, so a caller needing all three pays ONE device->host fetch
    # (~70-100ms RTT each on the tunneled TPU) instead of three.  None
    # when the producing kernel doesn't fuse it.
    packed: "jnp.ndarray | None" = None


@functools.partial(jax.jit,
                   static_argnames=("gpu_strategy", "cpu_strategy",
                                    "allow_pipeline", "pipeline_only"))
def allocate_jobs_kernel(node_allocatable, node_idle, node_releasing,
                         node_labels, node_taints, node_pod_room,
                         task_req, task_job, task_selector, task_tolerations,
                         job_allowed, task_extra_scores=None,
                         task_node_mask=None, task_anti_domain=None,
                         task_aff_domain=None,
                         gpu_strategy: int = BINPACK,
                         cpu_strategy: int = BINPACK,
                         allow_pipeline: bool = True,
                         pipeline_only: bool = False) -> AllocationResult:
    """Place every job's gang greedily; roll failed gangs back.

    job_allowed: [J] bool gate (e.g. queue capacity check, proportion
    capacity_policy) — a gated-out job fails without touching state.
    task_extra_scores: optional [T,N] additive score terms (topology,
    nominated node) computed by other kernels.
    task_node_mask: optional [T,N] bool hard predicate (inter-pod affinity
    terms against existing pods, upstream-predicate verdicts): a False
    node is infeasible for that task, not merely low-scored.
    task_anti_domain: optional (dom [T,N] int32, marks [T] bool,
    avoids [T] bool) — in-gang REQUIRED anti-affinity for ONE term.
    ``dom`` maps nodes to the term's topology domains (-1 = no domain);
    a task with ``marks`` creates a pod matching the term's selector, a
    task with ``avoids`` carries the term.  Within a gang, K8s semantics
    (incl. symmetry) reduce to: an avoider cannot enter a domain where a
    marker already landed, and a marker cannot enter a domain where an
    avoider already landed.  Blocked state lives in the scan carry and
    resets at each job boundary, so rollback is automatic.
    task_aff_domain: optional (dom [T,N] int32, marks [T] bool,
    avoids [T] bool, static_ok [T,N] bool, bootstrap [T] bool) — in-gang
    REQUIRED affinity for ONE term.  An avoider may sit only in a domain
    holding a matching pod: one that held a match before the cycle
    (``static_ok``) OR one a gang marker landed in this scan
    (accumulated union).  ``bootstrap`` flags the upstream first-pod rule:
    a self-matching avoider may open a fresh domain while the gang has
    placed no marker yet.
    pipeline_only: scenario-simulation mode — all placements pipeline
    (statement.go ConvertAllAllocatedToPipelined semantics come free:
    nothing claims idle).
    """
    T = task_req.shape[0]
    N = node_allocatable.shape[0]
    if task_extra_scores is None:
        task_extra_scores = jnp.zeros((T, N))
    if task_node_mask is None:
        task_node_mask = jnp.ones((T, N), bool)
    if task_anti_domain is None:
        anti_dom = jnp.full((T, N), -1, jnp.int32)
        anti_marks = jnp.zeros(T, bool)
        anti_avoids = jnp.zeros(T, bool)
    else:
        anti_dom, anti_marks, anti_avoids = task_anti_domain
    if task_aff_domain is None:
        aff_dom = jnp.full((T, N), -1, jnp.int32)
        aff_marks = jnp.zeros(T, bool)
        aff_avoids = jnp.zeros(T, bool)
        aff_static = jnp.ones((T, N), bool)
        aff_boot = jnp.zeros(T, bool)
    else:
        aff_dom, aff_marks, aff_avoids, aff_static, aff_boot = \
            task_aff_domain

    class Carry(NamedTuple):
        idle: jnp.ndarray
        rel: jnp.ndarray
        room: jnp.ndarray
        ck_idle: jnp.ndarray
        ck_rel: jnp.ndarray
        ck_room: jnp.ndarray
        cur_job: jnp.ndarray
        cur_ok: jnp.ndarray
        # Self-anti-affinity: domains closed to avoiders (a marker landed)
        # and to markers (an avoider landed; upstream symmetry).
        blocked_avoiders: jnp.ndarray  # [N] bool
        blocked_markers: jnp.ndarray   # [N] bool
        # Self-affinity: union of domains gang markers landed in, and
        # whether any marker has landed yet (bootstrap gate).
        aff_union: jnp.ndarray         # [N] bool
        any_marker: jnp.ndarray        # scalar bool

    init = Carry(node_idle, node_releasing, node_pod_room,
                 node_idle, node_releasing, node_pod_room,
                 jnp.array(-1, jnp.int32), jnp.array(False),
                 jnp.zeros(N, bool), jnp.zeros(N, bool),
                 jnp.zeros(N, bool), jnp.array(False))

    def step(carry: Carry, t):
        j = task_job[t]
        new_job = j != carry.cur_job
        # Job boundary: commit previous gang if it succeeded, else restore.
        keep = jnp.where(new_job & ~carry.cur_ok, False, True)
        idle = jnp.where(keep, carry.idle, carry.ck_idle)
        rel = jnp.where(keep, carry.rel, carry.ck_rel)
        room = jnp.where(keep, carry.room, carry.ck_room)
        ck_idle = jnp.where(new_job, idle, carry.ck_idle)
        ck_rel = jnp.where(new_job, rel, carry.ck_rel)
        ck_room = jnp.where(new_job, room, carry.ck_room)
        ok = jnp.where(new_job, job_allowed[j], carry.cur_ok)
        blocked_avoiders = jnp.where(new_job, False, carry.blocked_avoiders)
        blocked_markers = jnp.where(new_job, False, carry.blocked_markers)
        aff_union = jnp.where(new_job, False, carry.aff_union)
        any_marker = jnp.where(new_job, False, carry.any_marker)

        req = task_req[t]
        fit_now, fit_future = feasibility_row(
            idle, rel, node_labels, node_taints, room, req,
            task_selector[t], task_tolerations[t])
        if pipeline_only:
            fit_now = jnp.zeros_like(fit_now)
        feasible = fit_now | (fit_future if (allow_pipeline or pipeline_only)
                              else jnp.zeros_like(fit_future))
        feasible = feasible & task_node_mask[t] \
            & ~(anti_avoids[t] & blocked_avoiders) \
            & ~(anti_marks[t] & blocked_markers)
        # Required affinity: an avoider needs a matching pod in its domain
        # — pre-existing (static), placed by this gang (union), or itself
        # under the first-pod bootstrap rule.
        aff_ok = aff_static[t] | aff_union | (aff_boot[t] & ~any_marker)
        feasible = feasible & jnp.where(aff_avoids[t], aff_ok, True)
        score = score_row(node_allocatable, idle, req, feasible,
                          fit_now, gpu_strategy, cpu_strategy)
        score = score + task_extra_scores[t]
        found = ok & jnp.any(feasible)
        best = jnp.argmax(jnp.where(feasible, score, NEG))
        pipelined = found & ~fit_now[best]

        one_hot = (jnp.arange(idle.shape[0]) == best) & found
        take_idle = jnp.where((one_hot & ~pipelined)[:, None], req[None, :],
                              0.0)
        take_rel = jnp.where((one_hot & pipelined)[:, None], req[None, :],
                             0.0)
        idle = idle - take_idle
        rel = rel - take_rel
        room = room - one_hot.astype(room.dtype)

        # Self-anti-affinity: close the winning node's whole topology
        # domain to the complementary role for the rest of the gang.
        dom_row = anti_dom[t]
        won_dom = dom_row[best]
        in_dom = found & (won_dom >= 0) & (dom_row == won_dom)
        blocked_avoiders = blocked_avoiders | (anti_marks[t] & in_dom)
        blocked_markers = blocked_markers | (anti_avoids[t] & in_dom)

        a_row = aff_dom[t]
        a_won = a_row[best]
        a_in_dom = found & (a_won >= 0) & (a_row == a_won)
        aff_union = aff_union | (aff_marks[t] & a_in_dom)
        any_marker = any_marker | (aff_marks[t] & found)

        ok = ok & found
        out = (jnp.where(found, best, -1).astype(jnp.int32), pipelined, found)
        return Carry(idle, rel, room, ck_idle, ck_rel, ck_room,
                     j.astype(jnp.int32), ok,
                     blocked_avoiders, blocked_markers,
                     aff_union, any_marker), out

    carry, (placements, pipelined, found) = jax.lax.scan(
        step, init, jnp.arange(T))

    # Final gang commits or rolls back too.
    idle = jnp.where(carry.cur_ok, carry.idle, carry.ck_idle)
    rel = jnp.where(carry.cur_ok, carry.rel, carry.ck_rel)

    num_jobs = job_allowed.shape[0]
    placed_per_job = jax.ops.segment_sum(found.astype(jnp.int32), task_job,
                                         num_segments=num_jobs)
    tasks_per_job = jax.ops.segment_sum(jnp.ones(T, jnp.int32), task_job,
                                        num_segments=num_jobs)
    job_success = (tasks_per_job > 0) & (placed_per_job == tasks_per_job)
    # Failed gangs contribute no placements.
    valid = job_success[task_job]
    placements = jnp.where(valid, placements, -1)
    pipelined = pipelined & valid
    packed = jnp.concatenate([placements,
                              pipelined.astype(jnp.int32),
                              job_success.astype(jnp.int32)])
    return AllocationResult(placements, pipelined, job_success, idle, rel,
                            packed)
