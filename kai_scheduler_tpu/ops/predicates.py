"""Vectorized predicate masks: tasks × nodes feasibility in one shot.

Replaces the reference's per-task-per-node predicate chain
(pkg/scheduler/plugins/predicates/predicates.go:106,
pkg/scheduler/k8s_internal/predicates/predicates.go:70-167 and
NodeInfo.IsTaskAllocatable node_info.go:168) with dense tensor ops over the
packed snapshot: resource capacity, node-selector/affinity label matching,
taint/toleration, and pod-count room all evaluate as one boolean program
under jit.

``feasibility_row`` is the canonical single-task implementation; the gang
allocation kernel steps it per task against mutating node state, and the
batch [T, N] form is its vmap — one definition, no drift between paths.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NO_LABEL = -1
NO_TAINT = -1
EPS = 1e-9


def feasibility_row(idle, releasing, labels, taints, room,
                    req, selector, tolerations):
    """One task against all nodes: ([N,R] state, [R]/[L]/[Tl] task) ->
    (fit_now [N], fit_future [N]).

    fit_now: IsTaskAllocatable (idle resources); fit_future:
    IsTaskAllocatableOnReleasingOrIdle (pipelining candidates).
    """
    sel_ok = jnp.all((selector[None, :] == NO_LABEL)
                     | (selector[None, :] == labels), axis=-1)
    tol = jnp.any(taints[:, :, None] == tolerations[None, None, :], axis=-1)
    taint_ok = jnp.all((taints == NO_TAINT) | tol, axis=-1)
    hard = sel_ok & taint_ok & (room >= 1.0)
    fit_now = hard & jnp.all(req[None, :] <= idle + EPS, axis=-1)
    fit_future = hard & jnp.all(req[None, :] <= idle + releasing + EPS,
                                axis=-1)
    return fit_now, fit_future


@jax.jit
def feasibility_masks(node_idle, node_releasing, node_labels, node_taints,
                      node_pod_room, task_req, task_selector,
                      task_tolerations):
    """Batch predicate evaluation: vmap of feasibility_row over tasks.
    Returns (fit_now, fit_future): [T,N] bool masks."""
    return jax.vmap(
        lambda req, sel, tol: feasibility_row(
            node_idle, node_releasing, node_labels, node_taints,
            node_pod_room, req, sel, tol)
    )(task_req, task_selector, task_tolerations)


# -- standalone sub-masks (used directly by tests/tools) --------------------

@jax.jit
def selector_mask(node_labels: jnp.ndarray,
                  task_selector: jnp.ndarray) -> jnp.ndarray:
    """[N,L] x [T,L] -> [T,N] bool: every constrained label matches."""
    t_sel = task_selector[:, None, :]   # [T,1,L]
    n_lab = node_labels[None, :, :]     # [1,N,L]
    ok = (t_sel == NO_LABEL) | (t_sel == n_lab)
    return jnp.all(ok, axis=-1)


@jax.jit
def toleration_mask(node_taints: jnp.ndarray,
                    task_tolerations: jnp.ndarray) -> jnp.ndarray:
    """[N,Tt] x [T,Tl] -> [T,N] bool: every node taint is tolerated."""
    taints = node_taints[None, :, :, None]        # [1,N,Tt,1]
    tols = task_tolerations[:, None, None, :]     # [T,1,1,Tl]
    tolerated = jnp.any(taints == tols, axis=-1)  # [T,N,Tt]
    ok = (node_taints[None, :, :] == NO_TAINT) | tolerated
    return jnp.all(ok, axis=-1)


@jax.jit
def capacity_mask(node_free: jnp.ndarray, task_req: jnp.ndarray
                  ) -> jnp.ndarray:
    """[N,R] x [T,R] -> [T,N] bool: request fits into free resources."""
    return jnp.all(task_req[:, None, :] <= node_free[None, :, :] + EPS,
                   axis=-1)
