"""Vectorized predicate masks: tasks × nodes feasibility in one shot.

Replaces the reference's per-task-per-node predicate chain
(pkg/scheduler/plugins/predicates/predicates.go:106,
pkg/scheduler/k8s_internal/predicates/predicates.go:70-167 and
NodeInfo.IsTaskAllocatable node_info.go:168) with dense tensor ops over the
packed snapshot: resource capacity, node-selector/affinity label matching,
taint/toleration, and pod-count room all evaluate as one boolean program
under jit.

``feasibility_row`` is the canonical single-task implementation; the gang
allocation kernel steps it per task against mutating node state, and the
batch [T, N] form is its vmap — one definition, no drift between paths.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NO_LABEL = -1
NO_TAINT = -1
EPS = 1e-9


def feasibility_row(idle, releasing, labels, taints, room,
                    req, selector, tolerations):
    """One task against all nodes: ([N,R] state, [R]/[L]/[Tl] task) ->
    (fit_now [N], fit_future [N]).

    fit_now: IsTaskAllocatable (idle resources); fit_future:
    IsTaskAllocatableOnReleasingOrIdle (pipelining candidates).
    """
    sel_ok = jnp.all((selector[None, :] == NO_LABEL)
                     | (selector[None, :] == labels), axis=-1)
    tol = jnp.any(taints[:, :, None] == tolerations[None, None, :], axis=-1)
    taint_ok = jnp.all((taints == NO_TAINT) | tol, axis=-1)
    hard = sel_ok & taint_ok & (room >= 1.0)
    fit_now = hard & jnp.all(req[None, :] <= idle + EPS, axis=-1)
    fit_future = hard & jnp.all(req[None, :] <= idle + releasing + EPS,
                                axis=-1)
    return fit_now, fit_future


@jax.jit
def feasibility_masks(node_idle, node_releasing, node_labels, node_taints,
                      node_pod_room, task_req, task_selector,
                      task_tolerations):
    """Batch predicate evaluation: vmap of feasibility_row over tasks.
    Returns (fit_now, fit_future): [T,N] bool masks."""
    return jax.vmap(
        lambda req, sel, tol: feasibility_row(
            node_idle, node_releasing, node_labels, node_taints,
            node_pod_room, req, sel, tol)
    )(task_req, task_selector, task_tolerations)


def feasibility_caps_row(idle, releasing, labels, taints, room,
                         req, selector, tolerations):
    """Fused single-pass variant of ``feasibility_row`` + the grouped
    kernel's whole-task capacity math: one read of the node state yields
    (fit_now, fit_future, cap_now_f, cap_tot_f), each [N].

    The resource axis is unrolled (R is static and small), so XLA sees a
    single elementwise DAG per node instead of a chain of [N,R]
    broadcast+reduce ops — the per-group-step formulation the fused
    allocation kernel (ops/allocate_grouped) runs inside its scan.  The
    float semantics are formula-identical to ``feasibility_row``:
    comparisons against ``idle + EPS``, capacity as floor(idle/req)
    bounded later by the caller; min/all over R reassociate only exact
    operations (min is exact; the boolean chain is order-free).

    ``releasing=None`` declares the caller has proven the releasing pool
    empty: fit_future and cap_tot_f alias the fit-now outputs (with
    releasing == 0 the legacy formulas reduce to exactly that, including
    EPS behaviour).
    """
    sel_ok = jnp.all((selector[None, :] == NO_LABEL)
                     | (selector[None, :] == labels), axis=-1)
    tol = jnp.any(taints[:, :, None] == tolerations[None, None, :], axis=-1)
    taint_ok = jnp.all((taints == NO_TAINT) | tol, axis=-1)
    hard = sel_ok & taint_ok & (room >= 1.0)

    r_dims = idle.shape[1]
    fits_idle = hard
    fits_total = hard
    cap_now_f = None
    cap_tot_f = None
    inf = jnp.asarray(jnp.inf, idle.dtype)
    for r in range(r_dims):
        rq = req[r]
        safe = jnp.where(rq > 0, rq, 1.0)
        col = idle[:, r]
        fits_idle = fits_idle & (rq <= col + EPS)
        ratio = jnp.where(rq > 0, jnp.floor(col / safe), inf)
        cap_now_f = ratio if cap_now_f is None \
            else jnp.minimum(cap_now_f, ratio)
        if releasing is not None:
            tot = col + releasing[:, r]
            fits_total = fits_total & (rq <= tot + EPS)
            ratio_t = jnp.where(rq > 0, jnp.floor(tot / safe), inf)
            cap_tot_f = ratio_t if cap_tot_f is None \
                else jnp.minimum(cap_tot_f, ratio_t)
    if releasing is None:
        return fits_idle, fits_idle, cap_now_f, cap_now_f
    return fits_idle, fits_total, cap_now_f, cap_tot_f


# -- standalone sub-masks (used directly by tests/tools) --------------------

@jax.jit
def selector_mask(node_labels: jnp.ndarray,
                  task_selector: jnp.ndarray) -> jnp.ndarray:
    """[N,L] x [T,L] -> [T,N] bool: every constrained label matches."""
    t_sel = task_selector[:, None, :]   # [T,1,L]
    n_lab = node_labels[None, :, :]     # [1,N,L]
    ok = (t_sel == NO_LABEL) | (t_sel == n_lab)
    return jnp.all(ok, axis=-1)


@jax.jit
def toleration_mask(node_taints: jnp.ndarray,
                    task_tolerations: jnp.ndarray) -> jnp.ndarray:
    """[N,Tt] x [T,Tl] -> [T,N] bool: every node taint is tolerated."""
    taints = node_taints[None, :, :, None]        # [1,N,Tt,1]
    tols = task_tolerations[:, None, None, :]     # [T,1,1,Tl]
    tolerated = jnp.any(taints == tols, axis=-1)  # [T,N,Tt]
    ok = (node_taints[None, :, :] == NO_TAINT) | tolerated
    return jnp.all(ok, axis=-1)


@jax.jit
def capacity_mask(node_free: jnp.ndarray, task_req: jnp.ndarray
                  ) -> jnp.ndarray:
    """[N,R] x [T,R] -> [T,N] bool: request fits into free resources."""
    return jnp.all(task_req[:, None, :] <= node_free[None, :, :] + EPS,
                   axis=-1)
