"""Vectorized predicate masks: tasks × nodes feasibility in one shot.

Replaces the reference's per-task-per-node predicate chain
(pkg/scheduler/plugins/predicates/predicates.go:106,
pkg/scheduler/k8s_internal/predicates/predicates.go:70-167 and
NodeInfo.IsTaskAllocatable node_info.go:168) with dense tensor ops over the
packed snapshot: resource capacity, node-selector/affinity label matching,
taint/toleration, and pod-count room all evaluate as one [T, N] boolean
program under jit.  The Go code runs these per candidate node inside the
allocation loop; here the full mask is one fused XLA computation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NO_LABEL = -1
NO_TAINT = -1
EPS = 1e-9


@jax.jit
def selector_mask(node_labels: jnp.ndarray,
                  task_selector: jnp.ndarray) -> jnp.ndarray:
    """[N,L] x [T,L] -> [T,N] bool: every constrained label matches.

    A task entry of NO_LABEL means "don't care"; a node entry of NO_LABEL
    means the label is absent (fails any constraint on that key).
    """
    t_sel = task_selector[:, None, :]   # [T,1,L]
    n_lab = node_labels[None, :, :]     # [1,N,L]
    ok = (t_sel == NO_LABEL) | (t_sel == n_lab)
    return jnp.all(ok, axis=-1)


@jax.jit
def toleration_mask(node_taints: jnp.ndarray,
                    task_tolerations: jnp.ndarray) -> jnp.ndarray:
    """[N,Tt] x [T,Tl] -> [T,N] bool: every node taint is tolerated."""
    taints = node_taints[None, :, :, None]        # [1,N,Tt,1]
    tols = task_tolerations[:, None, None, :]     # [T,1,1,Tl]
    tolerated = jnp.any(taints == tols, axis=-1)  # [T,N,Tt]
    ok = (node_taints[None, :, :] == NO_TAINT) | tolerated
    return jnp.all(ok, axis=-1)


@jax.jit
def capacity_mask(node_free: jnp.ndarray, task_req: jnp.ndarray
                  ) -> jnp.ndarray:
    """[N,R] x [T,R] -> [T,N] bool: request fits into free resources."""
    return jnp.all(task_req[:, None, :] <= node_free[None, :, :] + EPS,
                   axis=-1)


@jax.jit
def feasibility_masks(node_idle, node_releasing, node_labels, node_taints,
                      node_pod_room, task_req, task_selector,
                      task_tolerations):
    """Full predicate evaluation.

    Returns (fit_now, fit_future): [T,N] bool masks for allocation on idle
    resources and for pipelining onto idle+releasing resources
    (IsTaskAllocatable / IsTaskAllocatableOnReleasingOrIdle).
    """
    hard = (selector_mask(node_labels, task_selector)
            & toleration_mask(node_taints, task_tolerations)
            & (node_pod_room[None, :] >= 1.0))
    fit_now = hard & capacity_mask(node_idle, task_req)
    fit_future = hard & capacity_mask(node_idle + node_releasing, task_req)
    return fit_now, fit_future
