"""Batched scenario feasibility: score K victim prefixes in one call.

The scenario solvers (actions/solvers.py, mirroring
pkg/scheduler/actions/common/solvers/job_solver.go:47-90) accumulate
victims one step at a time and simulate each prefix — one device round
trip per scenario.  On a tunneled device every round trip costs ~RTT, so
worst-case reclaim latency is scenario-count-bound (SURVEY §7.6 /
BASELINE config #3 call this out).

This kernel evaluates ALL prefixes at once: prefix k's node state is the
live state plus the cumulative released resources of victims 1..k (an
eviction moves a victim's request into the releasing pool), and the
pending job's pipeline-only placement attempt vmaps over that leading
axis.  The result is a [K] feasibility vector from ONE device call; the
solver then exact-confirms only the smallest feasible prefix through the
ordinary statement path (validators, victim re-placement, masks), so
semantics stay identical to the sequential search.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .allocate import allocate_jobs_kernel
from .scoring import BINPACK


@functools.partial(jax.jit,
                   static_argnames=("num_prefixes", "gpu_strategy",
                                    "cpu_strategy"))
def batch_prefix_feasibility(node_allocatable, node_idle, node_releasing,
                             node_labels, node_taints, node_room,
                             release_step, release_node, release_vec,
                             task_req, task_job, task_selector,
                             task_tolerations, num_prefixes: int,
                             task_node_mask=None,
                             gpu_strategy: int = BINPACK,
                             cpu_strategy: int = BINPACK) -> jnp.ndarray:
    """[num_prefixes] bool: can the pending job pipeline onto each
    prefix's released resources?

    Victim releases arrive SPARSE — (release_step [M], release_node [M],
    release_vec [M,R]) rows, padded with step >= num_prefixes — and the
    dense per-prefix releasing pools materialize on device (scatter-add +
    cumulative sum over the prefix axis), so the host->device transfer is
    O(victim tasks), never O(prefixes x nodes).  node_room is
    prefix-invariant (evicted pods stay on their node as Releasing).
    """
    n = node_allocatable.shape[0]
    r = node_releasing.shape[1]
    delta = jnp.zeros((num_prefixes, n, r), node_releasing.dtype)
    delta = delta.at[release_step, release_node].add(release_vec,
                                                     mode="drop")
    prefix_rel = node_releasing[None, :, :] + jnp.cumsum(delta, axis=0)
    # Job 1 holds the caller's padding task rows; gate it off so the
    # kernel skips their placement work entirely (same convention as
    # session.propose_placements padding).
    job_allowed = jnp.array([True, False])

    def one(prefix):
        result = allocate_jobs_kernel(
            node_allocatable, node_idle, prefix, node_labels,
            node_taints, node_room, task_req, task_job, task_selector,
            task_tolerations, job_allowed,
            task_node_mask=task_node_mask,
            gpu_strategy=gpu_strategy, cpu_strategy=cpu_strategy,
            pipeline_only=True)
        return result.job_success[0]

    return jax.vmap(one)(prefix_rel)
