"""Batched scenario feasibility: score K victim prefixes in one call.

The scenario solvers (actions/solvers.py, mirroring
pkg/scheduler/actions/common/solvers/job_solver.go:47-90) accumulate
victims one step at a time and simulate each prefix — one device round
trip per scenario.  On a tunneled device every round trip costs ~RTT, so
worst-case reclaim latency is scenario-count-bound (SURVEY §7.6 /
BASELINE config #3 call this out).

This kernel evaluates ALL prefixes at once: prefix k's node state is the
live state plus the cumulative released resources of victims 1..k (an
eviction moves a victim's request into the releasing pool), and the
pending job's pipeline-only placement attempt vmaps over that leading
axis.  The result is a [K] feasibility vector from ONE device call; the
solver then exact-confirms only the smallest feasible prefix through the
ordinary statement path (validators, victim re-placement, masks), so
semantics stay identical to the sequential search.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .allocate import allocate_jobs_kernel
from .scoring import BINPACK


@functools.partial(jax.jit,
                   static_argnames=("gpu_strategy", "cpu_strategy"))
def batch_prefix_feasibility(node_allocatable, node_idle, node_labels,
                             node_taints, prefix_releasing, node_room,
                             task_req, task_job, task_selector,
                             task_tolerations, task_node_mask=None,
                             gpu_strategy: int = BINPACK,
                             cpu_strategy: int = BINPACK) -> jnp.ndarray:
    """[K] bool: can the pending job pipeline onto each prefix's released
    resources?

    prefix_releasing: [K,N,R] releasing pool per prefix (live releasing +
    cumulative victim releases).  node_room: [N] — prefix-invariant, since
    evicted pods stay on their node as Releasing; broadcast, not tiled.
    Static node tables (allocatable/labels/taints) and the pending job's
    task rows are shared across the batch.
    """
    job_allowed = jnp.ones(1, bool)

    def one(prefix_rel):
        result = allocate_jobs_kernel(
            node_allocatable, node_idle, prefix_rel, node_labels,
            node_taints, node_room, task_req, task_job, task_selector,
            task_tolerations, job_allowed,
            task_node_mask=task_node_mask,
            gpu_strategy=gpu_strategy, cpu_strategy=cpu_strategy,
            pipeline_only=True)
        return result.job_success[0]

    return jax.vmap(one)(prefix_releasing)
