"""Topology-aware scheduling (TAS): domain trees as segment ops.

Re-designs pkg/scheduler/plugins/topology/ for the device: the reference
walks a pointer tree of domains per job (job_filtering.go:34-111,
calcSubTreeFreeResources :192, calcNodeAccommodation :213,
getJobAllocatableDomains :265, sortTree :460, getJobRatioToFreeResources
:491); here every topology level is a segment-id vector over the node axis,
so per-domain free-resource aggregation and gang-accommodation counting are
``segment_sum``s over the packed node state — one fused kernel per level
instead of a tree walk per job.

Semantics preserved:
- a domain fits a gang iff the gang's total request fits the domain's
  idle+releasing pool AND enough whole pods fit stackwise on its nodes;
- candidate levels run from the preferred level up to the required level
  (calculateRelevantDomainLevels :381-424); required-only means exactly
  that level; preferred-only climbs to the root;
- fitting domains are ordered most-packed-first (ratio of requested to
  free, descending — bin-pack, docs/topology/README.md:50-53), ties by
  domain id;
- a job with running pods and a required constraint is pinned to the
  domains already hosting its pods (getRelevantDomainsWithAllocatedPods);
- nodes inside preferred-level domains get a Topology-tier score boost
  (node_scoring.go:17-55) scaled by domain rank.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from .scoring import TOPOLOGY

ROOT_LEVEL = "__root__"
# Ratio assigned when a required resource doesn't exist in the domain.
IMPOSSIBLE_RATIO = 1e9


@dataclass
class TopologyTree:
    """Host-side encoding of one Topology CRD over the packed node axis."""
    name: str
    levels: list                      # deepest-last label keys, as in CRD
    # Per level: [N] int32 domain index (-1 = node lacks the label chain).
    node_domain: dict = field(default_factory=dict)   # level -> np.ndarray
    domain_names: dict = field(default_factory=dict)  # level -> [id->path]

    def num_domains(self, level: str) -> int:
        return len(self.domain_names.get(level, []))


def build_tree(name: str, levels: list, node_names: list,
               node_labels_by_name: dict) -> TopologyTree:
    """Group nodes into domains per level.  A domain's identity is the
    label-value path from the top level down (topology_structs.go:20-94)."""
    tree = TopologyTree(name, list(levels))
    n = len(node_names)
    # Root level: every node in domain 0.
    tree.node_domain[ROOT_LEVEL] = np.zeros(n, np.int32)
    tree.domain_names[ROOT_LEVEL] = ["root"]
    path_so_far = [() for _ in range(n)]
    for depth, label_key in enumerate(levels):
        ids: dict[tuple, int] = {}
        seg = np.full(n, -1, np.int32)
        names = []
        for i, node in enumerate(node_names):
            value = node_labels_by_name.get(node, {}).get(label_key)
            if value is None or path_so_far[i] is None:
                path_so_far[i] = None
                continue
            path_so_far[i] = path_so_far[i] + (value,)
            key = path_so_far[i]
            if key not in ids:
                ids[key] = len(names)
                names.append("/".join(key))
            seg[i] = ids[key]
        tree.node_domain[label_key] = seg
        tree.domain_names[label_key] = names
    return tree


@functools.partial(jax.jit, static_argnames=("num_domains",))
def domain_aggregates(node_free, node_room, seg, max_pod_req, gang_size,
                      num_domains: int):
    """Per-domain (free [D,R], pod-accommodation count [D]).

    Accommodation mirrors calcNodeAccommodation: per node, how many
    max-sized gang pods stack into idle+releasing resources, summed over
    the domain (capped at gang_size per node).
    """
    member = seg >= 0
    seg_safe = jnp.where(member, seg, 0)
    free = jax.ops.segment_sum(
        jnp.where(member[:, None], node_free, 0.0), seg_safe,
        num_segments=num_domains)
    per_res = jnp.where(max_pod_req[None, :] > 0,
                        jnp.floor(node_free / jnp.where(
                            max_pod_req[None, :] > 0, max_pod_req[None, :],
                            1.0)),
                        jnp.inf)
    fit = jnp.min(per_res, axis=1)
    fit = jnp.minimum(fit, node_room)
    fit = jnp.clip(fit, 0.0, gang_size)
    pods = jax.ops.segment_sum(jnp.where(member, fit, 0.0), seg_safe,
                               num_segments=num_domains)
    return free, pods


class TopologySession:
    """Per-session TAS state: registered by the topology plugin."""

    def __init__(self, ssn):
        self.ssn = ssn
        self.trees: dict[str, TopologyTree] = {}
        node_labels = {name: ssn.cluster.nodes[name].labels
                       for name in ssn.snapshot.node_names
                       if name in ssn.cluster.nodes}
        for name, spec in ssn.cluster.topologies.items():
            levels = list(spec.get("levels", []))
            self.trees[name] = build_tree(
                name, levels, ssn.snapshot.node_names, node_labels)
        # job uid -> [N] preferred-level score boosts (set by subset_nodes).
        # kairace: single-writer=main
        self._job_node_scores: dict[str, np.ndarray] = {}
        # tree name -> rankplace.TopoOrder (built lazily, once per
        # session: pure function of the tree + packed node order).
        # kairace: single-writer=main
        self._topo_orders: dict[str, object] = {}

    # -- constraint resolution ---------------------------------------------
    def _job_constraint(self, job, podset=None):
        """Podset-level constraints override the job-level ones
        (subgroup TopologyConstraint, topology_plugin.go)."""
        required = job.required_topology_level
        preferred = job.preferred_topology_level
        topo_name = job.topology_name
        if podset is not None and podset.has_own_topology_constraint():
            required = podset.required_topology_level
            preferred = podset.preferred_topology_level
            topo_name = podset.topology_name or topo_name
        tree = self.trees.get(topo_name or next(iter(self.trees), ""))
        if tree is None:
            return None
        if not required and not preferred:
            return None
        return tree, required, preferred

    def _relevant_levels(self, tree: TopologyTree, required, preferred):
        """calculateRelevantDomainLevels: deepest -> root, collect from
        preferred/required until required (inclusive)."""
        ordered = list(reversed(tree.levels)) + [ROOT_LEVEL]
        out, collecting = [], False
        for level in ordered:
            if level == preferred or level == required:
                collecting = True
            if collecting:
                out.append(level)
            if level == required:
                break
        return out

    # -- the SubsetNodes extension point -----------------------------------
    def subset_nodes(self, job, tasks, podset=None):
        constraint = self._job_constraint(job, podset)
        if constraint is None:
            return None
        tree, required, preferred = constraint
        ssn = self.ssn
        n_pad = ssn.node_idle.shape[0]
        n = len(ssn.snapshot.node_names)

        reqs = np.stack([ssn._task_row(t)[0] for t in tasks]) \
            if tasks else np.zeros((1, ssn.node_idle.shape[1]))
        total_req = reqs.sum(axis=0)
        max_pod_req = reqs.max(axis=0)
        gang_size = len(tasks)
        node_free = (ssn.node_idle + ssn.node_releasing)[:n]
        node_room = ssn.node_room[:n]

        # Pin to domains already hosting running pods of the podset(s)
        # being allocated (getRelevantDomainsWithAllocatedPods takes the
        # podSets under allocation, not the whole job) when required is set.
        pinned_domains = None
        if required and required in tree.node_domain:
            pods = (podset.pods.values() if podset is not None
                    else job.pods.values())
            active_nodes = {t.node_name for t in pods
                            if t.is_active_allocated() and t.node_name}
            if active_nodes:
                seg_req = tree.node_domain[required]
                pinned_domains = {
                    int(seg_req[ssn.node_index(node)])
                    for node in active_nodes
                    if ssn.node_index(node) >= 0
                    and seg_req[ssn.node_index(node)] >= 0}

        candidates = []  # (level_rank, ratio, domain_name, mask)
        self._job_node_scores.pop(job.uid, None)
        for level_rank, level in enumerate(
                self._relevant_levels(tree, required, preferred)):
            seg = tree.node_domain.get(level)
            if seg is None:
                continue
            d = tree.num_domains(level)
            if d == 0:
                continue
            free, pods = domain_aggregates(
                jnp.asarray(node_free), jnp.asarray(node_room),
                jnp.asarray(seg), jnp.asarray(max_pod_req),
                float(gang_size), d)
            free = np.asarray(free)
            pods = np.asarray(pods)
            for dom in range(d):
                if pinned_domains is not None and level == required \
                        and dom not in pinned_domains:
                    continue
                if pods[dom] < gang_size:
                    continue
                if np.any(total_req > free[dom] + 1e-9):
                    continue
                ratio = _pack_ratio(total_req, free[dom])
                mask = np.zeros(n_pad, bool)
                mask[:n] = seg == dom
                if pinned_domains is not None and level != required:
                    # Sub/ancestor domains must intersect the pinned set.
                    seg_req = tree.node_domain[required]
                    pin_mask = np.isin(seg_req, list(pinned_domains))
                    if not np.any(mask[:n] & pin_mask):
                        continue
                candidates.append(
                    (level_rank, -ratio, tree.domain_names[level][dom],
                     mask))

        if not candidates:
            job.add_fit_error(
                f"no topology domain of {tree.name} can host the gang "
                f"(required={required}, preferred={preferred})")
            return []
        candidates.sort(key=lambda c: (c[0], c[1], c[2]))

        # Preferred-level boost: nodes of better-ranked preferred domains
        # score higher (node_scoring.go).
        if preferred:
            boosts = np.zeros(n_pad)
            rank = 0
            for level_rank, _, _, mask in candidates:
                if level_rank == 0:  # preferred level entries come first
                    boosts = np.maximum(
                        boosts, mask * (TOPOLOGY / (rank + 1)))
                    rank += 1
            self._job_node_scores[job.uid] = boosts

        return [mask for _, _, _, mask in candidates]

    # -- rank-aware placement (ops/rankplace.py) ---------------------------
    def _topo_order_for(self, tree):
        from . import rankplace as rp
        order = self._topo_orders.get(tree.name)
        if order is None:
            order = rp.build_topo_order(tree, self.ssn.node_idle.shape[0])
            self._topo_orders[tree.name] = order
        return order

    def assign_ranks(self, tasks, placements):
        """Rank-aware reorder of one placed gang chunk
        (ssn.rank_assign_fns contract): returns the permuted
        [(task, node, piped)] list, or None to keep the rank-oblivious
        assignment.

        Preconditions verified here (cheap, O(gang)):
        - every task carries a distinct non-negative rank;
        - the tasks are interchangeable (identical request vector,
          node selector, and toleration set) — permuting them across
          the fill plan's slots then changes nothing but which rank
          runs where.
        The (node, piped) pairs permute as units: pipelined-ness
        belongs to the slot's capacity phase, not the task.
        """
        from ..utils.metrics import METRICS
        from ..utils.tracing import TRACER
        from . import rankplace as rp
        if len(placements) < 2 or not self.trees:
            return None
        chunk = [t for t, _n, _p in placements]
        ranks = [t.rank for t in chunk]
        if min(ranks) < 0 or len(set(ranks)) != len(ranks):
            return None
        t0 = chunk[0]
        req0 = t0.res_req.to_vec(mig_as_gpu=False)
        for t in chunk[1:]:
            if (t.node_selector != t0.node_selector
                    or t.tolerations != t0.tolerations
                    or not np.array_equal(
                        t.res_req.to_vec(mig_as_gpu=False), req0)):
                return None
        job = self.ssn.cluster.podgroups.get(t0.job_id)
        topo_name = getattr(job, "topology_name", None) if job else None
        tree = self.trees.get(topo_name) if topo_name else None
        if tree is None:
            tree = next(iter(self.trees.values()))
        order = self._topo_order_for(tree)
        ssn = self.ssn
        slot_nodes = np.empty(len(placements), np.int32)
        for i, (_t, node_name, _p) in enumerate(placements):
            idx = ssn.node_index(node_name)
            if idx < 0:
                return None
            slot_nodes[i] = idx
        mode = rp.resolve_mode(None, len(placements))
        with TRACER.span("rankplace", kind="rankplace",
                         gang=len(placements), tree=tree.name,
                         mode=mode) as sp:
            if mode == "kernel":
                t_len = len(placements)
                # rank_place_padded buckets the gang axis to pow2 so
                # fleets of varied gang sizes share one compilation.
                perm, hops = ssn.dispatch_kernel(
                    lambda: rp.rank_place_padded(
                        slot_nodes, order.topo_rank, order.level_segs),
                    label="rank_place",
                    validate=lambda r: getattr(
                        r[0], "shape", (0,))[0] == t_len)
                perm = np.asarray(perm)
                hops = np.asarray(hops)
            else:
                perm, hops = rp.rank_place_np(
                    slot_nodes, order.topo_rank, order.level_segs)
            mean = float(hops.mean()) if hops.size else 0.0
            sp.set(mean_hop=round(mean, 3))
        METRICS.inc("rank_place_assignments_total", mode=mode)
        METRICS.set_gauge("rank_place_mean_hop", mean)
        by_rank = sorted(range(len(chunk)), key=lambda i: chunk[i].rank)
        return [(chunk[by_rank[k]], placements[int(perm[k])][1],
                 placements[int(perm[k])][2])
                for k in range(len(placements))]

    # -- the extra-score extension point -----------------------------------
    def extra_scores(self, tasks):
        if not tasks:
            return None
        boosts = self._job_node_scores.get(tasks[0].job_id)
        if boosts is None:
            return None
        return np.tile(boosts, (len(tasks), 1))


def _pack_ratio(total_req: np.ndarray, free: np.ndarray) -> float:
    """getJobRatioToFreeResources: dominant requested/free ratio."""
    ratio = 0.0
    for i in range(total_req.shape[0]):
        if total_req[i] <= 0:
            continue
        if free[i] <= 0:
            ratio = max(ratio, IMPOSSIBLE_RATIO)
        else:
            ratio = max(ratio, float(total_req[i] / free[i]))
    return ratio
