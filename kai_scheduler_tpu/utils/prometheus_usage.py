"""Prometheus-backed historical-usage client for time-based fairness.

Mirrors pkg/scheduler/cache/usagedb/prometheus/prometheus.go:29-113: the
scheduler's own queue-allocation gauges (kai_queue_allocated_*) are scraped
by Prometheus; each fetch builds a window query — sliding
(``sum_over_time((m)[window:resolution])`` via /api/v1/query, :217-229) or
tumbling/cron (``sum_over_time(m)`` over a /api/v1/query_range from the
last window reset, :231-250) — optionally multiplied by the exponential
half-life decay term ``0.5^((now - time()) / half_life)`` (:290-299), and
normalizes per-queue usage by cluster capacity from
``sum(kube_node_status_capacity{resource=...})`` (:70-76,140-143).

Transport is stdlib urllib against the Prometheus HTTP API; the fetch-loop
caching + staleness semantics of usagedb.go (defaultFetchInterval 1m,
staleness 5x) live here too, so the scheduler reads a cached snapshot
between fetches and degrades to "no usage data" when stale.
"""

from __future__ import annotations

import json
import math
import time
import urllib.parse
import urllib.request

import numpy as np

from ..api import resources as rs
from .logging import LOG
from .usagedb import UsageLister, UsageParams

QUEUE_NAME_LABEL = "queue_name"

# Resource axis -> (allocation metric param/default, capacity param/default);
# prometheus.go:64-76.
DEFAULT_ALLOCATION_METRICS = {
    rs.RES_GPU: ("gpuAllocationMetric", "kai_queue_allocated_gpus"),
    rs.RES_CPU: ("cpuAllocationMetric", "kai_queue_allocated_cpu_cores"),
    rs.RES_MEM: ("memoryAllocationMetric", "kai_queue_allocated_memory_bytes"),
}
DEFAULT_CAPACITY_METRICS = {
    rs.RES_GPU: ("gpuCapacityMetric",
                 'sum(kube_node_status_capacity{resource="nvidia_com_gpu"})'),
    rs.RES_CPU: ("cpuCapacityMetric",
                 'sum(kube_node_status_capacity{resource="cpu"})'),
    rs.RES_MEM: ("memoryCapacityMetric",
                 'sum(kube_node_status_capacity{resource="memory"})'),
}


class PrometheusUsageClient(UsageLister):
    def __init__(self, address: str, params: UsageParams | None = None,
                 extra: dict | None = None, now_fn=time.time):
        self.address = address.rstrip("/")
        self.params = params or UsageParams()
        extra = extra or {}
        self.now_fn = now_fn
        self.query_timeout = float(extra.get("usageQueryTimeout", 10.0))
        self.resolution = float(extra.get("queryResolution", 60.0))
        self.allocation_metrics = {
            i: extra.get(key, default)
            for i, (key, default) in DEFAULT_ALLOCATION_METRICS.items()}
        self.capacity_metrics = {
            i: extra.get(key, default)
            for i, (key, default) in DEFAULT_CAPACITY_METRICS.items()}
        # Tumbling windows anchor at an explicit start time (prometheus.go
        # requires TumblingWindowStartTime when WindowType == tumbling).
        self.tumbling_start = float(extra.get("tumblingWindowStartTime", 0.0))
        # Fetch-loop cache (usagedb.go:17-40).
        self.fetch_interval = self.params.fetch_interval_seconds
        self._cached: dict | None = None
        self.last_fetch_ts: float | None = None

    # -- query building ----------------------------------------------------
    def _decay_expr(self, metric: str) -> str:
        hl = self.params.half_life_period_seconds
        if not hl:
            return metric
        now = int(self.now_fn())
        return f"(({metric}) * (0.5^(({now} - time()) / {hl:f})))"

    def _latest_reset_time(self, now: float) -> float:
        window = self.params.window_size_seconds
        elapsed = now - self.tumbling_start
        return self.tumbling_start + math.floor(elapsed / window) * window

    def _http_get(self, path: str, query_params: dict) -> dict:
        qs = urllib.parse.urlencode(query_params)
        with urllib.request.urlopen(f"{self.address}{path}?{qs}",
                                    timeout=self.query_timeout) as resp:
            payload = json.loads(resp.read())
        if payload.get("status") != "success":
            raise RuntimeError(f"prometheus error: {payload}")
        return payload["data"]

    def _query_window(self, metric: str) -> list:
        """Run the windowed query; returns a list of
        (labels, summed value) samples."""
        decayed = self._decay_expr(metric)
        if self.params.window_type == "sliding":
            window = int(self.params.window_size_seconds)
            step = int(self.resolution)
            expr = f"sum_over_time(({decayed})[{window}s:{step}s])"
            data = self._http_get("/api/v1/query",
                                  {"query": expr, "time": self.now_fn()})
            return [(r["metric"], float(r["value"][1]))
                    for r in data.get("result", [])]
        # Tumbling: sum since the last window reset.  Expressed as a valid
        # PromQL subquery over the elapsed-since-reset range (the Go
        # reference's bare sum_over_time over a range query is not valid
        # PromQL — this realizes the same sum-since-reset semantics).
        now = self.now_fn()
        since = max(int(now - self._latest_reset_time(now)),
                    int(self.resolution))
        step = int(self.resolution)
        expr = f"sum_over_time(({decayed})[{since}s:{step}s])"
        data = self._http_get("/api/v1/query",
                              {"query": expr, "time": now})
        return [(r["metric"], float(r["value"][1]))
                for r in data.get("result", [])]

    # -- fetch + normalize (GetResourceUsage, prometheus.go:113-147) -------
    def fetch(self) -> dict:
        usage: dict[str, np.ndarray] = {}
        for i in range(rs.NUM_RES):
            samples = self._query_window(self.capacity_metrics[i])
            capacity = samples[0][1] if samples else 1.0
            if capacity <= 0:
                capacity = 1.0
            for labels, value in self._query_window(
                    self.allocation_metrics[i]):
                queue = labels.get(QUEUE_NAME_LABEL, "")
                if not queue:
                    continue
                vec = usage.setdefault(queue, rs.zeros())
                vec[i] = value / capacity
        return usage

    # -- UsageLister surface ----------------------------------------------
    def queue_usage(self, now: float):
        from .usagedb import UsageSnapshot
        data = None
        if (self._cached is not None and self.last_fetch_ts is not None
                and now - self.last_fetch_ts < self.fetch_interval):
            data = self._cached
        else:
            try:
                self._cached = self.fetch()
                self.last_fetch_ts = now
            except Exception as exc:  # keep serving the cache until stale
                LOG.warning("prometheus usage fetch failed: %s", exc)
                if self._cached is None or self.is_stale(now):
                    data = {}
            if data is None:
                data = self._cached or {}
        # Staleness rides the snapshot: the proportion plugin must see a
        # scrape outage as "ignore usage" (degraded mode,
        # docs/DEGRADATION.md), never as authoritative zeros.
        snap = UsageSnapshot(data)
        snap.ts = now
        snap.stale = self.is_stale(now)
        return snap

    def is_stale(self, now: float) -> bool:
        return (self.last_fetch_ts is None
                or now - self.last_fetch_ts
                > self.params.staleness_period_seconds)
