"""Wire observatory: shared byte/syscall accounting + server span ring.

PR 19 makes the control-plane transport measurable end to end.  Both
dialect ends (``controllers/httpclient.HTTPKubeAPI`` and
``controllers/apiserver.KubeAPIServer``) funnel their accounting through
THIS module so the metric families keep one label-key set at every call
site (the KAI008 metrics-hygiene contract) and so the two ends agree on
what a request class is:

- ``wire_bytes_total{path,dir,end}``: request/response BODY bytes (and
  watch frame bytes) per request class, direction (``in``/``out``) and
  dialect end (``client``/``server``).  Body bytes, not raw socket
  bytes: the reconciliation contract (tests/test_wiretrace.py) is
  client-sent body bytes == server-received body bytes ± faulted or
  refused requests, which header framing would blur.
- ``wire_syscalls_total{path,op,end}``: sendall/recv *call* counts per
  request class — the structural cost the future binary-codec PR
  (ROADMAP item 1) must drive down.  One count per logical send/recv
  call at the seam, deterministic, not a strace.
- ``frame_cache_bytes_total{src}``: bytes served from the preserialized
  frame cache (``src="cache"``) vs bytes that paid a fresh
  ``json.dumps`` (``src="encode"``) — the BYTE-weighted companion of
  ``watch_frame_cache_hits/misses_total``, gated as a hit ratio by
  tools/fleet_budget.py.
- ``watch_fanout_frames_total{stream}`` / ``watch_fanout_bytes_total
  {stream}``: per-watcher fanout volume, labeled by the watcher's
  bounded stream slot (< MAX_WATCH_STREAMS, never a client identity —
  label cardinality stays bounded by construction).
- ``watch_fanout_lag_frames{stream}`` (gauge): frames still buffered
  in the event ring behind this watcher after its last burst — the
  "slowest watcher" blind spot.
- ``watch_stream_queue_depth{stream}`` (gauge): the send-queue depth
  of one streamer at burst time; a depth beyond ``watch_queue_cap()``
  answers an explicit GONE (``watch_stream_depth_gone_total``) instead
  of buffering without bound.

``SpanRing`` is the apiserver's bounded buffer of completed server-side
span records, served at ``GET /debug/spans?since=`` and grafted into
the scheduler's flight-recorder traces by ``Tracer.graft_remote_spans``
(utils/tracing.py).  All timing near this module is
``time.perf_counter`` (KAI003).
"""

from __future__ import annotations

import itertools
import os
import threading
from collections import deque

from .metrics import METRICS

# Request classes both ends agree on (the `path` label's vocabulary).
PATH_CLASSES = ("list", "get", "mutate", "bulk", "watch", "digest")

SPAN_RING_DEFAULT = 2048


def watch_queue_cap() -> int:
    """Max frames a watch streamer may buffer for one burst before the
    watcher is declared too slow and answered GONE (satellite fix:
    previously a stalled watcher could accumulate the whole event ring
    into one in-flight buffer).  Env-tunable per test."""
    try:
        return max(1, int(os.environ.get("KAI_WATCH_QUEUE_CAP", 10000)))
    except ValueError:
        return 10000


def path_class(method: str, path: str) -> str:
    """Classify one request path into the bounded `path` label set.
    Shared by both dialect ends so client-sent and server-received
    series line up key for key."""
    if path.startswith("/watch"):
        return "watch"
    if path.startswith("/bulk"):
        return "bulk"
    if path.startswith("/digest"):
        return "digest"
    if path.startswith("/relist"):
        return "list"
    if path.startswith("/apis"):
        parts = [p for p in path.partition("?")[0].split("/") if p]
        named = len(parts) > 3  # /apis/{kind}/{ns}/{name}
        if method == "GET":
            return "get" if named else "list"
        return "mutate"
    return "get"  # /healthz, /debug/*, unknown routes


def count_bytes(end: str, path: str, direction: str, n: int) -> None:
    """``wire_bytes_total{dir,end,path}`` — body bytes at one seam."""
    if n:
        METRICS.inc("wire_bytes_total", float(n),
                    dir=direction, end=end, path=path)


def count_syscall(end: str, path: str, op: str, n: int = 1) -> None:
    """``wire_syscalls_total{end,op,path}`` — sendall/recv call counts."""
    METRICS.inc("wire_syscalls_total", float(n),
                end=end, op=op, path=path)


def count_frame_bytes(src: str, n: int) -> None:
    """``frame_cache_bytes_total{src}`` — cache-served vs freshly
    encoded bytes (src ``cache`` | ``encode``)."""
    if n:
        METRICS.inc("frame_cache_bytes_total", float(n), src=src)


def note_fanout(stream: int, frames: int, nbytes: int, lag: int) -> None:
    """One watch fanout burst shipped to stream slot ``stream``."""
    slot = str(stream)
    if frames:
        METRICS.inc("watch_fanout_frames_total", float(frames),
                    stream=slot)
    if nbytes:
        METRICS.inc("watch_fanout_bytes_total", float(nbytes),
                    stream=slot)
    METRICS.set_gauge("watch_fanout_lag_frames", float(max(0, lag)),
                      stream=slot)


def note_stream_depth(stream: int, depth: int) -> None:
    """``watch_stream_queue_depth{stream}`` — the streamer's send-queue
    depth (frames pending behind its cursor) at burst time."""
    METRICS.set_gauge("watch_stream_queue_depth", float(depth),
                      stream=str(stream))


# Counter families the per-cycle `wire` section and the fleet budget
# fold over (gauges are point-in-time, not deltas — excluded).
WIRE_COUNTER_FAMILIES = (
    "wire_bytes_total",
    "wire_syscalls_total",
    "frame_cache_bytes_total",
    "frame_cache_serve_encodes_total",
    "watch_fanout_frames_total",
    "watch_fanout_bytes_total",
    "watch_frame_cache_hits_total",
    "watch_frame_cache_misses_total",
    "watch_stream_depth_gone_total",
)


def wire_totals() -> dict:
    """Flat snapshot of every wire-observatory counter series, keyed by
    the rendered series name — ``/debug/cycles``' top-level ``wire``
    section, and the operand of ``wire_delta`` for the per-cycle
    section each CycleTrace carries."""
    out = {}
    # Lock-free read of a monotonically growing counter dict: at worst
    # one tick stale (the Metrics read contract).
    for key, value in list(METRICS.counters.items()):
        if key.partition("{")[0] in WIRE_COUNTER_FAMILIES:
            out[key] = value
    return out


def wire_delta(prev: dict, cur: dict) -> dict:
    """Series that moved between two ``wire_totals`` snapshots."""
    return {key: round(value - prev.get(key, 0), 3)
            for key, value in cur.items()
            if value != prev.get(key, 0)}


class SpanRing:
    """Bounded ring of completed server-side span records.

    The apiserver records one dict per finished request (phases,
    byte counts, the client's injected trace context) and per watch
    fanout burst; ``GET /debug/spans?since=N`` serves the tail past a
    client cursor.  Records carry contiguous monotone ids, so the
    ``since`` read is a tail slice (O(result)), exactly like
    ``EventLog.since``.  Bounded by construction: a scheduler that
    never pulls costs the server ``capacity`` dicts, not memory
    proportional to uptime."""

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("KAI_SERVER_SPAN_RING",
                                              SPAN_RING_DEFAULT))
            except ValueError:
                capacity = SPAN_RING_DEFAULT
        self.capacity = max(16, capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._next = 0

    def record(self, rec: dict) -> int:
        """Append one completed span record; returns its id."""
        with self._lock:
            self._next += 1
            rec = dict(rec)
            rec["id"] = self._next
            self._ring.append(rec)
            return self._next

    def since(self, after: int) -> tuple[int, list]:
        """(head_id, records with id > after).  A cursor from before
        the ring's horizon simply yields the whole retained window —
        span records are observability, not state: missing ones are
        counted by the ring's bound, never a correctness gap."""
        with self._lock:
            head = self._next
            missing = head - after
            if missing <= 0:
                return head, []
            if missing >= len(self._ring):
                return head, list(self._ring)
            tail = list(itertools.islice(reversed(self._ring), missing))
            tail.reverse()
            return head, tail

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
