"""Continuous sampling profiler — the pprof/Pyroscope analog.

Mirrors the reference's always-on profiling surface
(/root/reference/cmd/scheduler/profiling/profiler.go:14 net/http/pprof,
pyroscope.go:13 continuous profiles): a daemon thread samples every live
Python thread's stack at a fixed interval and aggregates collapsed
stacks (pprof "folded" format — one line per unique stack with a sample
count, flamegraph-ready).  Pure stdlib, a few microseconds per sample;
JAX device time is covered separately by the ``--profile-dir``
jax.profiler trace flag.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter


class SamplingProfiler:
    """Collapsed-stack wall-clock sampler over all live threads."""

    def __init__(self, interval_seconds: float = 0.01,
                 max_depth: int = 64):
        self.interval = interval_seconds
        self.max_depth = max_depth
        self.samples: Counter = Counter()
        self.total_samples = 0
        self.started_at = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        # Monotonic: running_seconds is a duration — an NTP step must
        # not produce a negative or inflated profile window.
        self.started_at = time.monotonic()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sampling-profiler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    # -- sampling ----------------------------------------------------------
    def _run(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.interval):
            frames = sys._current_frames()
            with self._lock:
                for tid, frame in frames.items():
                    if tid == me:
                        continue
                    stack = []
                    depth = 0
                    while frame is not None and depth < self.max_depth:
                        code = frame.f_code
                        stack.append(f"{code.co_filename.rsplit('/', 1)[-1]}"
                                     f":{code.co_name}:{frame.f_lineno}")
                        frame = frame.f_back
                        depth += 1
                    if stack:
                        self.samples[";".join(reversed(stack))] += 1
                        self.total_samples += 1

    # -- reporting ---------------------------------------------------------
    def folded(self, top: int = 5000) -> str:
        """pprof collapsed format: ``stack;frames count`` per line,
        heaviest stacks first (feed straight into flamegraph.pl /
        speedscope)."""
        with self._lock:
            lines = [f"{stack} {count}"
                     for stack, count in self.samples.most_common(top)]
        return "\n".join(lines)

    def summary(self, top: int = 30) -> dict:
        """Leaf-frame aggregation: where the wall-clock actually goes."""
        leaves: Counter = Counter()
        with self._lock:
            for stack, count in self.samples.items():
                leaves[stack.rsplit(";", 1)[-1]] += count
            total = self.total_samples
        return {
            "total_samples": total,
            "interval_seconds": self.interval,
            "running_seconds": round(time.monotonic() - self.started_at, 1)
            if self.started_at else 0.0,
            "top_leaves": [
                {"frame": frame, "samples": count,
                 "share": round(count / total, 4) if total else 0.0}
                for frame, count in leaves.most_common(top)],
        }

    def reset(self) -> None:
        with self._lock:
            self.samples.clear()
            self.total_samples = 0
