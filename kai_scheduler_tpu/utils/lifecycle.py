"""Pod-lifecycle SLO tracker: event-sourced submit→bound timelines.

Cycle time says how fast the scheduler loops; it says nothing about how
long a POD waits.  Kant (arxiv 2510.01256) reports scheduler health as
end-to-end pod latency percentiles, and the transfer-learning line of
work (arxiv 2509.22701) consumes exactly these recorded lifecycle traces
as training features — so submit→bound latency is a first-class,
continuously measured signal here, not a bench-day artifact.

Every pod the fleet touches gets a **timeline**: an ordered set of phase
timestamps fed by one-line hooks in the controllers —

    submit ─ watch_observed ─ grouped ─ snapshotted ─ scheduled
           ─ bind_requested ─ bound | evicted

``submit`` is stamped when the timeline opens (first observation);
``watch_observed``/``grouped`` come from the PodGrouper's watch handler,
``snapshotted`` from ``ClusterCache.snapshot``, ``scheduled`` from
``Statement.commit`` (carrying the cycle's trace id, so a timeline joins
the flight recorder), ``bind_requested`` from ``ClusterCache.bind`` and
``bound`` from the Binder's reconciler.  An eviction closes the current
**attempt** and the next scheduling pass opens a new one — an
evicted-and-rescheduled pod is ONE coherent timeline with two attempt
records, never a leaked open state.

Design constraints (the kailint contracts):

- all timing is monotonic (``time.perf_counter`` via an injectable
  clock — KAI003: no wall clock in utils/);
- the hot hooks are one dict probe on the no-change path: ``note`` reads
  the open-timeline map lock-free first (GIL-safe dict get) and takes
  the lock only when there is something to write — ``snapshot()`` calls
  it once per pending pod per cycle;
- memory is bounded at every layer: open timelines are capped
  (``KAI_LIFECYCLE_OPEN_CAP``, default 8192 — overflow drops the pod and
  counts ``lifecycle_open_overflow_total``), closed timelines live in a
  ring (``KAI_LIFECYCLE_RING``, default 2048), attempts per timeline cap
  at 8 with counted drops;
- per-queue metric families go through the bounded-cardinality guard in
  utils/metrics.py (overflow folds into ``other``).

Published signals:

- ``pod_latency_ms{queue=}`` histogram — submit→bound, per queue;
- ``pod_phase_latency_ms{phase=}`` histogram — time spent in each phase
  (delta to the next stamped phase) for bound pods;
- ``slo_pod_latency_burn_total{queue=}`` counter — bound pods whose
  submit→bound exceeded the pod budget (``KAI_SLO_POD_LATENCY_MS``,
  default 1000);
- ``slo_cycle_budget_burn_total`` counter — cycles over the cycle budget
  (``KAI_SLO_CYCLE_MS``, default 100; fed by ``note_cycle``);
- ``pods_in_phase{phase=}`` / ``pod_time_in_state_max_ms{phase=}``
  gauges — how many open pods sit in each phase and the oldest age;
- ``lifecycle_open_timelines`` / ``lifecycle_ring_occupancy`` gauges.

``GET /debug/latency?queue=|podgroup=`` (server.py) renders timelines
joined to the flight recorder's ``/explain`` ledger; ``summary()`` feeds
``bench.py``'s fleet phase its ``pod_latency`` section.
"""

from __future__ import annotations

import os
import time
from collections import deque

from .metrics import METRICS

PHASES = ("submit", "watch_observed", "grouped", "snapshotted",
          "scheduled", "bind_requested", "bound", "evicted")
# Phases that may open a NEW attempt after the previous one closed
# (evicted / bind_failed): the pod re-entered scheduling.
_REOPEN_PHASES = ("snapshotted", "scheduled", "bind_requested",
                  "watch_observed", "grouped")
_PHASE_INDEX = {p: i for i, p in enumerate(PHASES)}

MAX_ATTEMPTS = 8


def _env_int(name: str, default: int, lo: int = 1) -> int:
    try:
        return max(lo, int(os.environ.get(name, default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class Attempt:
    """One scheduling attempt: phase -> monotonic timestamp, plus the
    bind-retry count and the closing outcome."""

    __slots__ = ("phases", "trace_id", "node", "bind_attempts", "outcome")

    def __init__(self):
        self.phases: dict[str, float] = {}
        self.trace_id: str | None = None
        self.node: str = ""
        self.bind_attempts = 0
        self.outcome: str | None = None   # bound|evicted|bind_failed|...

    @property
    def open(self) -> bool:
        return self.outcome is None

    def to_dict(self, origin: float) -> dict:
        out = {
            "phases": {p: round((t - origin) * 1e3, 3)
                       for p, t in sorted(self.phases.items(),
                                          key=lambda kv: kv[1])},
            "outcome": self.outcome,
        }
        if self.trace_id:
            out["trace_id"] = self.trace_id
        if self.node:
            out["node"] = self.node
        if self.bind_attempts:
            out["bind_attempts"] = self.bind_attempts
        return out


class PodTimeline:
    """All attempts of one pod, newest last.  ``origin`` is the submit
    stamp every rendered offset is relative to."""

    __slots__ = ("uid", "name", "namespace", "podgroup", "queue",
                 "attempts", "dropped_attempts", "resynced", "closed",
                 "outcome", "origin", "last_ts")

    def __init__(self, uid: str, now: float):
        self.uid = uid
        self.name = ""
        self.namespace = ""
        self.podgroup = ""
        self.queue = ""
        self.attempts: list[Attempt] = [Attempt()]
        self.attempts[0].phases["submit"] = now
        self.dropped_attempts = 0
        self.resynced = False
        self.closed = False
        self.outcome: str | None = None
        self.origin = now
        self.last_ts = now

    @property
    def current(self) -> Attempt:
        return self.attempts[-1]

    def current_phase(self) -> str:
        att = self.attempts[-1]
        if not att.phases:
            return "submit"
        return max(att.phases, key=att.phases.get)


class LifecycleTracker:
    """Bounded, thread-safe pod-lifecycle store + SLO accountant."""

    def __init__(self, open_cap: int | None = None,
                 ring: int | None = None,
                 pod_budget_ms: float | None = None,
                 cycle_budget_ms: float | None = None,
                 clock=time.perf_counter):
        self.open_cap = open_cap if open_cap is not None else \
            _env_int("KAI_LIFECYCLE_OPEN_CAP", 8192)
        ring = ring if ring is not None else \
            _env_int("KAI_LIFECYCLE_RING", 2048)
        self.pod_budget_ms = pod_budget_ms if pod_budget_ms is not None \
            else _env_float("KAI_SLO_POD_LATENCY_MS", 1000.0)
        self.cycle_budget_ms = cycle_budget_ms \
            if cycle_budget_ms is not None \
            else _env_float("KAI_SLO_CYCLE_MS", 100.0)
        self.clock = clock
        import threading
        self._lock = threading.Lock()
        self._open: dict[str, PodTimeline] = {}
        self._ring: deque = deque(maxlen=max(1, ring))
        self.open_overflows = 0
        # PodGroup -> last Unschedulable message the status updater
        # shipped (bounded; /debug/latency joins it to the timelines).
        self._group_marks: dict[str, str] = {}
        self.resyncs = 0

    # -- hot hooks ---------------------------------------------------------
    def note(self, uid: str, phase: str, name: str = "",
             namespace: str = "", podgroup: str = "", queue: str = "",
             trace_id: str | None = None, node: str = "") -> None:
        """Stamp ``phase`` on the pod's current attempt (idempotent: a
        phase already stamped this attempt is a lock-free no-op — the
        common per-cycle ``snapshotted`` path).  Opens the timeline, and
        a fresh attempt after a closed one, as needed."""
        tl = self._open.get(uid)
        if tl is not None and not tl.closed \
                and phase in tl.current.phases and tl.current.open:
            return  # fast path: nothing new (GIL-safe read)
        with self._lock:
            tl = self._open.get(uid)
            if tl is None:
                if len(self._open) >= self.open_cap:
                    self.open_overflows += 1
                    METRICS.inc("lifecycle_open_overflow_total")
                    return
                tl = self._open[uid] = PodTimeline(uid, self.clock())
            att = tl.current
            if not att.open:
                if phase not in _REOPEN_PHASES:
                    return  # e.g. a late duplicate close
                if len(tl.attempts) >= MAX_ATTEMPTS:
                    tl.dropped_attempts += 1
                    return
                att = Attempt()
                tl.attempts.append(att)
            if phase in att.phases:
                return
            now = self.clock()
            att.phases[phase] = now
            tl.last_ts = now
            if name:
                tl.name = name
            if namespace:
                tl.namespace = namespace
            if podgroup:
                tl.podgroup = podgroup
            if queue:
                tl.queue = queue
            if trace_id:
                att.trace_id = trace_id
            if node:
                att.node = node

    def note_bind_attempt(self, uid: str) -> None:
        """A binder reconcile attempt failed and will back off; counted
        on the attempt so a backoff-then-success timeline shows how many
        tries the bind took."""
        with self._lock:
            tl = self._open.get(uid)
            if tl is not None and tl.current.open:
                tl.current.bind_attempts += 1

    def note_bound(self, uid: str, node: str = "") -> None:
        """Terminal success: stamp ``bound``, close the timeline, publish
        the latency histograms and SLO burn."""
        with self._lock:
            tl = self._open.pop(uid, None)
            if tl is None:
                return
            att = tl.current
            now = self.clock()
            att.phases.setdefault("bound", now)
            if node:
                att.node = node
            att.outcome = "bound"
            tl.outcome = "bound"
            tl.closed = True
            tl.last_ts = now
            self._ring.append(tl)
            total_ms = (att.phases["bound"] - tl.origin) * 1e3
            queue = tl.queue or "unknown"
            phase_deltas = _phase_deltas(att)
        # Metric publication outside the lock (KAI006: no foreign calls
        # under our lock; METRICS has its own guard).
        METRICS.observe("pod_latency_ms", total_ms, queue=queue)
        for phase, delta_ms in phase_deltas:
            METRICS.observe("pod_phase_latency_ms", delta_ms, phase=phase)
        if total_ms > self.pod_budget_ms:
            METRICS.inc("slo_pod_latency_burn_total", queue=queue)

    def note_evicted(self, uid: str) -> None:
        """The scheduler evicted the pod: the current attempt closes
        ``evicted``; the timeline stays open — a resubmit/reschedule
        opens attempt N+1 (one coherent timeline per pod)."""
        with self._lock:
            tl = self._open.get(uid)
            if tl is None or tl.closed:
                return
            att = tl.current
            if att.open:
                now = self.clock()
                att.phases.setdefault("evicted", now)
                att.outcome = "evicted"
                tl.last_ts = now
        METRICS.inc("pod_evictions_tracked_total")

    def note_bind_failed(self, uid: str) -> None:
        """Bind backoff exhausted: the attempt closes ``bind_failed``;
        the reaped pod re-enters scheduling as a new attempt."""
        with self._lock:
            tl = self._open.get(uid)
            if tl is None or tl.closed:
                return
            att = tl.current
            if att.open:
                att.outcome = "bind_failed"
                tl.last_ts = self.clock()

    def mark_vanished(self, uid: str) -> None:
        """The pod left the store (deleted / dropped out of every live
        group) without binding: close the timeline so nothing leaks.  The
        outcome keeps the last attempt's verdict (an evicted pod that was
        then deleted reads ``evicted``, not ``removed``)."""
        with self._lock:
            tl = self._open.pop(uid, None)
            if tl is None:
                return
            att = tl.current
            if att.open:
                att.outcome = "removed"
            tl.outcome = att.outcome
            tl.closed = True
            self._ring.append(tl)

    def note_resync(self) -> None:
        """A watch gap forced a re-list: open timelines survive (their
        pods are still real) but are flagged, and the event is counted —
        a resynced timeline's phase gaps may include the outage."""
        with self._lock:
            self.resyncs += 1
            for tl in self._open.values():
                tl.resynced = True

    def note_group_unschedulable(self, podgroup: str, message: str) -> None:
        """Status-updater hook: the latest Unschedulable verdict shipped
        for a PodGroup (joined into /debug/latency next to /explain)."""
        with self._lock:
            if len(self._group_marks) >= 1024 \
                    and podgroup not in self._group_marks:
                self._group_marks.clear()  # bounded in a churning fleet
            self._group_marks[podgroup] = message[:300]

    def note_cycle(self, duration_ms: float) -> None:
        """Cycle-budget SLO burn + per-cycle gauge refresh (called once
        per scheduling cycle from the cycle driver)."""
        if duration_ms > self.cycle_budget_ms:
            METRICS.inc("slo_cycle_budget_burn_total")
        self.publish_gauges()

    # -- publication -------------------------------------------------------
    def publish_gauges(self) -> None:
        now = self.clock()
        with self._lock:
            per_phase: dict[str, list] = {}
            for tl in self._open.values():
                per_phase.setdefault(tl.current_phase(), []).append(
                    tl.last_ts)
            open_n = len(self._open)
            ring_n = len(self._ring)
        METRICS.set_gauge("lifecycle_open_timelines", float(open_n))
        METRICS.set_gauge("lifecycle_ring_occupancy", float(ring_n))
        for phase in PHASES:
            stamps = per_phase.get(phase)
            METRICS.set_gauge("pods_in_phase",
                              float(len(stamps) if stamps else 0),
                              phase=phase)
            oldest_ms = ((now - min(stamps)) * 1e3) if stamps else 0.0
            METRICS.set_gauge("pod_time_in_state_max_ms",
                              round(oldest_ms, 3), phase=phase)

    # -- reads (bench, /debug/latency, /healthz, tests) --------------------
    def status(self) -> dict:
        with self._lock:
            return {"open_timelines": len(self._open),
                    "ring_occupancy": len(self._ring),
                    "ring_capacity": self._ring.maxlen,
                    "open_cap": self.open_cap,
                    "open_overflows": self.open_overflows,
                    "watch_resyncs": self.resyncs}

    def timelines(self, queue: str | None = None,
                  podgroup: str | None = None,
                  limit: int = 200) -> list[dict]:
        """Rendered timelines, newest-closed first then open ones —
        filtered by queue and/or podgroup for /debug/latency.

        Only cheap dict copies happen under the lock (the same lock the
        scheduling-path hooks contend on); the sort/round/format work of
        rendering runs after release, on the copies."""
        picked = []
        with self._lock:
            rows = list(self._ring)[::-1] + list(self._open.values())
            for tl in rows:
                if queue and tl.queue != queue:
                    continue
                if podgroup and tl.podgroup != podgroup:
                    continue
                picked.append((
                    tl.uid, tl.name, tl.namespace, tl.podgroup, tl.queue,
                    tl.outcome, tl.resynced, tl.dropped_attempts,
                    tl.origin,
                    [(dict(a.phases), a.trace_id, a.node,
                      a.bind_attempts, a.outcome) for a in tl.attempts]))
                if len(picked) >= limit:
                    break
        out = []
        for (uid, name, ns, pg, q, outcome, resynced, dropped, origin,
             attempts) in picked:
            rendered = []
            for phases, trace_id, node, bind_attempts, a_out in attempts:
                att = Attempt()
                att.phases = phases
                att.trace_id = trace_id
                att.node = node
                att.bind_attempts = bind_attempts
                att.outcome = a_out
                rendered.append(att.to_dict(origin))
            out.append({"uid": uid, "name": name, "namespace": ns,
                        "podgroup": pg, "queue": q, "outcome": outcome,
                        "resynced": resynced, "attempts": rendered,
                        "dropped_attempts": dropped})
        return out

    def group_mark(self, podgroup: str) -> str | None:
        with self._lock:
            return self._group_marks.get(podgroup)

    def summary(self) -> dict:
        """The bench's ``pod_latency`` section: submit→bound p50/p99 and
        per-phase medians over the bound timelines in the ring."""
        totals: list[float] = []
        deltas: dict[str, list] = {}
        queues: set = set()
        with self._lock:
            bound = [tl for tl in self._ring if tl.outcome == "bound"]
            for tl in bound:
                att = tl.attempts[-1]
                totals.append((att.phases["bound"] - tl.origin) * 1e3)
                queues.add(tl.queue or "unknown")
                for phase, delta_ms in _phase_deltas(att):
                    deltas.setdefault(phase, []).append(delta_ms)
        if not totals:
            return {"bound_pods": 0}
        totals.sort()

        def pct(q):
            i = min(len(totals) - 1,
                    max(0, int(round(q * (len(totals) - 1)))))
            return round(totals[i], 3)

        return {
            "bound_pods": len(totals),
            "queues": len(queues),
            "submit_to_bound_p50_ms": pct(0.5),
            "submit_to_bound_p99_ms": pct(0.99),
            "submit_to_bound_max_ms": round(totals[-1], 3),
            "phase_median_ms": {
                phase: round(sorted(v)[len(v) // 2], 3)
                for phase, v in sorted(deltas.items())},
        }

    def check_invariants(self) -> list[str]:
        """Timeline invariants the chaos matrix asserts per fault seed:
        monotone timestamps within each attempt, no closed attempt
        without an outcome, no open attempt after a closed timeline, and
        every non-final attempt closed.  Returns violations (empty =
        healthy)."""
        bad = []
        with self._lock:
            everything = list(self._ring) + list(self._open.values())
            for tl in everything:
                for i, att in enumerate(tl.attempts):
                    stamps = sorted(att.phases.items(), key=lambda kv:
                                    (kv[1], _PHASE_INDEX.get(kv[0], 99)))
                    order = [_PHASE_INDEX.get(p, 99) for p, _ in stamps]
                    if order != sorted(order):
                        bad.append(f"{tl.uid}: attempt {i} phase order "
                                   f"{[p for p, _ in stamps]}")
                    if i < len(tl.attempts) - 1 and att.open:
                        bad.append(f"{tl.uid}: non-final attempt {i} "
                                   f"still open")
                if tl.closed and tl.current.open:
                    bad.append(f"{tl.uid}: closed timeline with an open "
                               f"attempt")
                if tl.closed and tl.outcome is None:
                    bad.append(f"{tl.uid}: closed without outcome")
        return bad

    def configure_bounds(self, open_cap: int | None = None,
                         ring: int | None = None) -> dict:
        """Resize the tracker's bounds (bench fleet shapes exceed the
        daemon defaults).  Returns the PREVIOUS bounds so a caller can
        restore them; the closed ring's contents carry over up to the
        new capacity."""
        with self._lock:
            prev = {"open_cap": self.open_cap,
                    "ring": self._ring.maxlen}
            if open_cap is not None:
                self.open_cap = max(1, int(open_cap))
            if ring is not None and ring != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=max(1, int(ring)))
        return prev

    def reset(self) -> None:
        with self._lock:
            self._open.clear()
            self._ring.clear()
            self._group_marks.clear()
            self.open_overflows = 0
            self.resyncs = 0


def _phase_deltas(att: Attempt) -> list[tuple[str, float]]:
    """(phase, ms-until-next-stamp) pairs in stamp order — the
    "time spent in each state" breakdown of one attempt."""
    stamps = sorted(att.phases.items(), key=lambda kv: kv[1])
    return [(phase, (stamps[i + 1][1] - t) * 1e3)
            for i, (phase, t) in enumerate(stamps[:-1])]


# Process-wide tracker, like METRICS and TRACER: hooks in controllers,
# the statement, and the binder record into it without plumbing; the
# server and bench read it back out.
LIFECYCLE = LifecycleTracker()
