"""Historical-usage store for time-based fair share.

Mirrors pkg/scheduler/cache/usagedb/ (UsageLister usagedb.go:20-138,
client resolver hub.go:26-69, prometheus impl prometheus.go:29-113 with
sliding/tumbling/cron windows and half-life decay, params
api/interface.go:44-49): the scheduler fetches per-queue normalized
historical usage each cycle and feeds it into the fair-share usage penalty
``w' = max(0, W' + k(W' - U'))``.

The in-memory implementation is TENSOR-BACKED (DESIGN §13): the whole
fleet's history lives as one ``[Q, R]`` decayed integral plus a decayed
weight scalar, folded once per cycle by the jitted
``ops/usage.usage_decay_kernel`` (single dispatch — the per-cycle cost
the queue-forest kernel's argument demands, structurally pinned by
tools/fleet_budget.py).  ``queue_usage`` then serves the
exponentially-weighted average allocation per queue, normalized by
cluster capacity when known — no per-sample host loop anywhere.

Persistence follows the commit-log pattern (utils/commitlog.py wire
format): ``UsageLog`` appends one CRC-guarded checkpoint line per fold
and compacts atomically, so a scheduler restart replays the last valid
checkpoint and the usage penalty survives the process
(``attach_log``/``restore`` — asserted by tests/test_timeaware.py).

Staleness: ``is_stale`` tracks the last RECORD (data ingest), not the
last fetch — a wedged recorder must trip the proportion plugin's
degraded mode (ignore usage, count ``usage_stale_cycles_total``,
docs/DEGRADATION.md) instead of silently serving decayed-to-zero
values forever.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

import numpy as np

from ..api import resources as rs
from .logging import LOG
from .metrics import METRICS


@dataclass
class UsageParams:
    half_life_period_seconds: float | None = None  # decay; None = flat
    window_size_seconds: float = 3600.0
    window_type: str = "sliding"  # sliding | tumbling
    fetch_interval_seconds: float = 60.0
    staleness_period_seconds: float = 300.0


class UsageSnapshot(dict):
    """``queue_usage`` result: {queue: [R] normalized usage} plus the
    staleness verdict the proportion plugin keys its degraded mode on."""

    stale: bool = False
    ts: float = 0.0


class UsageLister:
    """Interface: queue_usage(now) -> UsageSnapshot."""

    def queue_usage(self, now: float) -> UsageSnapshot:
        raise NotImplementedError

    def record(self, now: float, queue: str, allocated: np.ndarray,
               duration: float = 1.0) -> None:
        """Ingest one cycle's allocation sample.  No-op for clients whose
        history lives elsewhere (Prometheus scrapes the gauges itself)."""

    def record_cycle(self, now: float, allocations: dict,
                     duration: float = 1.0) -> None:
        """Ingest one WHOLE cycle's {queue: [R] allocated} and fold it —
        the one-dispatch fast path ``System._record_decisions`` uses."""
        for queue, vec in allocations.items():
            self.record(now, queue, vec, duration)


class UsageLog:
    """Checkpoint journal for the usage tensor — the commit-log pattern
    (utils/commitlog.py wire format: ``<crc32 hex> <canonical JSON>``
    per line, torn-tail safe, atomic compaction).

    Each fold appends one full-state checkpoint; ``load`` trusts the
    LAST valid line (a torn tail from a crash mid-append falls back to
    the previous checkpoint).  The file compacts — rewrite with only
    the latest state via tmp+fsync+rename — every ``compact_every``
    appends, bounding it at O(one checkpoint)."""

    def __init__(self, path: str, compact_every: int = 64,
                 fsync: bool = True):
        self.path = path
        self.compact_every = compact_every
        self.fsync = fsync
        self._appends = 0
        # True after a load() that hit a torn tail or CRC mismatch:
        # the restore fell back to an OLDER checkpoint (or none), and
        # the owner must degrade LOUDLY, not serve it as current.
        self.last_load_corrupt = False

    def append(self, state: dict) -> None:
        from .commitlog import _encode
        self._appends += 1
        if self._appends >= self.compact_every:
            self.compact(state)
            return
        with open(self.path, "ab") as f:
            f.write(_encode(state))
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())

    def compact(self, state: dict) -> None:
        from .commitlog import _encode
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_encode(state))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._appends = 0

    def load(self) -> dict | None:
        from .commitlog import _decode
        self.last_load_corrupt = False
        try:
            with open(self.path, "rb") as f:
                lines = f.readlines()
        except OSError:
            return None
        state = None
        for line in lines:
            rec = _decode(line)
            if rec is None:
                # Torn tail (crash mid-append) or bit rot: trust
                # everything before it — but REPORT it, because the
                # restored state is older than the history claims and
                # the fairness penalty computed from it is, too.
                self.last_load_corrupt = True
                break
            state = rec
        return state


class InMemoryUsageDB(UsageLister):
    """Tensor-backed sliding-window usage with half-life decay.

    ``record``/``record_cycle`` buffer one cycle's allocation samples;
    the fold (lazy, at the next fetch or explicit ``record_cycle``)
    applies the half-life factor to the standing integral and adds the
    sample — ONE jitted dispatch over a pow2-padded ``[Q, R]`` tensor
    (``usage_decay_dispatch_total`` counts folds; shape buckets keep
    recompiles to queue-set growth only).  ``queue_usage(now)`` returns
    usage normalized by the decayed weight (the exponentially-weighted
    average allocation — decay-invariant between samples, exactly like
    the per-sample weighted average it replaces) and by cluster
    capacity (the division algorithm expects U' in capacity units —
    resource_division.go:242).
    """

    def __init__(self, params: UsageParams | None = None,
                 cluster_capacity: np.ndarray | None = None):
        self.params = params or UsageParams()
        self.cluster_capacity = cluster_capacity
        self._qids: list[str] = []
        self._qindex: dict[str, int] = {}
        cap = 8
        self._usage = np.zeros((cap, rs.NUM_RES))  # decayed integral
        self._seen = np.full(cap, -np.inf)         # per-queue last sample ts
        self._weight = 0.0                         # decayed duration sum
        self._state_ts: float | None = None        # decay reference time
        self.last_record_ts: float | None = None
        self.last_fetch_ts: float | None = None
        self._pending: dict[str, np.ndarray] = {}
        self._pending_ts: float | None = None
        self._pending_duration = 1.0
        self._log: UsageLog | None = None
        # True after a restore from a corrupt checkpoint log: the
        # snapshot reports stale (degraded mode) until a fresh sample
        # folds, regardless of how recent the salvaged state claims
        # to be.
        self.restored_corrupt = False

    # -- maintenance -------------------------------------------------------
    def _row(self, queue: str) -> int:
        i = self._qindex.get(queue)
        if i is None:
            i = len(self._qids)
            if i >= self._usage.shape[0]:
                cap = self._usage.shape[0] * 2
                usage = np.zeros((cap, self._usage.shape[1]))
                usage[:i] = self._usage
                seen = np.full(cap, -np.inf)
                seen[:i] = self._seen
                self._usage, self._seen = usage, seen
            self._qindex[queue] = i
            self._qids.append(queue)
        return i

    def record(self, now: float, queue: str, allocated: np.ndarray,
               duration: float = 1.0) -> None:
        if self._pending and self._pending_ts is not None \
                and now != self._pending_ts:
            # A new timestamp closes the buffered cycle: fold it so the
            # decay sees each cycle's samples at their own age.
            self._flush()
        vec = np.asarray(allocated, float) * duration
        prev = self._pending.get(queue)
        self._pending[queue] = vec if prev is None else prev + vec
        self._pending_ts = now
        self._pending_duration = duration

    def record_cycle(self, now: float, allocations: dict,
                     duration: float = 1.0) -> None:
        for queue, vec in allocations.items():
            self.record(now, queue, vec, duration)
        self._flush()

    def _decay_factor(self, dt: float) -> float:
        hl = self.params.half_life_period_seconds
        if not hl or dt <= 0:
            return 1.0
        return 0.5 ** (dt / hl)

    def _window_start(self, now: float) -> float:
        window = self.params.window_size_seconds
        if self.params.window_type == "tumbling":
            return math.floor(now / window) * window
        return now - window

    def _flush(self) -> None:
        """Fold the buffered cycle sample into the standing tensor —
        the subsystem's ONE device dispatch per cycle."""
        if not self._pending:
            return
        now = self._pending_ts
        for queue in self._pending:
            self._row(queue)
        alloc = np.zeros_like(self._usage)
        for queue, vec in self._pending.items():
            alloc[self._qindex[queue], :vec.shape[0]] = vec
        d = self._decay_factor(now - self._state_ts
                               if self._state_ts is not None else 0.0)
        # Queues whose last sample already fell out of the window restart
        # from zero (the tensor analog of the sample-deque popleft).
        window_start = self._window_start(now)
        keep = self._seen >= window_start
        from ..ops.usage import usage_decay_kernel
        from .deviceguard import device_guard
        import jax.numpy as jnp
        usage = self._usage

        METRICS.inc("usage_decay_dispatch_total")
        # Guarded like every device dispatch (watchdog/breaker/CPU
        # fallback); no Session exists at the operator layer, so the
        # thunk goes straight to the guard.
        self._usage = np.asarray(device_guard().call(
            lambda: usage_decay_kernel(
                jnp.asarray(usage), jnp.asarray(alloc),
                jnp.asarray(keep), float(d)),
            label="usage_decay"))
        self._weight = self._weight * d + self._pending_duration
        for queue in self._pending:
            self._seen[self._qindex[queue]] = now
        self._state_ts = now
        self.last_record_ts = now
        # Fresh data folded: a corrupt-restore degradation ends here —
        # the tensor now carries at least one trustworthy sample.
        self.restored_corrupt = False
        self._pending = {}
        self._pending_ts = None
        if self._log is not None:
            try:
                self._log.append(self._state_dict())
            except OSError as exc:
                LOG.warning("usage log append failed: %s", exc)

    # -- persistence (the commit-log pattern) ------------------------------
    def _state_dict(self) -> dict:
        q = len(self._qids)
        return {
            "kind": "usage-checkpoint",
            "state_ts": self._state_ts,
            "last_record_ts": self.last_record_ts,
            "weight": self._weight,
            # The normalizer persists WITH the integral: a restart
            # within the staleness budget serves the restored usage on
            # its first fetch, before any cycle refreshes capacity —
            # un-normalized raw units there would zero every queue's
            # over-quota share for that cycle.
            "capacity": (None if self.cluster_capacity is None
                         else np.asarray(self.cluster_capacity,
                                         float).tolist()),
            "queues": {qid: {"u": self._usage[i].tolist(),
                             "seen": (None if np.isinf(self._seen[i])
                                      else float(self._seen[i]))}
                       for qid, i in self._qindex.items() if i < q},
        }

    def _restore(self, state: dict) -> None:
        queues = state.get("queues") or {}
        for qid, ent in queues.items():
            i = self._row(qid)
            u = np.asarray(ent.get("u", ()), float)
            self._usage[i, :u.shape[0]] = u
            seen = ent.get("seen")
            self._seen[i] = -np.inf if seen is None else float(seen)
        self._weight = float(state.get("weight") or 0.0)
        self._state_ts = state.get("state_ts")
        self.last_record_ts = state.get("last_record_ts")
        cap = state.get("capacity")
        if cap is not None and self.cluster_capacity is None:
            self.cluster_capacity = np.asarray(cap, float)

    def attach_log(self, path: str, fsync: bool = True) -> bool:
        """Arm checkpoint persistence at ``path``; restores the last
        valid checkpoint first.  Returns True when state was restored.

        A corrupt log (torn tail, CRC mismatch) restores whatever
        prefix is trustworthy but enters the documented stale->degraded
        mode LOUDLY: ``usage_log_corrupt_total`` fires, the snapshot
        reports stale (the proportion plugin then ignores usage and
        counts ``usage_stale_cycles_total``), and the flag clears only
        when a FRESH sample folds — decayed history of unknown age must
        not silently drive the fairness penalty."""
        self._log = UsageLog(path, fsync=fsync)
        state = self._log.load()
        if self._log.last_load_corrupt:
            METRICS.inc("usage_log_corrupt_total")
            LOG.warning("usage log %s: torn/corrupt checkpoint tail — "
                        "restoring the last valid prefix and entering "
                        "degraded (usage-ignored) mode until fresh "
                        "samples land", path)
            self.restored_corrupt = True
        if state:
            self._restore(state)
            METRICS.inc("usage_restore_total")
            return True
        return False

    # -- UsageLister surface ----------------------------------------------
    def queue_usage(self, now: float) -> UsageSnapshot:
        self._flush()
        self.last_fetch_ts = now
        out = UsageSnapshot()
        out.ts = now
        out.stale = self.is_stale(now)
        q = len(self._qids)
        if q == 0:
            return out
        # The exponentially-weighted average is decay-invariant between
        # samples ((u*d)/(w*d) == u/w), so no fetch-time dispatch is
        # needed — only the window mask re-evaluates against ``now``.
        window_start = self._window_start(now)
        inside = self._seen[:q] >= window_start
        w = self._weight if self._weight > 0 else 1.0
        vals = self._usage[:q] / w
        if self.cluster_capacity is not None:
            cap = np.where(self.cluster_capacity > 0,
                           self.cluster_capacity, 1.0)
            vals = vals / cap
        for qid, i in self._qindex.items():
            if i >= q:
                continue
            out[qid] = vals[i] if inside[i] else np.zeros_like(vals[i])
        return out

    def is_stale(self, now: float) -> bool:
        """Data-ingest staleness: the recorder stopped feeding samples.
        (The old fetch-based check could never trip for the in-memory
        store — queue_usage itself refreshed the timestamp it compared
        against, silently serving decayed-to-zero values instead of
        tripping the documented degraded mode.)  A restore from a
        corrupt checkpoint log is stale BY FIAT until fresh data folds:
        the salvaged state's own timestamps are exactly what the
        corruption makes untrustworthy."""
        if self.restored_corrupt:
            return True
        last = self.last_record_ts if self._pending_ts is None \
            else self._pending_ts
        return (last is not None
                and now - last > self.params.staleness_period_seconds)


def resolve_usage_client(spec: str | None,
                         params: UsageParams | None = None) -> UsageLister | None:
    """Client resolver (hub.go:26-69): scheme-based selection.  'memory://'
    and 'fake://' map to the in-memory store; 'prometheus://host:port'
    (or 'prometheus+https://...') to the Prometheus HTTP-API client;
    unknown schemes return None (usage penalty disabled)."""
    if not spec:
        return None
    if spec.startswith(("memory://", "fake://")):
        return InMemoryUsageDB(params)
    if spec.startswith(("prometheus://", "prometheus+https://")):
        from .prometheus_usage import PrometheusUsageClient
        scheme = "https" if spec.startswith("prometheus+https") else "http"
        address = spec.split("://", 1)[1]
        return PrometheusUsageClient(f"{scheme}://{address}", params)
    return None
