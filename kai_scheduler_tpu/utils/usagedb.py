"""Historical-usage store for time-based fair share.

Mirrors pkg/scheduler/cache/usagedb/ (UsageLister usagedb.go:20-138,
client resolver hub.go:26-69, prometheus impl prometheus.go:29-113 with
sliding/tumbling/cron windows and half-life decay, params
api/interface.go:44-49): the scheduler fetches per-queue normalized
historical usage each cycle and feeds it into the fair-share usage penalty
``w' = max(0, W' + k(W' - U'))``.

The in-memory implementation doubles as the "fake" client and as the
record-keeping engine for the time-based simulator; a metrics-backed
client can plug in through the same resolver.
"""

from __future__ import annotations

import math
from collections import defaultdict, deque
from dataclasses import dataclass

import numpy as np

from ..api import resources as rs


@dataclass
class UsageParams:
    half_life_period_seconds: float | None = None  # decay; None = flat
    window_size_seconds: float = 3600.0
    window_type: str = "sliding"  # sliding | tumbling
    fetch_interval_seconds: float = 60.0
    staleness_period_seconds: float = 300.0


class UsageLister:
    """Interface: queue_usage(now) -> {queue: [NUM_RES] normalized}."""

    def queue_usage(self, now: float) -> dict:
        raise NotImplementedError

    def record(self, now: float, queue: str, allocated: np.ndarray,
               duration: float = 1.0) -> None:
        """Ingest one cycle's allocation sample.  No-op for clients whose
        history lives elsewhere (Prometheus scrapes the gauges itself)."""


class InMemoryUsageDB(UsageLister):
    """Sliding/tumbling-window usage with half-life decay.

    record(now, queue, allocated_vec) each cycle; queue_usage(now) returns
    usage normalized by cluster capacity (the division algorithm expects
    U' in capacity units — resource_division.go:242).
    """

    def __init__(self, params: UsageParams | None = None,
                 cluster_capacity: np.ndarray | None = None):
        self.params = params or UsageParams()
        self.cluster_capacity = cluster_capacity
        self._samples: dict[str, deque] = defaultdict(deque)  # (t, vec)
        self.last_fetch_ts: float | None = None

    def record(self, now: float, queue: str, allocated: np.ndarray,
               duration: float = 1.0) -> None:
        self._samples[queue].append((now, allocated.copy() * duration))

    def _decay(self, age: float) -> float:
        hl = self.params.half_life_period_seconds
        if not hl:
            return 1.0
        return 0.5 ** (age / hl)

    def queue_usage(self, now: float) -> dict:
        self.last_fetch_ts = now
        out = {}
        window = self.params.window_size_seconds
        if self.params.window_type == "tumbling":
            window_start = math.floor(now / window) * window
        else:
            window_start = now - window
        for queue, samples in self._samples.items():
            while samples and samples[0][0] < window_start:
                samples.popleft()
            total = rs.zeros()
            weight_total = 0.0
            for t, vec in samples:
                w = self._decay(now - t)
                total += vec * w
                weight_total += w
            if weight_total > 0:
                total = total / weight_total
            if self.cluster_capacity is not None:
                cap = np.where(self.cluster_capacity > 0,
                               self.cluster_capacity, 1.0)
                total = total / cap
            out[queue] = total
        return out

    def is_stale(self, now: float) -> bool:
        return (self.last_fetch_ts is not None
                and now - self.last_fetch_ts
                > self.params.staleness_period_seconds)


def resolve_usage_client(spec: str | None,
                         params: UsageParams | None = None) -> UsageLister | None:
    """Client resolver (hub.go:26-69): scheme-based selection.  'memory://'
    and 'fake://' map to the in-memory store; 'prometheus://host:port'
    (or 'prometheus+https://...') to the Prometheus HTTP-API client;
    unknown schemes return None (usage penalty disabled)."""
    if not spec:
        return None
    if spec.startswith(("memory://", "fake://")):
        return InMemoryUsageDB(params)
    if spec.startswith(("prometheus://", "prometheus+https://")):
        from .prometheus_usage import PrometheusUsageClient
        scheme = "https" if spec.startswith("prometheus+https") else "http"
        address = spec.split("://", 1)[1]
        return PrometheusUsageClient(f"{scheme}://{address}", params)
    return None
