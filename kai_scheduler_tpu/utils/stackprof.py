"""Continuous whole-fleet host profiler: folded stacks, ring-bounded.

The device path is instrumented to death (flight recorder spans, arena
pack stats) but the FLEET cycle — watch drain, pod re-parse, grouper and
status churn, binder round trips — burns its milliseconds in plain
Python between the spans.  This sampler answers "where do the host
milliseconds live" across *whole fleet cycles*, not just inside
``run_once``: a daemon thread walks every live thread's stack at a fixed
rate (default ~67Hz — deliberately off 100Hz so it never phase-locks
with 10ms-period work) and aggregates **collapsed stacks** (pprof folded
format, flamegraph.pl / speedscope ready).

Differences from the per-run ``utils/profiling.SamplingProfiler``:

- frames are ``file.py:function`` WITHOUT line numbers — line-level
  frames explode one logical stack into dozens of series and defeat
  flame-graph aggregation;
- the table of distinct stacks is RING-BOUNDED (``KAI_STACKPROF_STACKS``,
  default 8192): a novel stack past the cap folds into a synthetic
  ``<stack-table-full>`` bucket and counts
  ``stackprof_dropped_stacks_total`` — a pathological workload degrades
  the profile's tail, never the daemon's memory;
- it is env-armable (``KAI_STACKPROF=1``) so bench children and chaos
  iterations profile without plumbing flags, and dump-on-stop
  (``KAI_STACKPROF_DIR``) writes the folded file where the ROADMAP's
  before/after comparisons want it.

Sampling is sigprof-free (pure ``threading`` + ``sys._current_frames``):
safe under JAX's C extensions where signal-based profilers misfire.

Served at ``GET /debug/flame`` (server.py); smoke-tested by
``python -m kai_scheduler_tpu.utils.stackprof --smoke`` (ci_check.sh),
which profiles a short embedded fleet burst and fails on empty output.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from .metrics import METRICS

OVERFLOW_STACK = "<stack-table-full>"


def _env_num(name: str, default: float, lo: float, hi: float) -> float:
    try:
        v = float(os.environ.get(name, default))
    except ValueError:
        return default
    return v if lo <= v <= hi else default


class StackProfiler:
    """Bounded collapsed-stack wall-clock sampler over all live threads."""

    def __init__(self, hz: float | None = None,
                 max_stacks: int | None = None, max_depth: int = 48,
                 clock=time.monotonic):
        self.hz = hz if hz is not None else \
            _env_num("KAI_STACKPROF_HZ", 67.0, 1.0, 1000.0)
        self.max_stacks = int(max_stacks) if max_stacks is not None else \
            int(_env_num("KAI_STACKPROF_STACKS", 8192, 16, 1 << 20))
        self.max_depth = max_depth
        self.clock = clock
        self.samples: dict[str, int] = {}
        self.total_samples = 0
        self.dropped_stacks = 0
        self.started_at = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    @property
    def running(self) -> bool:
        return self._thread is not None

    def start(self) -> "StackProfiler":
        if self._thread is not None:
            return self
        self.started_at = self.clock()
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="stackprof")
        self._thread.start()
        return self

    def stop(self, dump: bool = True) -> None:
        """Stop sampling; when ``KAI_STACKPROF_DIR`` is set (and ``dump``)
        the folded profile is written there before the thread state
        clears."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if dump:
            self.maybe_dump()

    # -- sampling ----------------------------------------------------------
    def _run(self) -> None:
        me = threading.get_ident()
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            frames = sys._current_frames()
            new = 0
            with self._lock:
                for tid, frame in frames.items():
                    if tid == me:
                        continue
                    stack = []
                    depth = 0
                    while frame is not None and depth < self.max_depth:
                        code = frame.f_code
                        stack.append(
                            f"{code.co_filename.rsplit('/', 1)[-1]}:"
                            f"{code.co_name}")
                        frame = frame.f_back
                        depth += 1
                    if not stack:
                        continue
                    key = ";".join(reversed(stack))
                    if key not in self.samples \
                            and len(self.samples) >= self.max_stacks:
                        key = OVERFLOW_STACK
                        self.dropped_stacks += 1
                    self.samples[key] = self.samples.get(key, 0) + 1
                    self.total_samples += 1
                    new += 1
            if new:
                METRICS.inc("stackprof_samples_total", new)
            if self.dropped_stacks:
                METRICS.set_gauge("stackprof_dropped_stacks",
                                  float(self.dropped_stacks))

    # -- reporting ---------------------------------------------------------
    def folded(self, top: int = 5000) -> str:
        """pprof collapsed format: ``frame;frame;... count`` per line,
        heaviest first — pipe into flamegraph.pl or drop into
        speedscope.app."""
        with self._lock:
            rows = sorted(self.samples.items(), key=lambda kv: -kv[1])
        return "\n".join(f"{stack} {count}"
                         for stack, count in rows[:top])

    # Leaves that mean "a thread parked waiting", not work: pool workers
    # blocking on their queues and accept loops would otherwise dominate
    # every leaf aggregation and hide the actual bottleneck.
    IDLE_LEAVES = frozenset((
        "threading.py:wait", "threading.py:_wait_for_tstate_lock",
        "queue.py:get", "selectors.py:select",
        "socketserver.py:serve_forever", "socketserver.py:get_request"))

    def top_frames(self, top: int = 10,
                   exclude_idle: bool = True) -> list[dict]:
        """Leaf-frame aggregation — the "what is the fleet bottleneck"
        one-liner bench.py embeds next to the latency numbers.  Shares
        are of ALL samples, so busy leaves still read small on a mostly
        idle fleet."""
        leaves: dict[str, int] = {}
        with self._lock:
            for stack, count in self.samples.items():
                leaf = stack.rsplit(";", 1)[-1]
                if exclude_idle and leaf in self.IDLE_LEAVES:
                    continue
                leaves[leaf] = leaves.get(leaf, 0) + count
            total = self.total_samples
        return [{"frame": frame, "samples": count,
                 "share": round(count / total, 4) if total else 0.0}
                for frame, count in sorted(leaves.items(),
                                           key=lambda kv: -kv[1])[:top]]

    def status(self) -> dict:
        with self._lock:
            return {"running": self.running,
                    "hz": self.hz,
                    "samples": self.total_samples,
                    "distinct_stacks": len(self.samples),
                    "stack_cap": self.max_stacks,
                    "dropped_stacks": self.dropped_stacks,
                    "running_seconds": round(
                        self.clock() - self.started_at, 1)
                    if self.started_at else 0.0}

    def maybe_dump(self, out_dir: str | None = None) -> str | None:
        """Write the folded profile to ``out_dir`` (default
        ``KAI_STACKPROF_DIR``); returns the path, or None when no dir is
        armed.  IO happens outside the sample lock."""
        out_dir = out_dir or os.environ.get("KAI_STACKPROF_DIR")
        if not out_dir:
            return None
        body = self.folded(top=self.max_stacks)
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(out_dir,
                                f"stackprof_{os.getpid()}.folded")
            with open(path, "w") as fh:
                fh.write(body + "\n")
            return path
        except OSError:
            METRICS.inc("stackprof_dump_errors_total")
            return None

    def reset(self) -> None:
        with self._lock:
            self.samples.clear()
            self.total_samples = 0
            self.dropped_stacks = 0


# Process-wide profiler, like METRICS/TRACER/LIFECYCLE: the server, the
# bench fleet phase, and env arming all share one instance so /debug/flame
# always shows whatever is currently collected.
STACKPROF = StackProfiler()


def ensure_started_from_env() -> bool:
    """Arm the shared profiler when ``KAI_STACKPROF`` is truthy (1/true/
    yes/on); returns whether it is running afterwards."""
    val = (os.environ.get("KAI_STACKPROF") or "").strip().lower()
    if val in ("1", "true", "yes", "on"):
        STACKPROF.start()
    return STACKPROF.running


def _smoke() -> int:
    """Profile a short embedded fleet burst and assert a non-empty folded
    profile whose frames include the scheduler pipeline — the CI gate
    that keeps the profiler able to see the fleet loop."""
    from ..controllers import System, SystemConfig, make_pod
    from ..controllers.podgrouper import POD_GROUP_LABEL

    prof = StackProfiler(hz=250.0, max_stacks=4096)
    prof.start()
    system = System(SystemConfig())
    for i in range(60):
        system.api.create({
            "kind": "Node", "metadata": {"name": f"n{i:03d}"}, "spec": {},
            "status": {"allocatable": {"cpu": "32", "memory": "256Gi",
                                       "nvidia.com/gpu": 8, "pods": 110}}})
    system.api.create({"kind": "Queue", "metadata": {"name": "q"},
                       "spec": {}})
    for j in range(4):
        system.api.create({"kind": "PodGroup",
                           "metadata": {"name": f"pg{j}"},
                           "spec": {"queue": "q", "minMember": 20}})
        for k in range(20):
            system.api.create(make_pod(
                f"p{j}-{k:03d}", labels={POD_GROUP_LABEL: f"pg{j}"},
                gpu=1 if j % 2 == 0 else 0))
    for _ in range(3):
        system.run_cycle()
    prof.stop(dump=False)
    body = prof.folded()
    ok = bool(body.strip()) and prof.total_samples > 0
    print(f"stackprof smoke: {prof.total_samples} samples, "
          f"{len(prof.samples)} stacks "
          f"({'OK' if ok else 'EMPTY PROFILE'})")
    for row in prof.top_frames(5):
        print(f"  {row['share']:6.1%}  {row['frame']}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(_smoke() if "--smoke" in sys.argv else 0)
