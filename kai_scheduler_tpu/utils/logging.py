"""Leveled, scoped logging.

Mirrors pkg/scheduler/log (zap-based InfraLogger with verbosity levels and
per-session / per-action child loggers): numeric verbosity levels on top of
the stdlib logger, with scope-tagged children created per scheduling
session and action.
"""

from __future__ import annotations

import logging
import sys

_BASE = logging.getLogger("kai_scheduler_tpu")
_VERBOSITY = 0


def init_loggers(verbosity: int = 0, stream=None) -> None:
    global _VERBOSITY
    _VERBOSITY = verbosity
    if not _BASE.handlers:
        handler = logging.StreamHandler(stream or sys.stderr)
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname).1s %(name)s: %(message)s"))
        _BASE.addHandler(handler)
    _BASE.setLevel(logging.DEBUG if verbosity > 0 else logging.INFO)


class ScopedLogger:
    """V-leveled logger: log.v(6).info(...) only emits when verbosity>=6."""

    def __init__(self, scope: str = ""):
        self.scope = scope
        self._logger = _BASE.getChild(scope) if scope else _BASE

    def child(self, scope: str) -> "ScopedLogger":
        full = f"{self.scope}.{scope}" if self.scope else scope
        return ScopedLogger(full)

    def v(self, level: int) -> "_LevelProxy":
        return _LevelProxy(self._logger, enabled=_VERBOSITY >= level)

    def info(self, msg, *args):
        self._logger.info(msg, *args)

    def warning(self, msg, *args):
        self._logger.warning(msg, *args)

    def error(self, msg, *args):
        self._logger.error(msg, *args)


class _LevelProxy:
    def __init__(self, logger, enabled: bool):
        self._logger = logger
        self._enabled = enabled

    def info(self, msg, *args):
        if self._enabled:
            self._logger.debug(msg, *args)


LOG = ScopedLogger()


def session_logger(session_id: int) -> ScopedLogger:
    return LOG.child(f"session-{session_id}")
