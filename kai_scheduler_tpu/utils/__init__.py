"""Shared utilities: metrics, logging."""


import argparse


def parse_bool(value: str) -> bool:
    """Strict CLI boolean: chart templating renders --flag=true/false, and
    a typo must fail loudly, not silently pick a default."""
    lowered = value.strip().lower()
    if lowered in ("true", "1", "yes", "on"):
        return True
    if lowered in ("false", "0", "no", "off"):
        return False
    raise argparse.ArgumentTypeError(f"not a boolean: {value!r}")


def backoff_delay(base_s: float, cap_s: float, attempt: int, rng,
                  spread: float = 0.5) -> float:
    """Jittered exponential backoff: ``min(cap, base * 2^(attempt-1))``
    stretched by ``[1, 1+spread)`` from the caller's seeded RNG.  The one
    implementation behind every retry loop in the fleet (binder retries,
    watch reconnects) so thundering-herd tuning happens in one place."""
    exp = min(max(0, attempt - 1), 16)  # bound the power, min() caps anyway
    return min(cap_s, base_s * (2 ** exp)) * (1.0 + spread * rng.random())
