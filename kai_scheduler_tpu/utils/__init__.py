"""Shared utilities: metrics, logging."""


import argparse


def parse_bool(value: str) -> bool:
    """Strict CLI boolean: chart templating renders --flag=true/false, and
    a typo must fail loudly, not silently pick a default."""
    lowered = value.strip().lower()
    if lowered in ("true", "1", "yes", "on"):
        return True
    if lowered in ("false", "0", "no", "off"):
        return False
    raise argparse.ArgumentTypeError(f"not a boolean: {value!r}")
