"""Shared utilities: metrics, logging."""
