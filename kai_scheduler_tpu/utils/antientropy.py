"""Anti-entropy digests: prove the incremental caches still mirror truth.

The scheduler's steady state is built entirely from O(delta) folds —
watch payloads into ``ClusterCache`` mirrors (DESIGN §9/§12), mirrors
into the columnar store (DESIGN §11).  Every fold is bit-true *by
construction and by test*, but a wire that lies (truncated or corrupted
frames, a replayed stream, a seq regression across an apiserver
restart) can desynchronize the replica silently: nothing in the fold
itself can notice an event it never saw.  Classic anti-entropy closes
that gap — both sides periodically exchange a cheap summary of their
full state and re-list exactly what disagrees (Dynamo's Merkle
exchange, collapsed to one level: our stores are small enough that a
flat per-kind digest is the whole tree).

Digest shape: per kind, ``{"count": N, "hash": "<16 hex>"}`` where the
hash is an ORDER-INSENSITIVE fold (XOR) of each object's independent
64-bit content hash.  XOR makes the digest incrementally maintainable
and iteration-order-free; content hashing over canonical JSON
(``sort_keys`` + compact separators) makes it representation-free — a
manifest that round-tripped through the wire digests identically to the
store's original.

Consumers: the apiserver serves ``GET /digest`` (store truth at one
event seq, atomic under the server lock); ``ClusterCache`` digests its
mirrors and compares (``anti_entropy_check``), repairing divergent
kinds with a targeted re-list and quarantining the columnar fast path
when the column projection disagrees with the mirrors
(docs/DEGRADATION.md, "anti-entropy" rows).
"""

from __future__ import annotations

import hashlib
import json

EMPTY_HASH = "%016x" % 0


def obj_hash64(obj) -> int:
    """Independent 64-bit content hash of one JSON-able value.

    Canonical encoding (sorted keys, compact separators) so two dicts
    with different insertion order — the store's original vs its
    wire round trip — hash identically; ``default=str`` keeps the
    digest total on degenerate non-JSON values (both sides apply the
    same coercion, so parity still holds)."""
    payload = json.dumps(obj, sort_keys=True, separators=(",", ":"),
                         default=str).encode()
    return int.from_bytes(
        hashlib.blake2b(payload, digest_size=8).digest(), "big")


def digest_objects(objs) -> dict:
    """Per-kind digest of an iterable of manifests:
    ``{kind: {"count": N, "hash": "<16 hex>"}}``."""
    counts: dict = {}
    hashes: dict = {}
    for obj in objs:
        kind = obj.get("kind") or "?"
        counts[kind] = counts.get(kind, 0) + 1
        hashes[kind] = hashes.get(kind, 0) ^ obj_hash64(obj)
    return {k: {"count": counts[k], "hash": f"{hashes[k]:016x}"}
            for k in counts}


def diverged_kinds(local: dict, remote: dict, kinds=None) -> list:
    """Kinds whose digests differ, sorted.  ``kinds`` restricts the
    comparison to the kinds the local replica actually consumes (a
    cache must not be held to kinds it never watches); a kind absent
    on one side compares as the empty digest."""
    empty = {"count": 0, "hash": EMPTY_HASH}
    keys = set(local) | set(remote)
    if kinds is not None:
        keys &= set(kinds)
    return sorted(k for k in keys
                  if (local.get(k) or empty) != (remote.get(k) or empty))
