"""KAI_LOCKTRACE runtime lock-order validator.

kairace (``tools/kairace/``) computes the STATIC lock acquisition graph
— which lock is ever acquired while another is held, program-wide.  This
shim records the DYNAMIC side: with ``KAI_LOCKTRACE=1``, the
``threading`` lock factories are replaced with tracing proxies, and
every real acquisition appends order edges (held-lock -> acquired-lock)
to a process-wide journal.  ``chaos_matrix --races`` then checks the
observed orders against the static graph:

- an observed edge whose REVERSE is reachable in the static graph is a
  **contradiction** — either the analyzer missed an acquisition path
  (false negative) or an annotation/document rotted; both are bugs;
- the per-subsystem acquisition counts prove the sweep actually
  exercised each threaded component's locks (a validator that records
  nothing validates nothing).

Lock identity is the CREATION SITE (``file:line`` of the factory call),
which is exactly what the static side exports per canonical lock name in
``kairace --lock-graph`` (``locks: {name: [{file, line}]}``), so the two
sides join without any runtime knowledge of attribute names.

Env contract:

- ``KAI_LOCKTRACE=1``       install the shim (tests/conftest.py honors
                            this before any suite code creates locks)
- ``KAI_LOCKTRACE_OUT``     dump the journal as JSON at process exit
- ``KAI_LOCKTRACE_GRAPH``   path to a ``kairace --lock-graph`` JSON;
                            when set, contradictions are detected ONLINE
                            and counted live

Metrics (``locktrace_orders_recorded_total``,
``locktrace_contradictions_total``) are published via
:func:`sync_metrics` — called from ``/healthz`` and the Prometheus
render path, NEVER from inside an acquire (incrementing a counter takes
the metrics registry's own lock, which is itself traced: the hot path
must not re-enter it).
"""

from __future__ import annotations

import _thread
import atexit
import json
import os
import sys
import threading

# Originals, captured at import time so install() can patch and
# uninstall() can restore, and so the tracer's own internals never go
# through the proxies.
_REAL = {
    "Lock": threading.Lock,
    "RLock": threading.RLock,
    "Condition": threading.Condition,
    "Semaphore": threading.Semaphore,
    "BoundedSemaphore": threading.BoundedSemaphore,
}

_PKG_MARKER = "kai_scheduler_tpu"


def _relpath(path: str) -> str:
    """Package-relative path, matching kailint's ``package_relative``
    (so runtime sites join against static lock_sites keys)."""
    path = path.replace(os.sep, "/")
    idx = path.rfind(_PKG_MARKER + "/")
    return path[idx:] if idx >= 0 else path


def _creation_site() -> str:
    """``file:line`` of the first frame outside this module and the
    threading internals — the ``self._lock = threading.Lock()`` line."""
    frame = sys._getframe(2)
    here = __file__.replace(os.sep, "/")
    while frame is not None:
        fname = frame.f_code.co_filename.replace(os.sep, "/")
        if not fname.endswith("threading.py") and fname != here:
            return f"{_relpath(fname)}:{frame.f_lineno}"
        frame = frame.f_back
    return "<unknown>:0"


def _internal_to_threading() -> bool:
    """True when the factory was invoked from inside threading.py
    itself — ``threading.Event()`` builds a ``Condition(Lock())``,
    ``Thread`` builds its started-Event, ``Barrier`` its condition.
    Those internals must NOT be traced: the frame walk above would
    blame the USER'S ``self._stop = threading.Event()`` line, and
    ``_site_name_map``'s +-2 fuzz then joins that site to an adjacent
    real lock's canonical name — `event.wait()` would count as
    acquisitions of (and order edges through) a lock that was never
    touched: fake coverage for the --races gate and potential bogus
    contradictions."""
    frame = sys._getframe(2)  # the traced factory's caller
    return frame is not None and \
        frame.f_code.co_filename.replace(os.sep, "/") \
             .endswith("threading.py")


class LockTracer:
    def __init__(self):
        # Raw lock: journal mutation must not trace itself.
        self._guard = _thread.allocate_lock()
        self._tls = threading.local()
        self.edges: dict = {}        # (site_a, site_b) -> count
        self.acquires: dict = {}     # site -> count
        self.creations: dict = {}    # site -> count
        self.contradictions: list = []   # [(held_name, acquired_name)]
        self._graph_names: dict = {}     # site -> canonical lock name
        self._static_edges: set = set()  # (name, name)
        self._succ: dict = {}            # name -> set(name), static graph
        self._observed_names: set = set()   # (name, name) seen at runtime
        self._reach_memo: dict = {}
        self._published = {"orders": 0, "contradictions": 0}
        self.installed = False

    # -- per-thread held stack --------------------------------------------
    def _held(self) -> list:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def note_create(self, site: str) -> None:
        with self._guard:
            self.creations[site] = self.creations.get(site, 0) + 1

    def note_acquire(self, site: str) -> None:
        held = self._held()
        new_edges = []
        for h in held:
            if h != site:
                new_edges.append((h, site))
        held.append(site)
        with self._guard:
            self.acquires[site] = self.acquires.get(site, 0) + 1
            for edge in new_edges:
                first = edge not in self.edges
                self.edges[edge] = self.edges.get(edge, 0) + 1
                # Gate on a LOADED graph (names mapped), not on it
                # having edges: mutual-observed inversion detection
                # works on an edge-free graph too.
                if first and self._graph_names:
                    self._check_online(edge)

    def note_release(self, site: str, recursive: bool = False) -> None:
        held = self._held()
        if recursive:
            self._tls.held = [h for h in held if h != site]
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i] == site:
                del held[i]
                return

    # -- static-graph join -------------------------------------------------
    def load_static_graph(self, graph: dict) -> None:
        """``graph``: the ``kairace --lock-graph`` payload.  Sites map to
        canonical names; a creation line may sit one line off the
        declaration's (multi-line assignment), so join with a +-2 line
        tolerance."""
        with self._guard:
            self._graph_names = _site_name_map(graph)
            self._static_edges = {tuple(e) for e in graph.get("edges", [])}
            # Adjacency once, up front: _reachable runs inside the
            # lock-acquire hot path (under _guard) — a per-expansion
            # scan of the whole edge set would put an O(V*E) walk in
            # every first-time acquisition.
            self._succ = {}
            for a, b in self._static_edges:
                self._succ.setdefault(a, set()).add(b)
            self._observed_names = set()
            self._reach_memo = {}

    def _reachable(self, src: str, dst: str) -> bool:
        """Path src -> ... -> dst in the static graph (memoized DFS)."""
        key = (src, dst)
        memo = self._reach_memo
        if key in memo:
            return memo[key]
        seen, stack = set(), [src]
        found = False
        while stack:
            node = stack.pop()
            if node == dst:
                found = True
                break
            if node in seen:
                continue
            seen.add(node)
            stack.extend(self._succ.get(node, ()))
        memo[key] = found
        return found

    def _check_online(self, edge: tuple) -> None:
        # caller holds self._guard
        a = self._graph_names.get(edge[0])
        b = self._graph_names.get(edge[1])
        if a is None or b is None or a == b:
            return
        # Two triggers: the reverse order is statically REACHABLE (the
        # analyzer knew about a path b -> ... -> a), or the reverse was
        # OBSERVED at runtime (strongest evidence there is — a
        # deadlock-capable inversion even when the static graph missed
        # both paths, e.g. dynamic dispatch it cannot resolve).
        if (a, b) not in self._static_edges and self._reachable(b, a):
            self.contradictions.append((a, b))
        elif (b, a) in self._observed_names:
            self.contradictions.append((a, b))
        self._observed_names.add((a, b))

    # -- reporting ---------------------------------------------------------
    def mapped_edges(self) -> dict:
        """Observed edges joined to canonical names (unmapped sites —
        stdlib/test locks — drop out): (name_a, name_b) -> count."""
        out: dict = {}
        with self._guard:
            for (sa, sb), n in self.edges.items():
                a, b = self._graph_names.get(sa), self._graph_names.get(sb)
                if a is not None and b is not None and a != b:
                    out[(a, b)] = out.get((a, b), 0) + n
        return out

    def stats(self) -> dict:
        with self._guard:
            return {
                "orders_recorded": len(self.edges),
                "acquires": sum(self.acquires.values()),
                "sites": len(self.acquires),
                "contradictions": len(self.contradictions),
            }

    def dump(self) -> dict:
        with self._guard:
            return {
                "edges": sorted([a, b, n] for (a, b), n
                                in self.edges.items()),
                "acquires": dict(sorted(self.acquires.items())),
                "creations": dict(sorted(self.creations.items())),
                "contradictions": [list(c) for c in self.contradictions],
            }

    def reset(self) -> None:
        with self._guard:
            self.edges.clear()
            self.acquires.clear()
            self.creations.clear()
            self.contradictions.clear()
            self._observed_names.clear()
            self._published = {"orders": 0, "contradictions": 0}


TRACER = LockTracer()


def sync_metrics() -> None:
    """Publish journal sizes as counters (delta since last sync).  Safe
    to call from any thread; called OUTSIDE the acquire hot path only
    (see module docstring for why)."""
    from .metrics import METRICS
    with TRACER._guard:
        orders = len(TRACER.edges)
        contras = len(TRACER.contradictions)
        d_orders = orders - TRACER._published["orders"]
        d_contras = contras - TRACER._published["contradictions"]
        TRACER._published = {"orders": orders, "contradictions": contras}
    if d_orders > 0:
        METRICS.inc("locktrace_orders_recorded_total", d_orders)
    if d_contras > 0:
        METRICS.inc("locktrace_contradictions_total", d_contras)


# -- proxies -----------------------------------------------------------------

class _TracedLock:
    """Plain Lock proxy; also what Semaphore wraps."""

    def __init__(self, inner, site: str):
        self._inner = inner
        self.site = site
        TRACER.note_create(site)

    def acquire(self, blocking=True, timeout=-1):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            TRACER.note_acquire(self.site)
        return ok

    def release(self):
        self._inner.release()
        TRACER.note_release(self.site)

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        # Stdlib internals reach past the public protocol —
        # concurrent.futures registers `_at_fork_reinit` with
        # os.register_at_fork at IMPORT time, threading's fork hooks do
        # the same — so unknown attributes delegate to the real lock
        # (only missing ones reach here; the traced methods above win).
        if name == "_inner":
            raise AttributeError(name)
        return getattr(self._inner, name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<traced {self._inner!r} @ {self.site}>"


class _TracedRLock(_TracedLock):
    """RLock proxy: exposes the ``_release_save``/``_acquire_restore``/
    ``_is_owned`` protocol so ``threading.Condition`` wait() keeps the
    held-stack honest (wait RELEASES the lock — the tracer must not
    think it is still held while the thread sleeps)."""

    def _release_save(self):
        state = self._inner._release_save()
        TRACER.note_release(self.site, recursive=True)
        return state

    def _acquire_restore(self, state):
        self._inner._acquire_restore(state)
        TRACER.note_acquire(self.site)

    def _is_owned(self):
        return self._inner._is_owned()


class _TracedSemaphore:
    """Semaphore proxy: acquisition order still matters (a semaphore
    held while taking a lock is an ordering edge), release has no owner
    thread so the stack pop is best-effort on the releasing thread."""

    def __init__(self, inner, site: str):
        self._inner = inner
        self.site = site
        TRACER.note_create(site)

    def acquire(self, blocking=True, timeout=None):
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            TRACER.note_acquire(self.site)
        return ok

    def release(self, n=1):
        self._inner.release(n)
        TRACER.note_release(self.site)

    def __getattr__(self, name):
        if name == "_inner":
            raise AttributeError(name)
        return getattr(self._inner, name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def _lock_factory():
    if _internal_to_threading():
        return _REAL["Lock"]()
    return _TracedLock(_REAL["Lock"](), _creation_site())


def _rlock_factory():
    if _internal_to_threading():
        return _REAL["RLock"]()
    return _TracedRLock(_REAL["RLock"](), _creation_site())


def _condition_factory(lock=None):
    """``Condition(self._lock)`` ALIASES the lock: handing the existing
    proxy to the real Condition means waiting/notifying records against
    the very same site — the aliasing kairace resolves statically."""
    if lock is None and not _internal_to_threading():
        lock = _TracedRLock(_REAL["RLock"](), _creation_site())
    return _REAL["Condition"](lock)


def _semaphore_factory(value=1):
    if _internal_to_threading():
        return _REAL["Semaphore"](value)
    return _TracedSemaphore(_REAL["Semaphore"](value), _creation_site())


def _bounded_semaphore_factory(value=1):
    if _internal_to_threading():
        return _REAL["BoundedSemaphore"](value)
    return _TracedSemaphore(_REAL["BoundedSemaphore"](value),
                            _creation_site())


def install() -> LockTracer:
    """Patch the threading factories.  Locks created BEFORE install are
    invisible — install from conftest/process start, before any suite
    code constructs its objects."""
    if TRACER.installed:
        return TRACER
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    threading.Semaphore = _semaphore_factory
    threading.BoundedSemaphore = _bounded_semaphore_factory
    TRACER.installed = True

    graph_path = os.environ.get("KAI_LOCKTRACE_GRAPH")
    if graph_path and os.path.isfile(graph_path):
        try:
            with open(graph_path, encoding="utf-8") as fh:
                TRACER.load_static_graph(json.load(fh))
        except (OSError, ValueError):
            pass  # validation degrades to offline; recording continues

    out = os.environ.get("KAI_LOCKTRACE_OUT")
    if out:
        atexit.register(_dump_to, out)
    return TRACER


def uninstall() -> None:
    for name, real in _REAL.items():
        setattr(threading, name, real)
    TRACER.installed = False


def _dump_to(path: str) -> None:
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(TRACER.dump(), fh, indent=2, sort_keys=True)
            fh.write("\n")
    except OSError:
        pass  # a failed dump must not fail the traced process


def _site_name_map(graph: dict) -> dict:
    """site (``file:line``) -> canonical lock name, with the same +-2
    line tolerance as :meth:`LockTracer.load_static_graph` (a creation
    call can sit a line off its declaration in a wrapped assignment)."""
    names: dict = {}
    # Exact declaration lines claim their site FIRST — two locks
    # declared on adjacent lines must never steal each other's site via
    # the fuzzy fill.
    for name, sites in graph.get("locks", {}).items():
        for ent in sites:
            names[f"{ent['file']}:{ent['line']}"] = name
    for name, sites in graph.get("locks", {}).items():
        for ent in sites:
            for delta in (1, -1, 2, -2):
                names.setdefault(f"{ent['file']}:{ent['line'] + delta}",
                                 name)
    return names


def _subsystem(site: str) -> str:
    """``kai_scheduler_tpu/utils/statusworker.py:41`` ->
    ``utils/statusworker`` — the per-component grouping the
    ``chaos_matrix --races`` coverage gate reports on."""
    path = site.rsplit(":", 1)[0]
    if path.startswith(_PKG_MARKER + "/"):
        path = path[len(_PKG_MARKER) + 1:]
    return path[:-3] if path.endswith(".py") else path


def validate_observed(graph: dict, dumps: list) -> dict:
    """Join merged ``KAI_LOCKTRACE_OUT`` journals against a static
    ``kairace --lock-graph`` payload (the offline half of the validator;
    the online half is :meth:`LockTracer._check_online`).

    Returns orders (mapped edges), contradictions (observed order whose
    reverse is statically reachable — analyzer false negative or rotted
    annotation), and per-subsystem coverage: every subsystem that
    CREATED a statically-known lock must show at least one acquisition,
    else the sweep never exercised it and proved nothing about it."""
    names = _site_name_map(graph)
    static_edges = {tuple(e) for e in graph.get("edges", [])}

    succ: dict = {}
    for a, b in static_edges:
        succ.setdefault(a, set()).add(b)

    def reachable(src: str, dst: str) -> bool:
        seen, stack = set(), [src]
        while stack:
            node = stack.pop()
            if node == dst:
                return True
            if node in seen:
                continue
            seen.add(node)
            stack.extend(succ.get(node, ()))
        return False

    orders: dict = {}
    subsystems: dict = {}
    contradictions: list = []
    unmapped = 0

    def sub_entry(site: str) -> dict:
        return subsystems.setdefault(_subsystem(site), {
            "locks_created": 0, "acquires": 0, "orders": 0})

    for dump in dumps:
        for site, n in dump.get("creations", {}).items():
            if site in names:
                sub_entry(site)["locks_created"] += n
        for site, n in dump.get("acquires", {}).items():
            if site in names:
                sub_entry(site)["acquires"] += n
        for sa, sb, n in dump.get("edges", []):
            a, b = names.get(sa), names.get(sb)
            if a is None or b is None or a == b:
                unmapped += 1
                continue
            first = (a, b) not in orders
            orders[(a, b)] = orders.get((a, b), 0) + n
            sub_entry(sa)["orders"] += 1 if first else 0
            sub_entry(sb)["orders"] += 1 if first else 0
            if first and (a, b) not in static_edges and reachable(b, a):
                contradictions.append(
                    {"observed": [a, b],
                     "static_path": f"{b} -> ... -> {a}"})

    # Observed-vs-observed inversions: both orders in the merged
    # journals (possibly from different seeds) is a deadlock-capable
    # cycle even when the static graph missed BOTH acquisition paths —
    # the strongest evidence the journals can carry, and invisible to
    # the static-reachability check above.
    for (a, b) in sorted(orders):
        if (b, a) in orders and a < b:
            contradictions.append(
                {"observed": [a, b],
                 "static_path": f"{b} -> {a} also observed at runtime"})

    uncovered = sorted(s for s, ent in subsystems.items()
                       if ent["locks_created"] and not ent["acquires"])
    return {
        "orders": {f"{a} -> {b}": n
                   for (a, b), n in sorted(orders.items())},
        "contradictions": contradictions,
        "subsystems": dict(sorted(subsystems.items())),
        "uncovered_subsystems": uncovered,
        "unmapped_edges": unmapped,
        "ok": not contradictions and not uncovered and bool(orders),
    }


def install_from_env() -> bool:
    """Honor ``KAI_LOCKTRACE=1`` (the conftest/server entry hook)."""
    if os.environ.get("KAI_LOCKTRACE", "") not in ("", "0", "false"):
        install()
        return True
    return False
