"""In-process metrics registry with Prometheus-compatible naming.

Mirrors pkg/scheduler/metrics/metrics.go's metric families
(e2e_scheduling_latency_milliseconds, action/plugin latency histograms,
queue fair-share/usage gauges, scenario counters).  Exported as a
Prometheus text endpoint by the scheduler server; in-process consumers read
the structured values directly.

Label cardinality is BOUNDED for histograms and counters: families keyed
by user-controlled values (per-queue latency, per-queue SLO burn) would
otherwise grow one series per distinct value forever — the classic
unbounded-label leak that OOMs a long-lived daemon and melts the scrape.
Each (family, label key) admits at most ``KAI_METRICS_LABEL_CAP`` distinct
values (default 512); further values fold into ``other`` and increment
``metrics_label_overflow_total``, so saturation is visible, never silent.
Gauges are exempt: their families (per-queue fair share) are overwritten
in place each cycle and sized by the cluster, not by unbounded history.
"""

from __future__ import annotations

import math
import os
import threading
from collections import defaultdict
from dataclasses import dataclass, field

LABEL_OVERFLOW_VALUE = "other"


def _label_cap() -> int:
    try:
        return max(1, int(os.environ.get("KAI_METRICS_LABEL_CAP", 512)))
    except ValueError:
        return 512


@dataclass
class Histogram:
    buckets: list = field(default_factory=lambda: [
        1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000, math.inf])
    counts: dict = field(default_factory=lambda: defaultdict(int))
    total: float = 0.0
    n: int = 0

    def observe(self, value: float) -> None:
        for b in self.buckets:
            if value <= b:
                self.counts[b] += 1
                break
        self.total += value
        self.n += 1

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile observation.

        ``q`` is clamped to [0, 1].  The target is the RANK of the
        quantile observation (1-based, ceil) — a plain ``acc >= q*n``
        misreports q=0: the target degenerates to 0, which the very
        first (possibly empty) bucket satisfies."""
        if self.n == 0:
            return 0.0
        q = min(1.0, max(0.0, q))
        target = max(1, math.ceil(q * self.n))
        acc = 0
        for b in self.buckets:
            acc += self.counts.get(b, 0)
            if acc >= target:
                return b
        return self.buckets[-1]

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0


class Metrics:
    def __init__(self, label_cap: int | None = None):
        self.histograms: dict[str, Histogram] = defaultdict(Histogram)
        self.gauges: dict[str, float] = {}
        self.counters: dict[str, float] = defaultdict(float)
        self._label_cap = label_cap
        # (family, label key) -> seen values; guarded by _label_lock (the
        # guard mutates across threads: scheduler cycle, watch drain,
        # status-updater workers all record labeled series).
        self._label_values: dict = defaultdict(set)
        self._label_lock = threading.Lock()
        # Registry mutation lock: `counters[key] += v` is a read-modify-
        # write — two threads (status workers, commit executor, HTTP
        # handlers, samplers) interleaving between the read and the
        # store LOSE increments, and histogram observes tear
        # (counts/total/n updated non-atomically).  Every mutation takes
        # this lock (kairace KRC001); reads stay lock-free — a torn read
        # of a monotonically growing counter is at worst one tick stale.
        self._data_lock = threading.Lock()
        # Labeled-histogram rendering: series key -> (family, labels).
        self._histogram_series: dict[str, tuple] = {}

    def _bound_labels(self, name: str, labels: dict) -> dict:
        """Cap distinct values per (family, label key); overflow folds
        into ``other`` and counts.  The cap is read per call so the env
        knob applies without a restart ceremony in tests."""
        if not labels:
            return labels
        cap = self._label_cap if self._label_cap is not None \
            else _label_cap()
        out = {}
        overflowed = 0
        with self._label_lock:
            for k, v in labels.items():
                v = str(v)
                seen = self._label_values[(name, k)]
                if v in seen:
                    out[k] = v
                elif len(seen) < cap:
                    seen.add(v)
                    out[k] = v
                else:
                    out[k] = LABEL_OVERFLOW_VALUE
                    overflowed += 1
        if overflowed:
            with self._data_lock:
                self.counters["metrics_label_overflow_total"] += overflowed
        return out

    def observe(self, name: str, value: float, **labels) -> None:
        if labels:
            labels = self._bound_labels(name, labels)
            key = _key(name, labels)
            with self._data_lock:
                self._histogram_series.setdefault(key, (name, labels))
                self.histograms[key].observe(value)
        else:
            with self._data_lock:
                self.histograms[name].observe(value)

    def set_gauge(self, name: str, value: float, **labels) -> None:
        with self._data_lock:
            self.gauges[_key(name, labels)] = value

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        if labels:
            labels = self._bound_labels(name, labels)
        with self._data_lock:
            self.counters[_key(name, labels)] += value

    def reset(self) -> None:
        with self._data_lock:
            self.histograms.clear()
            self.gauges.clear()
            self.counters.clear()
            self._histogram_series.clear()
        with self._label_lock:
            self._label_values.clear()

    def to_prometheus_text(self) -> str:
        # The whole render holds _data_lock: a first-time inc/observe on
        # another thread INSERTS into these dicts, and a dict resize
        # during iteration is a RuntimeError (a 500ing scrape), not a
        # stale read.  Render is pure string work at scrape frequency —
        # instruments blocking on it for a few hundred microseconds is
        # the cheap side of that trade.
        with self._data_lock:
            return self._render_locked()

    def _render_locked(self) -> str:
        lines = []
        # Group histogram series by family first: the text format
        # requires every line of one family to form a single
        # uninterrupted block after its # TYPE line — interleaving two
        # labeled families fails promtool/OpenMetrics-strict scrapers.
        families: dict[str, list] = {}
        for key, h in self.histograms.items():
            family, labels = self._histogram_series.get(key, (key, {}))
            families.setdefault(family, []).append((labels, h))
        for family, series in families.items():
            lines.append(f"# TYPE {family} histogram")
            for labels, h in series:
                # Cumulative buckets (the Prometheus histogram contract:
                # every `le` counts observations <= it, ending at `+Inf`
                # == _count) — `_sum`/`_count` alone is not scrapeable as
                # a histogram and breaks histogram_quantile().
                acc = 0
                for b in h.buckets:
                    acc += h.counts.get(b, 0)
                    le = "+Inf" if b == math.inf else f"{b:g}"
                    lines.append(f"{family}_bucket"
                                 f"{_labels_text(labels, le=le)} {acc}")
                if not h.buckets or h.buckets[-1] != math.inf:
                    # Custom bucket lists without an inf edge still need
                    # the mandatory +Inf bucket (== _count).
                    lines.append(
                        f"{family}_bucket"
                        f"{_labels_text(labels, le='+Inf')} {h.n}")
                lines.append(
                    f"{family}_sum{_labels_text(labels)} {h.total}")
                lines.append(
                    f"{family}_count{_labels_text(labels)} {h.n}")
        for key, v in self.gauges.items():
            lines.append(f"{key} {v}")
        for key, v in self.counters.items():
            lines.append(f"{key} {v}")
        return "\n".join(lines) + "\n"


def _labels_text(labels: dict, le: str | None = None) -> str:
    """Render a label set (optionally with a bucket ``le``) as the
    ``{k="v",...}`` suffix; empty labels and no le render as nothing."""
    items = list(sorted(labels.items()))
    if le is not None:
        items.append(("le", le))
    if not items:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in items)
    return f"{{{inner}}}"


def _key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


METRICS = Metrics()
