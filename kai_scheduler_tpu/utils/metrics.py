"""In-process metrics registry with Prometheus-compatible naming.

Mirrors pkg/scheduler/metrics/metrics.go's metric families
(e2e_scheduling_latency_milliseconds, action/plugin latency histograms,
queue fair-share/usage gauges, scenario counters).  Exported as a
Prometheus text endpoint by the scheduler server; in-process consumers read
the structured values directly.
"""

from __future__ import annotations

import math
from collections import defaultdict
from dataclasses import dataclass, field


@dataclass
class Histogram:
    buckets: list = field(default_factory=lambda: [
        1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000, math.inf])
    counts: dict = field(default_factory=lambda: defaultdict(int))
    total: float = 0.0
    n: int = 0

    def observe(self, value: float) -> None:
        for b in self.buckets:
            if value <= b:
                self.counts[b] += 1
                break
        self.total += value
        self.n += 1

    def quantile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile observation.

        ``q`` is clamped to [0, 1].  The target is the RANK of the
        quantile observation (1-based, ceil) — a plain ``acc >= q*n``
        misreports q=0: the target degenerates to 0, which the very
        first (possibly empty) bucket satisfies."""
        if self.n == 0:
            return 0.0
        q = min(1.0, max(0.0, q))
        target = max(1, math.ceil(q * self.n))
        acc = 0
        for b in self.buckets:
            acc += self.counts.get(b, 0)
            if acc >= target:
                return b
        return self.buckets[-1]

    @property
    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0


class Metrics:
    def __init__(self):
        self.histograms: dict[str, Histogram] = defaultdict(Histogram)
        self.gauges: dict[str, float] = {}
        self.counters: dict[str, float] = defaultdict(float)

    def observe(self, name: str, value: float) -> None:
        self.histograms[name].observe(value)

    def set_gauge(self, name: str, value: float, **labels) -> None:
        self.gauges[_key(name, labels)] = value

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        self.counters[_key(name, labels)] += value

    def reset(self) -> None:
        self.histograms.clear()
        self.gauges.clear()
        self.counters.clear()

    def to_prometheus_text(self) -> str:
        lines = []
        for name, h in self.histograms.items():
            lines.append(f"# TYPE {name} histogram")
            # Cumulative buckets (the Prometheus histogram contract:
            # every `le` counts observations <= it, ending at `+Inf`
            # == _count) — `_sum`/`_count` alone is not scrapeable as a
            # histogram and breaks histogram_quantile().
            acc = 0
            for b in h.buckets:
                acc += h.counts.get(b, 0)
                le = "+Inf" if b == math.inf else f"{b:g}"
                lines.append(f'{name}_bucket{{le="{le}"}} {acc}')
            if not h.buckets or h.buckets[-1] != math.inf:
                # Custom bucket lists without an inf edge still need the
                # mandatory +Inf bucket (== _count).
                lines.append(f'{name}_bucket{{le="+Inf"}} {h.n}')
            lines.append(f"{name}_sum {h.total}")
            lines.append(f"{name}_count {h.n}")
        for key, v in self.gauges.items():
            lines.append(f"{key} {v}")
        for key, v in self.counters.items():
            lines.append(f"{key} {v}")
        return "\n".join(lines) + "\n"


def _key(name: str, labels: dict) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


METRICS = Metrics()
