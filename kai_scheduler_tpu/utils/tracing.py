"""Cycle flight recorder: structured span tracing for the decision path.

The per-cycle hot loop (snapshot -> plugin opens -> actions -> kernel
dispatches -> commit) is the paper's latency-critical contribution, yet
``phase_timings`` averages cannot answer the two questions that matter
after an incident: *which span burned the budget of cycle N* and *why is
this PodGroup still pending*.  This module gives every cycle a structured
trace — nested spans with monotonic durations, attributes, and error
status — and keeps the last N complete traces in a bounded in-memory
**flight recorder**, exportable as Chrome trace-event / Perfetto JSON.

Design constraints (the kailint contracts):

- all timing is ``time.perf_counter`` (KAI003: no wall clock in utils/);
- span bookkeeping is thread-local and lock-free on the cycle path; the
  ring lock guards only finished-trace appends and reads (KAI006: no
  blocking work under a lock — trace-file dumps happen outside it);
- memory is bounded at every layer: the ring holds ``capacity`` traces,
  a trace holds at most ``max_spans_per_trace`` spans, and the
  explainability ledger caps groups/reasons per trace — every overflow
  is counted (``dropped_spans`` / ``dropped_rejections``), never silent.

Correlation: the scheduler threads the cycle's ``trace_id`` into
BindRequest specs (``spec.traceId``) and status-updater events
(``spec.traceId``), so a bind object in the store points back at the
exact cycle trace that produced it.  Rejection reasons land in a
per-cycle **explainability ledger** (``CycleTrace.explain``) surfaced at
``GET /explain?podgroup=<name>``.  See docs/OBSERVABILITY.md.

Post-mortem hook: when ``KAI_TRACE_DIR`` is set, every aborted or
degraded cycle's Chrome trace JSON is written there as it completes —
``tools/chaos_matrix.py --trace-dir`` uses this to capture the traces of
failing chaos iterations.

Cross-process propagation (PR 19, the wire observatory): a trace no
longer dies at the process boundary.  ``HTTPKubeAPI`` opens a
``client_span`` around every request and injects the active context as
``X-Kai-Trace`` / ``X-Kai-Span`` headers (W3C ``traceparent`` shape,
flattened to two headers because the only peer is our own apiserver);
the apiserver times each request's dispatch-queue wait / handler /
serialize / sendall phases and records them — tagged with the injected
context — into a bounded ``SpanRing`` (utils/wireobs.py) served at
``GET /debug/spans?since=``.  Once per cycle the operator pulls that
ring and ``graft_remote_spans`` joins the server's spans back into the
owning ring trace, CENTERED inside their client parent span: the two
processes' ``perf_counter`` domains are unrelated, so the only honest
alignment is containment — the residual gap on each side of the server
span IS the wire time, visible in Perfetto instead of lost.  Threads
that carry no live cycle (the commit executor) arm an **ambient wire
context** (``set_wire_context``) so their requests still stamp the
owning cycle's trace and their client spans attach post-hoc.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque

from .logging import LOG
from .metrics import METRICS

# Cross-process trace-context carriers (W3C traceparent analog, split
# into two headers: trace id and the client span awaiting its server
# half).  Shared by httpclient (inject) and apiserver (extract).
TRACE_HEADER = "X-Kai-Trace"
SPAN_HEADER = "X-Kai-Span"


class Span:
    """One timed operation inside a cycle trace.

    ``start_s`` is relative to the trace's origin (monotonic), so spans
    serialize directly into Chrome trace-event ``ts``/``dur`` pairs."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind",
                 "start_s", "duration_s", "attrs", "status", "error")

    def __init__(self, trace_id: str, span_id: str, parent_id: str | None,
                 name: str, kind: str, start_s: float):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.start_s = start_s
        self.duration_s = 0.0
        self.attrs: dict = {}
        self.status = "ok"
        self.error = ""

    def set(self, **attrs) -> None:
        """Attach attributes (kernel label, breaker state, ...)."""
        self.attrs.update(attrs)

    def mark_error(self, message: str) -> None:
        self.status = "error"
        self.error = message[:300]

    def to_event(self) -> dict:
        """Chrome trace-event (Perfetto/about:tracing) complete event."""
        args = dict(self.attrs)
        args["status"] = self.status
        if self.error:
            args["error"] = self.error
        if self.parent_id:
            args["parent"] = self.parent_id
        return {"name": self.name, "cat": self.kind, "ph": "X",
                "ts": round(self.start_s * 1e6, 1),
                "dur": round(self.duration_s * 1e6, 1),
                "pid": 1, "tid": 1, "id": self.span_id, "args": args}


class _NullSpan:
    """Span opened outside an active cycle (offline sessions, bench
    setup): every call is a no-op, so instrumented code never branches."""

    __slots__ = ()
    status = "ok"

    def set(self, **attrs) -> None:
        pass

    def mark_error(self, message: str) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _SpanCtx:
    """Context manager around a span: closes it on exit and converts an
    escaping exception into error status (the exception still
    propagates — tracing observes failures, never swallows them)."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span):
        self._tracer = tracer
        self.span = span

    def __enter__(self):
        return self.span

    def __exit__(self, exc_type, exc, tb):
        if self.span is not _NULL_SPAN:
            if exc is not None and self.span.status == "ok":
                self.span.mark_error(f"{exc_type.__name__}: {exc}")
            self._tracer._close_span(self.span)
        return False


class _ClientSpanCtx:
    """Client half of a cross-process wire span (one HTTP request).

    Three regimes, decided at open time by ``Tracer.client_span``:

    - **live**: a cycle trace is active on this thread — a real nested
      span rides the thread-local stack like any ``Tracer.span``;
    - **deferred**: no live trace, but an ambient wire context is armed
      (commit-executor threads) — the span's id is pre-allocated so the
      ``X-Kai-Span`` header can carry it, the duration is measured here,
      and the finished span attaches to the finalized ring trace on
      exit (same post-hoc path as ``attach_async_span``);
    - **null**: no context at all (watch thread, bench setup) — every
      call no-ops and ``trace_id`` is None, so the caller skips the
      headers.
    """

    __slots__ = ("_tracer", "trace_id", "span_id", "_span", "_name",
                 "_kind", "_parent_id", "_attrs", "_t0")

    def __init__(self, tracer, trace_id=None, span_id=None, span=None,
                 name="", kind="wire", parent_id=None, attrs=None):
        self._tracer = tracer
        self.trace_id = trace_id
        self.span_id = span_id
        self._span = span  # live regime only
        self._name = name
        self._kind = kind
        self._parent_id = parent_id
        self._attrs = dict(attrs) if attrs else {}
        self._t0 = 0.0

    def set(self, **attrs) -> None:
        if self._span is not None:
            self._span.set(**attrs)
        elif self.trace_id is not None:
            self._attrs.update(attrs)

    def mark_error(self, message: str) -> None:
        if self._span is not None:
            self._span.mark_error(message)
        elif self.trace_id is not None:
            self._attrs["status"] = "error"
            self._attrs["error"] = message[:300]

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._span is not None:
            if exc is not None and self._span.status == "ok":
                self._span.mark_error(f"{exc_type.__name__}: {exc}")
            self._tracer._close_span(self._span)
        elif self.trace_id is not None:
            if exc is not None and "error" not in self._attrs:
                self._attrs["status"] = "error"
                self._attrs["error"] = f"{exc_type.__name__}: {exc}"[:300]
            self._tracer._attach_completed_span(
                self.trace_id, self.span_id, self._parent_id, self._name,
                self._kind, time.perf_counter() - self._t0, self._attrs)
        return False


# Shared null client span: requests made with tracing off (observability
# traffic like the /debug/spans pull itself) reuse this.
NULL_CLIENT_SPAN = _ClientSpanCtx(None)


class CycleTrace:
    """One complete scheduling cycle: the root span, its children, the
    abort/degraded verdict, and the explainability ledger."""

    # Ledger bounds: a sustained over-capacity cluster keeps thousands
    # of PodGroups pending every cycle; without caps the ring would hold
    # ring-size x pending-groups x reasons strings live.  Overflow is
    # counted (dropped_rejections), never silent.
    MAX_EXPLAIN_GROUPS = 256
    MAX_REASONS_PER_GROUP = 8

    def __init__(self, trace_id: str, cycle: int, max_spans: int):
        self.trace_id = trace_id
        self.cycle = cycle
        self.t0 = time.perf_counter()
        self.root: Span | None = None
        self.spans: list[Span] = []   # completed spans, completion order
        self.max_spans = max_spans
        self.dropped_spans = 0
        self.aborted: str | None = None
        self.degraded = False
        self.duration_ms = 0.0
        self.explain: dict[str, list[str]] = {}  # podgroup -> reasons
        self.dropped_rejections = 0
        # Wire observatory (PR 19): per-cycle wire-counter delta
        # (attach_wire_summary) and the ids of server-side records
        # already grafted — the graft dedup set, so a re-pulled or
        # replayed /debug/spans record can never join twice.
        self.wire: dict | None = None
        self.grafted: set = set()

    def add_rejection(self, podgroup: str, reason: str) -> None:
        reasons = self.explain.get(podgroup)
        if reasons is None:
            if len(self.explain) >= self.MAX_EXPLAIN_GROUPS:
                self.dropped_rejections += 1
                return
            reasons = self.explain[podgroup] = []
        if reason in reasons:
            return
        if len(reasons) >= self.MAX_REASONS_PER_GROUP:
            self.dropped_rejections += 1
            return
        reasons.append(reason)

    def span_summary(self) -> dict:
        """kind -> {count, total_ms, errors}: where the cycle went."""
        out: dict = {}
        for sp in self.spans:
            entry = out.setdefault(sp.kind, {"count": 0, "total_ms": 0.0,
                                             "errors": 0})
            entry["count"] += 1
            entry["total_ms"] += sp.duration_s * 1e3
            if sp.status == "error":
                entry["errors"] += 1
        for entry in out.values():
            entry["total_ms"] = round(entry["total_ms"], 3)
        return out

    def to_summary(self) -> dict:
        return {"cycle": self.cycle, "trace_id": self.trace_id,
                "duration_ms": round(self.duration_ms, 3),
                "aborted": self.aborted, "degraded": self.degraded,
                "spans": self.span_summary(),
                "dropped_spans": self.dropped_spans,
                "dropped_rejections": self.dropped_rejections,
                "rejected_podgroups": sorted(self.explain),
                "wire": self.wire}

    def to_chrome(self) -> dict:
        """Chrome trace-event JSON: load in Perfetto (ui.perfetto.dev)
        or chrome://tracing."""
        return {"displayTimeUnit": "ms",
                "traceEvents": [sp.to_event() for sp in self.spans],
                "otherData": {"trace_id": self.trace_id,
                              "cycle": self.cycle,
                              "aborted": self.aborted,
                              "degraded": self.degraded,
                              "dropped_spans": self.dropped_spans,
                              "explain": self.explain}}


class Tracer:
    """Thread-safe tracer + bounded flight recorder.

    The active trace is thread-local: one scheduler thread drives one
    cycle, and spans opened on other threads (status-updater workers)
    deliberately no-op instead of racing the cycle's span stack.  Reads
    (`cycles`, `get_trace`, `explain_for`) come from HTTP handler threads
    and take the ring lock; finished traces are immutable."""

    def __init__(self, capacity: int | None = None,
                 max_spans_per_trace: int | None = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("KAI_TRACE_CYCLES", 32))
            except ValueError:
                capacity = 32
        if max_spans_per_trace is None:
            # Fleet-scale cycles (hundreds of nodes over the http wire)
            # legitimately record thousands of wire + grafted server
            # spans per cycle; KAI_TRACE_MAX_SPANS deepens the recorder
            # for those runs while the default keeps tier-1 memory flat.
            try:
                max_spans_per_trace = int(
                    os.environ.get("KAI_TRACE_MAX_SPANS", 512))
            except ValueError:
                max_spans_per_trace = 512
        self.capacity = max(1, capacity)
        self.max_spans_per_trace = max(8, max_spans_per_trace)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._ids = itertools.count(1)
        # podgroup -> latest rejection record ({"cycle", "trace_id",
        # "reasons"}); bounded like ClusterCache._warned_selectors.
        self._explain_latest: dict = {}

    # -- cycle lifecycle ---------------------------------------------------
    def _state(self) -> dict:
        st = getattr(self._local, "state", None)
        if st is None:
            st = self._local.state = {"trace": None, "stack": []}
        return st

    def begin_cycle(self, cycle: int) -> str:
        """Open a cycle trace (and its root span) on this thread; returns
        the trace id the scheduler threads into binds and events."""
        st = self._state()
        if st["trace"] is not None:
            # An exception escaped the previous cycle driver before
            # end_cycle ran: finalize the dangling trace as aborted so
            # the recorder never loses it (and the stack never leaks).
            self.end_cycle(aborted="trace abandoned by next cycle")
        trace_id = f"t{next(self._ids):06d}"
        trace = CycleTrace(trace_id, cycle, self.max_spans_per_trace)
        root = Span(trace_id, f"s{next(self._ids)}", None,
                    "cycle", "cycle", 0.0)
        root.set(cycle=cycle)
        trace.root = root
        st["trace"] = trace
        st["stack"] = [root]
        return trace_id

    def end_cycle(self, aborted: str | None = None, degraded: bool = False,
                  explain: dict | None = None,
                  dropped_rejections: int = 0,
                  resolved=()) -> CycleTrace | None:
        """Finalize the active trace: close leftover spans, record the
        verdict, merge the explainability ledger, push to the ring, emit
        per-span-kind latency histograms, and (when KAI_TRACE_DIR is
        armed) dump aborted/degraded traces for post-mortem.

        ``dropped_rejections``: rejections the caller discarded at the
        source (ledger caps) — folded in BEFORE publication so readers
        and the post-mortem dump never see a half-counted trace.
        ``resolved``: PodGroup names this cycle saw WITHOUT any rejection
        (scheduled, or no longer pending) — their stale ``/explain``
        records drop, so an operator is never pointed at a group that is
        actually running."""
        st = self._state()
        trace: CycleTrace | None = st["trace"]
        if trace is None:
            return None
        now = time.perf_counter()
        # Leftover spans above the root belong to an aborted phase whose
        # exception bypassed their context managers; close deepest-first.
        while len(st["stack"]) > 1:
            sp = st["stack"].pop()
            sp.duration_s = (now - trace.t0) - sp.start_s
            if aborted and sp.status == "ok":
                sp.mark_error(aborted)
            self._record_span(trace, sp)
        root = st["stack"].pop()
        root.duration_s = now - trace.t0
        if aborted:
            root.mark_error(aborted)
        trace.spans.append(root)  # the root always survives the span cap
        trace.aborted = aborted
        trace.degraded = bool(degraded)
        trace.duration_ms = root.duration_s * 1e3
        for podgroup, reasons in (explain or {}).items():
            for reason in reasons:
                trace.add_rejection(podgroup, reason)
        trace.dropped_rejections += int(dropped_rejections)
        st["trace"] = None
        st["stack"] = []
        for sp in trace.spans:
            METRICS.observe(f"cycle_span_{sp.kind}_latency_ms",
                            sp.duration_s * 1e3)
        with self._lock:
            self._ring.append(trace)
            for name in resolved:
                self._explain_latest.pop(name, None)
            if len(self._explain_latest) >= 4096:
                # Bounded memory in a long-lived daemon whose PodGroup
                # names churn: reset over growing forever.
                self._explain_latest.clear()
            for podgroup, reasons in trace.explain.items():
                self._explain_latest[podgroup] = {
                    "podgroup": podgroup, "cycle": trace.cycle,
                    "trace_id": trace.trace_id, "reasons": list(reasons)}
        self._maybe_dump(trace)
        return trace

    # -- spans -------------------------------------------------------------
    def span(self, name: str, kind: str, **attrs) -> _SpanCtx:
        """Open a child span under the current one.  Outside an active
        cycle this returns a null span — instrumentation is always safe
        to leave in place."""
        st = self._state()
        trace: CycleTrace | None = st["trace"]
        if trace is None:
            return _SpanCtx(self, _NULL_SPAN)
        parent = st["stack"][-1] if st["stack"] else None
        sp = Span(trace.trace_id, f"s{next(self._ids)}",
                  parent.span_id if parent is not None else None,
                  name, kind, time.perf_counter() - trace.t0)
        if attrs:
            sp.attrs.update(attrs)
        st["stack"].append(sp)
        return _SpanCtx(self, sp)

    def _close_span(self, span: Span) -> None:
        st = self._state()
        trace: CycleTrace | None = st["trace"]
        if st["stack"] and st["stack"][-1] is span:
            st["stack"].pop()
        else:  # out-of-order close (defensive): remove wherever it sits
            try:
                st["stack"].remove(span)
            except ValueError:
                pass
        if trace is None:
            return
        span.duration_s = (time.perf_counter() - trace.t0) - span.start_s
        self._record_span(trace, span)

    @staticmethod
    def _record_span(trace: CycleTrace, span: Span) -> None:
        if len(trace.spans) < trace.max_spans - 1:  # -1: root's seat
            trace.spans.append(span)
        else:
            trace.dropped_spans += 1

    def current_trace_id(self) -> str | None:
        st = getattr(self._local, "state", None)
        trace = st["trace"] if st else None
        return trace.trace_id if trace is not None else None

    # -- cross-process context (the wire observatory) ----------------------
    def current_context(self) -> tuple[str | None, str | None]:
        """(trace_id, span_id) to inject into outbound headers: the live
        thread-local trace's innermost open span when a cycle is active
        on this thread, else the ambient wire context armed by the
        commit executor, else (None, None)."""
        st = getattr(self._local, "state", None)
        trace = st["trace"] if st else None
        if trace is not None:
            stack = st["stack"]
            top = stack[-1] if stack else trace.root
            return trace.trace_id, (top.span_id if top is not None
                                    else None)
        ambient = getattr(self._local, "ambient", None)
        if ambient is not None:
            return ambient
        return None, None

    def set_wire_context(self, trace_id: str | None,
                         span_id: str | None = None) -> None:
        """Arm an ambient wire context on THIS thread: requests made
        here (commit executor, control epilogue) stamp ``trace_id``
        even though the cycle trace was finalized on another thread.
        Pair with ``clear_wire_context`` in a finally."""
        self._local.ambient = (trace_id, span_id) if trace_id else None

    def clear_wire_context(self) -> None:
        self._local.ambient = None

    def client_span(self, name: str, kind: str = "wire",
                    **attrs) -> _ClientSpanCtx:
        """Open the client half of a cross-process span (one outbound
        request).  See ``_ClientSpanCtx`` for the three regimes; the
        returned ctx's ``trace_id``/``span_id`` are what the transport
        injects as ``X-Kai-Trace``/``X-Kai-Span``."""
        st = self._state()
        trace: CycleTrace | None = st["trace"]
        if trace is not None:  # live: a real span on this thread's stack
            parent = st["stack"][-1] if st["stack"] else None
            sp = Span(trace.trace_id, f"s{next(self._ids)}",
                      parent.span_id if parent is not None else None,
                      name, kind, time.perf_counter() - trace.t0)
            if attrs:
                sp.attrs.update(attrs)
            st["stack"].append(sp)
            return _ClientSpanCtx(self, trace.trace_id, sp.span_id,
                                  span=sp)
        ambient = getattr(self._local, "ambient", None)
        if ambient is not None and ambient[0] is not None:  # deferred
            return _ClientSpanCtx(self, ambient[0],
                                  f"s{next(self._ids)}", name=name,
                                  kind=kind, parent_id=ambient[1],
                                  attrs=attrs)
        return NULL_CLIENT_SPAN

    def note_pipelined(self) -> None:
        """Mark the active cycle trace as running in overlapped-pipeline
        mode (the root span carries ``pipelined=True``)."""
        st = getattr(self._local, "state", None)
        trace = st["trace"] if st else None
        if trace is not None and trace.root is not None:
            trace.root.set(pipelined=True)

    def attach_async_span(self, trace_id: str | None, name: str,
                          kind: str, duration_s: float, **attrs) -> bool:
        """Attach a completed span to an ALREADY-FINALIZED trace still in
        the ring — the overlapped pipeline's commit stages finish after
        their cycle's ``end_cycle`` ran on the scheduler thread, and the
        flight recorder must still show where cycle N's commit budget
        went.  Thread-safe (ring lock); a trace that already aged out of
        the ring drops the span (returns False)."""
        if not self._attach_completed_span(trace_id,
                                           f"s{next(self._ids)}", None,
                                           name, kind, duration_s,
                                           attrs):
            return False
        METRICS.observe(f"cycle_span_{kind}_latency_ms",
                        duration_s * 1e3)
        return True

    def _attach_completed_span(self, trace_id, span_id, parent_id, name,
                               kind, duration_s, attrs) -> bool:
        """Append an already-measured span to a finalized ring trace
        (attach_async_span and the deferred client-span regime).  With
        no explicit parent the span hangs off the root; the start is
        back-dated from now so async work lands where it actually ran
        relative to the cycle origin."""
        if trace_id is None:
            return False
        with self._lock:
            for trace in reversed(self._ring):
                if trace.trace_id != trace_id:
                    continue
                root = trace.root
                pid = parent_id or (root.span_id if root is not None
                                    else None)
                sp = Span(trace_id, span_id, pid, name, kind,
                          max(0.0, time.perf_counter() - trace.t0
                              - duration_s))
                sp.duration_s = duration_s
                if attrs:
                    sp.attrs.update(attrs)
                self._record_span(trace, sp)
                return True
        return False

    # Phase order inside one server-side request record: insertion
    # order matters — grafted phase children are laid out sequentially.
    _SERVER_PHASES = ("queue_wait", "handler", "serialize", "sendall")

    def graft_remote_spans(self, remote_spans) -> dict:
        """Join server-side span records (``GET /debug/spans``) into
        their owning ring traces; returns counts
        ``{"grafted", "orphaned", "duplicate"}``.

        Each record carries the (trace, parent) context the client
        injected.  The server's ``perf_counter`` domain is unrelated to
        ours, so a grafted request span is CENTERED inside its client
        parent span — the residual left/right gap is the wire time,
        attributed instead of invisible.  Its phases become child spans
        (kinds ``server_queue_wait`` / ``server_handler`` /
        ``server_serialize`` / ``server_sendall``) laid out
        sequentially.  Records that carried no context at all (watch
        fanout bursts, pre-cycle traffic) are expected and count as
        unattributed; records whose trace already aged out of the ring
        count as orphaned; a record id seen before on its trace counts
        as duplicate and never double-grafts (``CycleTrace.grafted``)."""
        out = {"grafted": 0, "orphaned": 0, "duplicate": 0,
               "unattributed": 0}
        if not remote_spans:
            return out
        with self._lock:
            traces = {t.trace_id: t for t in self._ring}
            for rec in remote_spans:
                tid = rec.get("trace")
                if not tid:
                    out["unattributed"] += 1
                    continue
                trace = traces.get(tid)
                if trace is None:
                    out["orphaned"] += 1
                    continue
                rid = rec.get("id")
                if rid in trace.grafted:
                    out["duplicate"] += 1
                    continue
                trace.grafted.add(rid)
                parent = None
                parent_id = rec.get("parent")
                if parent_id:
                    for sp in trace.spans:
                        if sp.span_id == parent_id:
                            parent = sp
                            break
                dur = max(0.0, float(rec.get("dur_s") or 0.0))
                if parent is not None:
                    start = parent.start_s + max(
                        0.0, (parent.duration_s - dur) / 2.0)
                    pid = parent.span_id
                else:
                    # Client span lost (span cap) or never existed:
                    # hang off the root at the trace's tail.
                    start = max(0.0, trace.duration_ms / 1e3 - dur)
                    pid = (trace.root.span_id
                           if trace.root is not None else None)
                srv = Span(trace.trace_id, f"s{next(self._ids)}", pid,
                           str(rec.get("name") or "server"),
                           str(rec.get("kind") or "server_request"),
                           start)
                srv.duration_s = dur
                srv.attrs.update(
                    {k: rec[k] for k in ("path", "status", "bytes_in",
                                         "bytes_out", "frames",
                                         "lag_frames", "stream")
                     if k in rec})
                srv.attrs["remote_id"] = rid
                self._record_span(trace, srv)
                cursor = start
                phases = rec.get("phases") or {}
                for phase in self._SERVER_PHASES:
                    phase_s = max(0.0, float(phases.get(phase) or 0.0))
                    if phase_s <= 0.0:
                        continue
                    child = Span(trace.trace_id, f"s{next(self._ids)}",
                                 srv.span_id,
                                 f"{srv.name}:{phase}",
                                 f"server_{phase}", cursor)
                    child.duration_s = phase_s
                    cursor += phase_s
                    self._record_span(trace, child)
                out["grafted"] += 1
        if out["grafted"]:
            METRICS.inc("wire_spans_grafted_total", out["grafted"])
        if out["orphaned"]:
            METRICS.inc("wire_spans_orphaned_total", out["orphaned"])
        if out["duplicate"]:
            METRICS.inc("wire_spans_duplicate_total", out["duplicate"])
        if out["unattributed"]:
            METRICS.inc("wire_spans_unattributed_total",
                        out["unattributed"])
        return out

    def attach_wire_summary(self, trace_id: str | None,
                            wire: dict) -> bool:
        """Attach this cycle's wire-counter delta (wireobs.wire_delta)
        to its finalized ring trace — the `wire` section each row of
        ``GET /debug/cycles`` carries."""
        if trace_id is None or not wire:
            return False
        with self._lock:
            for trace in reversed(self._ring):
                if trace.trace_id == trace_id:
                    trace.wire = dict(wire)
                    return True
        return False

    def export_chrome(self, key: str | None = None) -> dict | None:
        """Chrome-trace JSON for one ring entry, serialized UNDER the
        ring lock (async commit spans may still be attaching to a
        finalized trace — an unlocked ``to_chrome`` could read a
        half-appended span list)."""
        with self._lock:
            if not self._ring:
                return None
            if key is None or key == "":
                return self._ring[-1].to_chrome()
            for trace in reversed(self._ring):
                if trace.trace_id == key or str(trace.cycle) == key:
                    return trace.to_chrome()
        return None

    def note_rejection(self, podgroup: str, reason: str) -> None:
        """Record a filter/score rejection into the active cycle's
        explainability ledger (actions call this as failures happen; the
        cycle driver merges fit errors again at end_cycle)."""
        st = getattr(self._local, "state", None)
        trace = st["trace"] if st else None
        if trace is not None:
            trace.add_rejection(podgroup, reason)

    # -- flight-recorder reads (HTTP endpoints, tests) ---------------------
    def cycles(self) -> list[dict]:
        """Last-N cycle summaries, newest first (GET /debug/cycles)."""
        with self._lock:
            return [t.to_summary() for t in reversed(self._ring)]

    def get_trace(self, key: str | None = None) -> CycleTrace | None:
        """Look a trace up by trace id or cycle number; None = latest."""
        with self._lock:
            if not self._ring:
                return None
            if key is None or key == "":
                return self._ring[-1]
            for trace in reversed(self._ring):
                if trace.trace_id == key or str(trace.cycle) == key:
                    return trace
        return None

    def explain_for(self, podgroup: str) -> dict | None:
        """Latest unschedulability record for a PodGroup, or None."""
        with self._lock:
            record = self._explain_latest.get(podgroup)
            return dict(record) if record is not None else None

    def explained_podgroups(self) -> list[str]:
        with self._lock:
            return sorted(self._explain_latest)

    def reset(self) -> None:
        """Drop all recorded state (tests)."""
        with self._lock:
            self._ring.clear()
            self._explain_latest.clear()
        self._local = threading.local()

    # -- post-mortem dump --------------------------------------------------
    def _maybe_dump(self, trace: CycleTrace) -> None:
        out_dir = os.environ.get("KAI_TRACE_DIR")
        if not out_dir or not (trace.aborted or trace.degraded):
            return
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(
                out_dir, f"cycle_{trace.cycle}_{trace.trace_id}.json")
            with open(path, "w") as fh:
                json.dump(trace.to_chrome(), fh)
        except OSError as exc:
            METRICS.inc("trace_dump_errors")
            LOG.warning("cycle trace dump to %s failed: %s", out_dir, exc)


# Process-wide tracer, like METRICS: every layer of the decision path
# records into it without plumbing, and the server reads it back out.
TRACER = Tracer()
