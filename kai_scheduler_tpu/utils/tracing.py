"""Cycle flight recorder: structured span tracing for the decision path.

The per-cycle hot loop (snapshot -> plugin opens -> actions -> kernel
dispatches -> commit) is the paper's latency-critical contribution, yet
``phase_timings`` averages cannot answer the two questions that matter
after an incident: *which span burned the budget of cycle N* and *why is
this PodGroup still pending*.  This module gives every cycle a structured
trace — nested spans with monotonic durations, attributes, and error
status — and keeps the last N complete traces in a bounded in-memory
**flight recorder**, exportable as Chrome trace-event / Perfetto JSON.

Design constraints (the kailint contracts):

- all timing is ``time.perf_counter`` (KAI003: no wall clock in utils/);
- span bookkeeping is thread-local and lock-free on the cycle path; the
  ring lock guards only finished-trace appends and reads (KAI006: no
  blocking work under a lock — trace-file dumps happen outside it);
- memory is bounded at every layer: the ring holds ``capacity`` traces,
  a trace holds at most ``max_spans_per_trace`` spans, and the
  explainability ledger caps groups/reasons per trace — every overflow
  is counted (``dropped_spans`` / ``dropped_rejections``), never silent.

Correlation: the scheduler threads the cycle's ``trace_id`` into
BindRequest specs (``spec.traceId``) and status-updater events
(``spec.traceId``), so a bind object in the store points back at the
exact cycle trace that produced it.  Rejection reasons land in a
per-cycle **explainability ledger** (``CycleTrace.explain``) surfaced at
``GET /explain?podgroup=<name>``.  See docs/OBSERVABILITY.md.

Post-mortem hook: when ``KAI_TRACE_DIR`` is set, every aborted or
degraded cycle's Chrome trace JSON is written there as it completes —
``tools/chaos_matrix.py --trace-dir`` uses this to capture the traces of
failing chaos iterations.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time
from collections import deque

from .logging import LOG
from .metrics import METRICS


class Span:
    """One timed operation inside a cycle trace.

    ``start_s`` is relative to the trace's origin (monotonic), so spans
    serialize directly into Chrome trace-event ``ts``/``dur`` pairs."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "kind",
                 "start_s", "duration_s", "attrs", "status", "error")

    def __init__(self, trace_id: str, span_id: str, parent_id: str | None,
                 name: str, kind: str, start_s: float):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.kind = kind
        self.start_s = start_s
        self.duration_s = 0.0
        self.attrs: dict = {}
        self.status = "ok"
        self.error = ""

    def set(self, **attrs) -> None:
        """Attach attributes (kernel label, breaker state, ...)."""
        self.attrs.update(attrs)

    def mark_error(self, message: str) -> None:
        self.status = "error"
        self.error = message[:300]

    def to_event(self) -> dict:
        """Chrome trace-event (Perfetto/about:tracing) complete event."""
        args = dict(self.attrs)
        args["status"] = self.status
        if self.error:
            args["error"] = self.error
        if self.parent_id:
            args["parent"] = self.parent_id
        return {"name": self.name, "cat": self.kind, "ph": "X",
                "ts": round(self.start_s * 1e6, 1),
                "dur": round(self.duration_s * 1e6, 1),
                "pid": 1, "tid": 1, "id": self.span_id, "args": args}


class _NullSpan:
    """Span opened outside an active cycle (offline sessions, bench
    setup): every call is a no-op, so instrumented code never branches."""

    __slots__ = ()
    status = "ok"

    def set(self, **attrs) -> None:
        pass

    def mark_error(self, message: str) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _SpanCtx:
    """Context manager around a span: closes it on exit and converts an
    escaping exception into error status (the exception still
    propagates — tracing observes failures, never swallows them)."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span):
        self._tracer = tracer
        self.span = span

    def __enter__(self):
        return self.span

    def __exit__(self, exc_type, exc, tb):
        if self.span is not _NULL_SPAN:
            if exc is not None and self.span.status == "ok":
                self.span.mark_error(f"{exc_type.__name__}: {exc}")
            self._tracer._close_span(self.span)
        return False


class CycleTrace:
    """One complete scheduling cycle: the root span, its children, the
    abort/degraded verdict, and the explainability ledger."""

    # Ledger bounds: a sustained over-capacity cluster keeps thousands
    # of PodGroups pending every cycle; without caps the ring would hold
    # ring-size x pending-groups x reasons strings live.  Overflow is
    # counted (dropped_rejections), never silent.
    MAX_EXPLAIN_GROUPS = 256
    MAX_REASONS_PER_GROUP = 8

    def __init__(self, trace_id: str, cycle: int, max_spans: int):
        self.trace_id = trace_id
        self.cycle = cycle
        self.t0 = time.perf_counter()
        self.root: Span | None = None
        self.spans: list[Span] = []   # completed spans, completion order
        self.max_spans = max_spans
        self.dropped_spans = 0
        self.aborted: str | None = None
        self.degraded = False
        self.duration_ms = 0.0
        self.explain: dict[str, list[str]] = {}  # podgroup -> reasons
        self.dropped_rejections = 0

    def add_rejection(self, podgroup: str, reason: str) -> None:
        reasons = self.explain.get(podgroup)
        if reasons is None:
            if len(self.explain) >= self.MAX_EXPLAIN_GROUPS:
                self.dropped_rejections += 1
                return
            reasons = self.explain[podgroup] = []
        if reason in reasons:
            return
        if len(reasons) >= self.MAX_REASONS_PER_GROUP:
            self.dropped_rejections += 1
            return
        reasons.append(reason)

    def span_summary(self) -> dict:
        """kind -> {count, total_ms, errors}: where the cycle went."""
        out: dict = {}
        for sp in self.spans:
            entry = out.setdefault(sp.kind, {"count": 0, "total_ms": 0.0,
                                             "errors": 0})
            entry["count"] += 1
            entry["total_ms"] += sp.duration_s * 1e3
            if sp.status == "error":
                entry["errors"] += 1
        for entry in out.values():
            entry["total_ms"] = round(entry["total_ms"], 3)
        return out

    def to_summary(self) -> dict:
        return {"cycle": self.cycle, "trace_id": self.trace_id,
                "duration_ms": round(self.duration_ms, 3),
                "aborted": self.aborted, "degraded": self.degraded,
                "spans": self.span_summary(),
                "dropped_spans": self.dropped_spans,
                "dropped_rejections": self.dropped_rejections,
                "rejected_podgroups": sorted(self.explain)}

    def to_chrome(self) -> dict:
        """Chrome trace-event JSON: load in Perfetto (ui.perfetto.dev)
        or chrome://tracing."""
        return {"displayTimeUnit": "ms",
                "traceEvents": [sp.to_event() for sp in self.spans],
                "otherData": {"trace_id": self.trace_id,
                              "cycle": self.cycle,
                              "aborted": self.aborted,
                              "degraded": self.degraded,
                              "dropped_spans": self.dropped_spans,
                              "explain": self.explain}}


class Tracer:
    """Thread-safe tracer + bounded flight recorder.

    The active trace is thread-local: one scheduler thread drives one
    cycle, and spans opened on other threads (status-updater workers)
    deliberately no-op instead of racing the cycle's span stack.  Reads
    (`cycles`, `get_trace`, `explain_for`) come from HTTP handler threads
    and take the ring lock; finished traces are immutable."""

    def __init__(self, capacity: int | None = None,
                 max_spans_per_trace: int = 512):
        if capacity is None:
            try:
                capacity = int(os.environ.get("KAI_TRACE_CYCLES", 32))
            except ValueError:
                capacity = 32
        self.capacity = max(1, capacity)
        self.max_spans_per_trace = max(8, max_spans_per_trace)
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._ids = itertools.count(1)
        # podgroup -> latest rejection record ({"cycle", "trace_id",
        # "reasons"}); bounded like ClusterCache._warned_selectors.
        self._explain_latest: dict = {}

    # -- cycle lifecycle ---------------------------------------------------
    def _state(self) -> dict:
        st = getattr(self._local, "state", None)
        if st is None:
            st = self._local.state = {"trace": None, "stack": []}
        return st

    def begin_cycle(self, cycle: int) -> str:
        """Open a cycle trace (and its root span) on this thread; returns
        the trace id the scheduler threads into binds and events."""
        st = self._state()
        if st["trace"] is not None:
            # An exception escaped the previous cycle driver before
            # end_cycle ran: finalize the dangling trace as aborted so
            # the recorder never loses it (and the stack never leaks).
            self.end_cycle(aborted="trace abandoned by next cycle")
        trace_id = f"t{next(self._ids):06d}"
        trace = CycleTrace(trace_id, cycle, self.max_spans_per_trace)
        root = Span(trace_id, f"s{next(self._ids)}", None,
                    "cycle", "cycle", 0.0)
        root.set(cycle=cycle)
        trace.root = root
        st["trace"] = trace
        st["stack"] = [root]
        return trace_id

    def end_cycle(self, aborted: str | None = None, degraded: bool = False,
                  explain: dict | None = None,
                  dropped_rejections: int = 0,
                  resolved=()) -> CycleTrace | None:
        """Finalize the active trace: close leftover spans, record the
        verdict, merge the explainability ledger, push to the ring, emit
        per-span-kind latency histograms, and (when KAI_TRACE_DIR is
        armed) dump aborted/degraded traces for post-mortem.

        ``dropped_rejections``: rejections the caller discarded at the
        source (ledger caps) — folded in BEFORE publication so readers
        and the post-mortem dump never see a half-counted trace.
        ``resolved``: PodGroup names this cycle saw WITHOUT any rejection
        (scheduled, or no longer pending) — their stale ``/explain``
        records drop, so an operator is never pointed at a group that is
        actually running."""
        st = self._state()
        trace: CycleTrace | None = st["trace"]
        if trace is None:
            return None
        now = time.perf_counter()
        # Leftover spans above the root belong to an aborted phase whose
        # exception bypassed their context managers; close deepest-first.
        while len(st["stack"]) > 1:
            sp = st["stack"].pop()
            sp.duration_s = (now - trace.t0) - sp.start_s
            if aborted and sp.status == "ok":
                sp.mark_error(aborted)
            self._record_span(trace, sp)
        root = st["stack"].pop()
        root.duration_s = now - trace.t0
        if aborted:
            root.mark_error(aborted)
        trace.spans.append(root)  # the root always survives the span cap
        trace.aborted = aborted
        trace.degraded = bool(degraded)
        trace.duration_ms = root.duration_s * 1e3
        for podgroup, reasons in (explain or {}).items():
            for reason in reasons:
                trace.add_rejection(podgroup, reason)
        trace.dropped_rejections += int(dropped_rejections)
        st["trace"] = None
        st["stack"] = []
        for sp in trace.spans:
            METRICS.observe(f"cycle_span_{sp.kind}_latency_ms",
                            sp.duration_s * 1e3)
        with self._lock:
            self._ring.append(trace)
            for name in resolved:
                self._explain_latest.pop(name, None)
            if len(self._explain_latest) >= 4096:
                # Bounded memory in a long-lived daemon whose PodGroup
                # names churn: reset over growing forever.
                self._explain_latest.clear()
            for podgroup, reasons in trace.explain.items():
                self._explain_latest[podgroup] = {
                    "podgroup": podgroup, "cycle": trace.cycle,
                    "trace_id": trace.trace_id, "reasons": list(reasons)}
        self._maybe_dump(trace)
        return trace

    # -- spans -------------------------------------------------------------
    def span(self, name: str, kind: str, **attrs) -> _SpanCtx:
        """Open a child span under the current one.  Outside an active
        cycle this returns a null span — instrumentation is always safe
        to leave in place."""
        st = self._state()
        trace: CycleTrace | None = st["trace"]
        if trace is None:
            return _SpanCtx(self, _NULL_SPAN)
        parent = st["stack"][-1] if st["stack"] else None
        sp = Span(trace.trace_id, f"s{next(self._ids)}",
                  parent.span_id if parent is not None else None,
                  name, kind, time.perf_counter() - trace.t0)
        if attrs:
            sp.attrs.update(attrs)
        st["stack"].append(sp)
        return _SpanCtx(self, sp)

    def _close_span(self, span: Span) -> None:
        st = self._state()
        trace: CycleTrace | None = st["trace"]
        if st["stack"] and st["stack"][-1] is span:
            st["stack"].pop()
        else:  # out-of-order close (defensive): remove wherever it sits
            try:
                st["stack"].remove(span)
            except ValueError:
                pass
        if trace is None:
            return
        span.duration_s = (time.perf_counter() - trace.t0) - span.start_s
        self._record_span(trace, span)

    @staticmethod
    def _record_span(trace: CycleTrace, span: Span) -> None:
        if len(trace.spans) < trace.max_spans - 1:  # -1: root's seat
            trace.spans.append(span)
        else:
            trace.dropped_spans += 1

    def current_trace_id(self) -> str | None:
        st = getattr(self._local, "state", None)
        trace = st["trace"] if st else None
        return trace.trace_id if trace is not None else None

    def note_pipelined(self) -> None:
        """Mark the active cycle trace as running in overlapped-pipeline
        mode (the root span carries ``pipelined=True``)."""
        st = getattr(self._local, "state", None)
        trace = st["trace"] if st else None
        if trace is not None and trace.root is not None:
            trace.root.set(pipelined=True)

    def attach_async_span(self, trace_id: str | None, name: str,
                          kind: str, duration_s: float, **attrs) -> bool:
        """Attach a completed span to an ALREADY-FINALIZED trace still in
        the ring — the overlapped pipeline's commit stages finish after
        their cycle's ``end_cycle`` ran on the scheduler thread, and the
        flight recorder must still show where cycle N's commit budget
        went.  Thread-safe (ring lock); a trace that already aged out of
        the ring drops the span (returns False)."""
        if trace_id is None:
            return False
        with self._lock:
            for trace in reversed(self._ring):
                if trace.trace_id != trace_id:
                    continue
                root = trace.root
                sp = Span(trace_id, f"s{next(self._ids)}",
                          root.span_id if root is not None else None,
                          name, kind,
                          max(0.0, time.perf_counter() - trace.t0
                              - duration_s))
                sp.duration_s = duration_s
                if attrs:
                    sp.attrs.update(attrs)
                self._record_span(trace, sp)
                break
            else:
                return False
        METRICS.observe(f"cycle_span_{kind}_latency_ms",
                        duration_s * 1e3)
        return True

    def export_chrome(self, key: str | None = None) -> dict | None:
        """Chrome-trace JSON for one ring entry, serialized UNDER the
        ring lock (async commit spans may still be attaching to a
        finalized trace — an unlocked ``to_chrome`` could read a
        half-appended span list)."""
        with self._lock:
            if not self._ring:
                return None
            if key is None or key == "":
                return self._ring[-1].to_chrome()
            for trace in reversed(self._ring):
                if trace.trace_id == key or str(trace.cycle) == key:
                    return trace.to_chrome()
        return None

    def note_rejection(self, podgroup: str, reason: str) -> None:
        """Record a filter/score rejection into the active cycle's
        explainability ledger (actions call this as failures happen; the
        cycle driver merges fit errors again at end_cycle)."""
        st = getattr(self._local, "state", None)
        trace = st["trace"] if st else None
        if trace is not None:
            trace.add_rejection(podgroup, reason)

    # -- flight-recorder reads (HTTP endpoints, tests) ---------------------
    def cycles(self) -> list[dict]:
        """Last-N cycle summaries, newest first (GET /debug/cycles)."""
        with self._lock:
            return [t.to_summary() for t in reversed(self._ring)]

    def get_trace(self, key: str | None = None) -> CycleTrace | None:
        """Look a trace up by trace id or cycle number; None = latest."""
        with self._lock:
            if not self._ring:
                return None
            if key is None or key == "":
                return self._ring[-1]
            for trace in reversed(self._ring):
                if trace.trace_id == key or str(trace.cycle) == key:
                    return trace
        return None

    def explain_for(self, podgroup: str) -> dict | None:
        """Latest unschedulability record for a PodGroup, or None."""
        with self._lock:
            record = self._explain_latest.get(podgroup)
            return dict(record) if record is not None else None

    def explained_podgroups(self) -> list[str]:
        with self._lock:
            return sorted(self._explain_latest)

    def reset(self) -> None:
        """Drop all recorded state (tests)."""
        with self._lock:
            self._ring.clear()
            self._explain_latest.clear()
        self._local = threading.local()

    # -- post-mortem dump --------------------------------------------------
    def _maybe_dump(self, trace: CycleTrace) -> None:
        out_dir = os.environ.get("KAI_TRACE_DIR")
        if not out_dir or not (trace.aborted or trace.degraded):
            return
        try:
            os.makedirs(out_dir, exist_ok=True)
            path = os.path.join(
                out_dir, f"cycle_{trace.cycle}_{trace.trace_id}.json")
            with open(path, "w") as fh:
                json.dump(trace.to_chrome(), fh)
        except OSError as exc:
            METRICS.inc("trace_dump_errors")
            LOG.warning("cycle trace dump to %s failed: %s", out_dir, exc)


# Process-wide tracer, like METRICS: every layer of the decision path
# records into it without plumbing, and the server reads it back out.
TRACER = Tracer()
