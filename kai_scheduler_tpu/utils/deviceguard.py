"""Device-guard: fault-tolerant dispatch of device-kernel calls.

The scheduler's latency-critical cycle puts a JAX/XLA device in the middle
of every placement decision — and a hung PJRT client blocks in C where no
in-process alarm can interrupt it (four bench rounds lost to exactly that,
VERDICT.md).  Production AI-cluster schedulers treat accelerator-path
failure as a first-class *degraded mode*, not a crash.  This module gives
the fleet that property:

- **Watchdog deadlines**: every guarded call runs on a worker thread; the
  calling (cycle) thread waits at most ``deadline_s`` and abandons the
  worker on expiry, so a hung XLA call can never block a cycle.
- **Bounded retry** with exponential backoff + deterministic jitter for
  transient device errors.
- **Circuit breaker**: after ``breaker_threshold`` consecutive failures the
  guard trips OPEN and routes calls straight to the CPU fallback path
  (re-running the same computation pinned to the host backend).  After
  ``breaker_cooloff_s`` it half-open-probes one call back through the
  device; success closes the breaker, failure re-opens it.
- **Deterministic fault injection** (``KAI_FAULT_INJECT`` env or the
  daemon's ``--fault-inject`` flag): ``hang``, ``slow:<ms>``, ``error``,
  ``flaky:<p>``, ``badshape`` — so all of the above is unit-testable
  without a real TPU (the chaos ring, tests/test_device_guard.py).

Observability: counters ``device_guard_{timeouts,retries,trips,probes,
fallback_calls,bad_results}`` and the gauge ``device_guard_state``
(0=closed, 1=half-open, 2=open) land in utils.metrics; state is exposed on
the daemon's ``/healthz`` (degraded, not dead).  See docs/DEGRADATION.md
for the full degraded-mode contract.
"""

from __future__ import annotations

import os
import queue
import random
import threading
import time

from .logging import LOG
from .metrics import METRICS

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
_STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class DeviceGuardError(RuntimeError):
    """A guarded call failed on the device AND no fallback succeeded."""


class DeviceTimeout(DeviceGuardError):
    """The watchdog deadline expired before the device call returned."""


class DeviceBadResult(DeviceGuardError):
    """The device returned a result the caller's validator rejected."""


class CycleDeadlineExceeded(DeviceGuardError):
    """The whole-cycle deadline expired; the dispatch was not attempted."""


class _Cancelled(Exception):
    """Internal: an abandoned worker noticed its cancel event."""


# -- watchdog primitives ------------------------------------------------------

class Watchdog:
    """Arm ``callback`` to fire once after ``seconds`` unless cancelled.

    The reusable deadline primitive behind the guard (and the bench
    orchestrator's child budgets): a daemon timer thread, a ``fired``
    flag, and idempotent ``cancel``."""

    def __init__(self, seconds: float, callback, reason: str = ""):
        self.reason = reason
        self.fired = False
        self._lock = threading.Lock()

        def fire():
            with self._lock:
                if self.fired:
                    return
                self.fired = True
            callback()

        self._timer = threading.Timer(max(0.001, seconds), fire)
        self._timer.daemon = True

    def start(self) -> "Watchdog":
        self._timer.start()
        return self

    def cancel(self) -> None:
        with self._lock:
            self.fired = True  # too late to fire now
        self._timer.cancel()


class _Worker:
    """A reusable watchdog worker: one daemon thread, one-job inbox.

    Spawning a thread per dispatch would put ~0.1ms of pure overhead on
    every kernel call of the <100ms-p99 scheduling hot path; instead
    healthy workers are parked in ``_IDLE`` and reused.  A worker whose
    call outlived its deadline is simply never returned to the pool —
    when (if) the hung call finally finishes, the thread parks on its
    empty inbox forever, which leaks no more than the abandoned
    per-call thread did."""

    def __init__(self):
        self.inbox: queue.Queue = queue.Queue(maxsize=1)
        threading.Thread(target=self._loop, daemon=True,
                         name="deviceguard-worker").start()

    def _loop(self):
        while True:
            job = self.inbox.get()
            if job is None:  # retired: the idle pool was already full
                return
            fn, box, done, cancel = job
            try:
                try:
                    box.append(("ok", fn(cancel=cancel)))
                except TypeError as exc:
                    # fn doesn't take the cancel kwarg; plain call.  Only
                    # the signature mismatch is retried — a TypeError
                    # raised from inside fn(cancel=...) must not run fn
                    # twice.
                    if "cancel" not in str(exc):
                        raise
                    box.append(("ok", fn()))
            except _Cancelled:
                pass  # abandoned worker exiting quietly
            except BaseException as exc:  # noqa: BLE001 — relayed
                box.append(("err", exc))
            finally:
                done.set()


_IDLE: list = []
_IDLE_LOCK = threading.Lock()
_MAX_IDLE = 4


def run_with_deadline(fn, deadline_s: float | None, label: str = "device"):
    """Run ``fn()`` on a watchdog worker, waiting at most ``deadline_s``.

    On expiry the worker is ABANDONED (daemon thread; a cooperative
    cancel event is set so injection-driven hangs exit promptly) and
    DeviceTimeout is raised — the caller's thread is never blocked past
    the deadline.  ``deadline_s`` None or <= 0 runs inline (no watchdog
    thread, no overhead).  ``fn`` may optionally accept a ``cancel``
    threading.Event keyword to observe abandonment."""
    if not deadline_s or deadline_s <= 0:
        return fn()
    box: list = []
    cancel = threading.Event()
    done = threading.Event()
    with _IDLE_LOCK:
        worker = _IDLE.pop() if _IDLE else None
    if worker is None:
        worker = _Worker()
    worker.inbox.put((fn, box, done, cancel))
    if not done.wait(deadline_s):
        cancel.set()
        raise DeviceTimeout(
            f"{label}: device call exceeded {deadline_s:.3g}s deadline")
    with _IDLE_LOCK:
        if len(_IDLE) < _MAX_IDLE:
            _IDLE.append(worker)
            worker = None
    if worker is not None:
        worker.inbox.put(None)  # pool full: let the thread exit
    kind, payload = box[0]
    if kind == "err":
        raise payload
    return payload


# -- deterministic fault injection -------------------------------------------

# Control-plane fault modes (injected OUTSIDE the device guard — in the
# apiserver watch stream, the HTTP client, and statement commit).  The
# device-path FaultInjector skips these; components query them with
# control_fault() below.  Specs compose comma-separated:
#   KAI_FAULT_INJECT="flaky:0.2,watchdrop:3"
#
# Wire modes (PR 15, docs/DEGRADATION.md "wire faults"): the lying-wire
# family, injected at the transport seams —
#   wire-truncate:<n>   apiserver watch stream: after <n> frames, write
#                       HALF of the next frame's bytes and close — the
#                       client must reconnect from its cursor, losing
#                       nothing.
#   wire-corrupt:<n>    apiserver watch stream: corrupt every <n>th
#                       frame's payload bytes (framing stays valid) —
#                       an unparseable line must drop the stream, never
#                       poison the store mirror.
#   wire-stall:<ms>     apiserver watch stream: sleep <ms> before every
#                       batch write — a stalled watcher may overrun the
#                       ring and must get an explicit GONE.
#   wire-reset:<n>      apiserver request path: every <n>th mutating
#                       request is APPLIED, then the connection is
#                       closed before any response bytes — the
#                       mid-bulk-POST reset (ambiguous outcome).
#   wire-storm:<n>      apiserver request path: answer the first <n>
#                       requests 429/503 (alternating, Retry-After set,
#                       store untouched) — the throttle storm.
#   wire-gone:<n>       apiserver watch connects: the first <n> streams
#                       answer 410 GONE regardless of cursor — the
#                       compaction storm (client re-list backoff test).
#   wire-drop:<n>       HTTP client shim: every <n>th mutating request
#                       is sent, then the response is discarded and the
#                       connection dropped (URLError) — "did my wave
#                       land?" without killing the server.
CONTROL_FAULT_MODES = ("watchdrop", "partition", "crash-after-journal",
                       "wire-truncate", "wire-corrupt", "wire-stall",
                       "wire-reset", "wire-storm", "wire-gone",
                       "wire-drop")


def control_fault(mode: str, env=None) -> str | None:
    """Return the argument of the control-plane ``KAI_FAULT_INJECT`` spec
    for ``mode`` (empty string when the mode has no argument), or None
    when the mode is not armed.  ``watchdrop[:<n>]`` drops the apiserver
    watch stream after <n> lines, ``partition:<ms>`` fails client
    requests for a window, ``crash-after-journal`` raises SimulatedCrash
    between the journal append and the API commit."""
    env = os.environ if env is None else env
    for part in (env.get("KAI_FAULT_INJECT") or "").split(","):
        m, _, arg = part.strip().partition(":")
        if m.lower() == mode:
            return arg
    return None


class FaultInjector:
    """Parse and apply a ``KAI_FAULT_INJECT`` spec.

    Modes: ``hang`` (block until the watchdog abandons the worker),
    ``slow:<ms>`` (delay every call), ``error`` (raise a transient
    RuntimeError), ``flaky:<p>`` (error with probability p from a seeded
    stream — deterministic across runs), ``badshape`` (return a result
    whose leading array axes are truncated, the XLA wrong-shape failure
    mode).  Injection applies ONLY to the device attempt; the CPU
    fallback path always runs clean, which is exactly the degraded-mode
    contract under test.

    Comma-separated specs compose with the control-plane modes
    (CONTROL_FAULT_MODES): the injector uses the first device-path spec
    and ignores control-plane ones, so one env var drives both planes."""

    def __init__(self, spec: str | None, seed: int = 0):
        parts = [p.strip() for p in (spec or "").split(",") if p.strip()]
        device_parts = [
            p for p in parts
            if p.partition(":")[0].lower() not in CONTROL_FAULT_MODES]
        self.spec = device_parts[0] if device_parts else ""
        self.mode, _, arg = self.spec.partition(":")
        self.mode = self.mode.lower()
        if self.mode not in ("", "hang", "slow", "error", "flaky",
                             "badshape"):
            raise ValueError(f"unknown fault-inject mode {self.mode!r} "
                             "(hang|slow:<ms>|error|flaky:<p>|badshape)")
        self.slow_ms = self.flaky_p = 0.0
        if self.mode in ("slow", "flaky"):
            try:
                val = float(arg)
            except ValueError:
                raise ValueError(
                    f"fault-inject mode {self.mode!r} needs a numeric "
                    f"argument — {self.mode}:<"
                    f"{'ms' if self.mode == 'slow' else 'p'}>, got "
                    f"{self.spec!r}") from None
            if self.mode == "slow":
                self.slow_ms = val
            else:
                self.flaky_p = val
        self._rng = random.Random(seed)

    @property
    def active(self) -> bool:
        return bool(self.mode)

    def before(self, label: str, cancel: threading.Event) -> None:
        """Pre-call fault: runs on the worker thread, before the kernel."""
        if self.mode == "hang":
            cancel.wait(3600.0)  # released the moment the guard abandons
            raise _Cancelled()
        if self.mode == "slow":
            time.sleep(self.slow_ms / 1000.0)
        elif self.mode == "error":
            raise RuntimeError(f"injected device error ({label})")
        elif self.mode == "flaky" and self._rng.random() < self.flaky_p:
            raise RuntimeError(f"injected flaky device error ({label})")

    def transform(self, result):
        """Post-call fault: corrupt the result (badshape mode).  A bare
        array result is truncated directly; container results (NamedTuple
        and friends) get the attribute-truncating proxy; scalars pass
        through — there is no shape to corrupt, and proxying them would
        crash formatting in callers instead of simulating a device
        fault."""
        if self.mode == "badshape":
            if hasattr(result, "shape") and getattr(result, "ndim", 0) >= 1:
                return result[:1]
            if getattr(result, "ndim", None) == 0 or \
                    isinstance(result, (bool, int, float, complex, str,
                                        bytes, type(None))):
                return result  # scalars: no shape to corrupt
            return _BadShapeProxy(result)
        return result


class _BadShapeProxy:
    """Wraps a kernel result so every array attribute comes back with its
    leading axis truncated — what a miscompiled/garbled device answer
    looks like to the host.  Callers' shape validators must catch it."""

    def __init__(self, wrapped):
        object.__setattr__(self, "_wrapped", wrapped)

    def __getattr__(self, name):
        value = getattr(object.__getattribute__(self, "_wrapped"), name)
        if hasattr(value, "shape") and getattr(value, "ndim", 0) >= 1:
            return value[:1]
        return value


# -- circuit breaker ----------------------------------------------------------

class CircuitBreaker:
    """CLOSED -> (threshold consecutive failures) -> OPEN -> (cooloff)
    -> HALF_OPEN probe -> CLOSED on success / OPEN on failure."""

    def __init__(self, threshold: int = 3, cooloff_s: float = 30.0,
                 clock=time.monotonic):
        self.threshold = max(1, int(threshold))
        self.cooloff_s = cooloff_s
        self.clock = clock
        self._lock = threading.Lock()
        self.state = CLOSED
        self.consecutive_failures = 0
        self.trips = 0
        self.opened_at = 0.0
        self.last_error = ""
        self._publish_state()

    def _publish_state(self) -> None:
        METRICS.set_gauge("device_guard_state", _STATE_CODE[self.state])

    def allow_device(self) -> bool:
        """May the next call attempt the device path?  Transitions
        OPEN -> HALF_OPEN once the cooloff elapsed; while HALF_OPEN only
        the probing call (the one that saw the transition, or raced into
        HALF_OPEN) attempts the device — concurrent calls during an open
        window go straight to the fallback."""
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN and \
                    self.clock() - self.opened_at >= self.cooloff_s:
                self.state = HALF_OPEN
                self._publish_state()
                METRICS.inc("device_guard_probes")
                return True
            return False

    def record_success(self) -> bool:
        """Returns True when this success CLOSED a tripped breaker."""
        with self._lock:
            recovered = self.state != CLOSED
            self.state = CLOSED
            self.consecutive_failures = 0
            self.last_error = ""
            self._publish_state()
            return recovered

    def record_failure(self, error: str) -> bool:
        """Returns True when this failure TRIPPED the breaker open."""
        with self._lock:
            self.consecutive_failures += 1
            self.last_error = error[:300]
            was_open = self.state == OPEN
            if (self.state == HALF_OPEN
                    or self.consecutive_failures >= self.threshold):
                self.state = OPEN
                self.opened_at = self.clock()
                self._publish_state()
                if not was_open:
                    self.trips += 1
                    METRICS.inc("device_guard_trips")
                    return True
            return False

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self.state,
                    "consecutive_failures": self.consecutive_failures,
                    "trips": self.trips,
                    "threshold": self.threshold,
                    "cooloff_s": self.cooloff_s,
                    "last_error": self.last_error}


# -- the guard ----------------------------------------------------------------

def _materialize(result):
    """Force device completion INSIDE the watchdog window: a jitted call
    returns lazily, so without this the hang would surface later at the
    (unguarded) host fetch.  Walks common result containers."""
    import jax
    if result is None:
        return result
    if hasattr(result, "block_until_ready"):
        return result.block_until_ready()
    fields = getattr(result, "_fields", None)
    values = ([getattr(result, f) for f in fields] if fields
              else result if isinstance(result, (tuple, list))
              else [result])
    for v in values:
        # jax.block_until_ready passes non-array leaves through
        # untouched, so anything it raises IS a device failure — it must
        # propagate to the guard, not be swallowed into a "success" that
        # detonates later at the unguarded host fetch.
        jax.block_until_ready(v)
    return result


class DeviceGuard:
    def __init__(self, deadline_s: float | None = None,
                 retries: int | None = None,
                 backoff_base_s: float = 0.05,
                 breaker_threshold: int | None = None,
                 breaker_cooloff_s: float | None = None,
                 fault: str | None = None,
                 fault_seed: int | None = None,
                 fallback_enabled: bool = True,
                 clock=time.monotonic,
                 name: str = "device"):
        env = os.environ
        if deadline_s is None:
            deadline_s = _env_float(env, "KAI_DEVICE_DEADLINE_S", 30.0)
        if retries is None:
            retries = int(_env_float(env, "KAI_DEVICE_RETRIES", 2))
        if breaker_threshold is None:
            breaker_threshold = int(
                _env_float(env, "KAI_BREAKER_THRESHOLD", 3))
        if breaker_cooloff_s is None:
            breaker_cooloff_s = _env_float(env, "KAI_BREAKER_COOLOFF_S",
                                           30.0)
        if fault is None:
            fault = env.get("KAI_FAULT_INJECT", "")
        if fault_seed is None:
            fault_seed = int(_env_float(env, "KAI_FAULT_SEED", 0))
        self.name = name
        self.deadline_s = deadline_s
        self.retries = max(0, int(retries))
        self.backoff_base_s = backoff_base_s
        self.fallback_enabled = fallback_enabled
        self.clock = clock
        self.injector = FaultInjector(fault, seed=fault_seed)
        self.breaker = CircuitBreaker(breaker_threshold, breaker_cooloff_s,
                                      clock=clock)
        self._jitter = random.Random(fault_seed + 1)
        self.timeouts = 0
        self.retried = 0
        self.bad_results = 0
        self.fallback_calls = 0
        # Event dedup: while the breaker stays open, only the FIRST
        # skipped call emits a degraded event (a contended cycle makes
        # hundreds of guarded calls; one event per state change is signal,
        # one per call is spam).
        self._announced_open = False

    # -- fault control (tests / the daemon's --fault-inject flag) ---------
    def set_fault(self, spec: str | None, seed: int = 0) -> None:
        self.injector = FaultInjector(spec, seed=seed)

    def clear_fault(self) -> None:
        self.injector = FaultInjector(None)

    # -- the guarded dispatch ---------------------------------------------
    def call(self, thunk, label: str = "kernel", validate=None,
             record_event=None, deadline_s: float | None = None,
             cycle_deadline_at: float | None = None,
             materialize: bool = True):
        """Run ``thunk`` (a zero-arg device dispatch) under the full
        guard: watchdog deadline, bounded retry, breaker, CPU fallback.

        ``validate``: optional result predicate; a False verdict is a
        device failure (the badshape class of fault).  ``record_event``:
        optional (kind, message) sink — breaker trips and degraded calls
        surface as scheduler events.  ``cycle_deadline_at``: absolute
        clock() value; past it the dispatch aborts immediately with
        CycleDeadlineExceeded (the scheduler's whole-cycle budget).
        ``materialize=False`` is the pipelined-dispatch mode: the call
        returns as soon as the kernel is ENQUEUED (no block_until_ready),
        letting the host overlap work with device execution; validators
        must then judge metadata only (shapes are known pre-completion),
        and an asynchronous device failure surfaces at the caller's later
        guarded fetch, not here.  The CPU fallback path always
        materializes — there is nothing to overlap with."""
        deadline = self.deadline_s if deadline_s is None else deadline_s
        if cycle_deadline_at is not None:
            # The in-flight watchdog must respect the cycle budget too:
            # without this clamp a hang starting just before the cycle
            # deadline could overrun it by the full device deadline.  An
            # exhausted budget must RAISE, never clamp to <= 0 — which
            # run_with_deadline would read as "no deadline, run inline".
            cycle_left = cycle_deadline_at - self.clock()
            if cycle_left <= 0:
                raise CycleDeadlineExceeded(
                    f"{label}: cycle deadline reached before dispatch")
            deadline = (min(deadline, cycle_left)
                        if deadline and deadline > 0 else cycle_left)
        if self.breaker.allow_device():
            error = None
            for attempt in range(self.retries + 1):
                try:
                    result = self._device_attempt(thunk, label, deadline,
                                                  materialize=materialize)
                    if validate is not None and not validate(result):
                        self.bad_results += 1
                        METRICS.inc("device_guard_bad_results")
                        raise DeviceBadResult(
                            f"{label}: result failed shape/validity check")
                    if self.breaker.record_success():
                        self._announced_open = False
                        LOG.info("device-guard %s: breaker closed after "
                                 "successful probe (%s)", self.name, label)
                        self._event(record_event, "DeviceGuardRecovered",
                                    f"{label}: device path recovered; "
                                    "breaker closed")
                    return result
                except DeviceTimeout as exc:
                    # A hang is persistent at the timescale of one call:
                    # retrying would burn deadline * retries of cycle
                    # budget for the same stall.  Straight to failure.
                    self.timeouts += 1
                    METRICS.inc("device_guard_timeouts")
                    error = exc
                    break
                except DeviceBadResult as exc:
                    # Deterministic corruption — retry is wasted work.
                    error = exc
                    break
                except Exception as exc:  # transient device error class
                    error = exc
                    if attempt < self.retries:
                        self.retried += 1
                        METRICS.inc("device_guard_retries")
                        time.sleep(self.backoff_base_s * (2 ** attempt)
                                   * (1.0 + self._jitter.random()))
            if self.breaker.record_failure(repr(error)):
                LOG.warning("device-guard %s: breaker OPEN after %d "
                            "consecutive failures (last: %r)", self.name,
                            self.breaker.consecutive_failures, error)
                self._event(record_event, "DeviceGuardTripped",
                            f"{label}: breaker open after "
                            f"{self.breaker.consecutive_failures} "
                            f"consecutive device failures: {error!r:.200}")
            announce = True
        else:
            error = DeviceGuardError(
                f"{label}: breaker {self.breaker.state}; device path "
                "skipped")
            announce = not self._announced_open
            self._announced_open = True
        return self._fallback(thunk, label, error, validate,
                              record_event if announce else None,
                              cycle_deadline_at=cycle_deadline_at)

    def _device_attempt(self, thunk, label: str, deadline: float | None,
                        materialize: bool = True):
        injector = self.injector

        def attempt(cancel=None):
            if injector.active:
                injector.before(label, cancel or threading.Event())
            result = thunk()
            if materialize:
                result = _materialize(result)
            return injector.transform(result)

        return run_with_deadline(attempt, deadline, label=label)

    def _fallback(self, thunk, label, error, validate, record_event,
                  cycle_deadline_at: float | None = None):
        if not self.fallback_enabled:
            raise error if isinstance(error, DeviceGuardError) else \
                DeviceGuardError(f"{label}: device path failed "
                                 f"({error!r}) and fallback is disabled")
        if cycle_deadline_at is not None and \
                self.clock() >= cycle_deadline_at:
            # The device attempt consumed the rest of the cycle budget:
            # the degraded path must not overrun it either — the cycle
            # driver rolls back and moves on.
            raise CycleDeadlineExceeded(
                f"{label}: cycle deadline reached before CPU fallback "
                f"(device path: {error!r})")
        self.fallback_calls += 1
        METRICS.inc("device_guard_fallback_calls")
        self._event(record_event, "DeviceGuardDegraded",
                    f"{label}: degraded to CPU fallback ({error!r:.200})")
        import jax
        try:
            cpu = jax.devices("cpu")[0]

            def on_host(cancel=None):
                # Clean re-execution on the host backend: no injection,
                # arrays not already committed to a device compile for
                # CPU.  (Committed device arrays keep their placement —
                # acceptable: the deterministic-injection environments
                # this protects are host-backed already, and a genuinely
                # dead device surfaces here as a loud error, not a hang.)
                with jax.default_device(cpu):
                    return _materialize(thunk())

            # The fallback gets a generous-but-bounded watchdog too: the
            # degraded path must also never wedge the cycle.  Floor of
            # 60s: the first fallback call legitimately pays an XLA
            # compile for the host backend, which a short device deadline
            # must not bound.  The cycle budget caps it regardless.
            fb_deadline = (max(60.0, self.deadline_s * 4)
                           if self.deadline_s else None)
            if cycle_deadline_at is not None:
                cycle_left = cycle_deadline_at - self.clock()
                if cycle_left <= 0:
                    # Budget ran out between the entry check and here
                    # (metrics/event/import overhead): raising keeps the
                    # contract — a clamp to <= 0 would run the fallback
                    # INLINE with no watchdog at all.
                    raise CycleDeadlineExceeded(
                        f"{label}: cycle deadline reached before CPU "
                        f"fallback (device path: {error!r})")
                fb_deadline = (min(fb_deadline, cycle_left)
                               if fb_deadline else cycle_left)
            result = run_with_deadline(on_host, fb_deadline,
                                       label=f"{label}@cpu-fallback")
            if validate is not None and not validate(result):
                raise DeviceBadResult(
                    f"{label}: CPU fallback result failed validation")
            return result
        except DeviceGuardError:
            raise
        except Exception as exc:
            raise DeviceGuardError(
                f"{label}: device path failed ({error!r}) and CPU "
                f"fallback also failed ({exc!r})") from exc

    @staticmethod
    def _event(record_event, kind: str, message: str) -> None:
        if record_event is None:
            return
        try:
            record_event(kind, message)
        except Exception:  # event sinks must never break scheduling
            LOG.debug("device-guard event sink failed", exc_info=True)

    def status(self) -> dict:
        """Structured state for /healthz and bench result details."""
        out = self.breaker.snapshot()
        out.update({"deadline_s": self.deadline_s,
                    "retries": self.retries,
                    "timeouts": self.timeouts,
                    "retried": self.retried,
                    "bad_results": self.bad_results,
                    "fallback_calls": self.fallback_calls,
                    "fault_inject": self.injector.spec or None})
        return out

    @property
    def degraded(self) -> bool:
        return self.breaker.state != CLOSED


def _env_float(env, name: str, default: float) -> float:
    try:
        return float(env.get(name, default))
    except (TypeError, ValueError):
        return default


# -- module singleton ---------------------------------------------------------

_GUARD: DeviceGuard | None = None
_GUARD_LOCK = threading.Lock()


def device_guard() -> DeviceGuard:
    """The process-wide guard every kernel dispatch routes through.
    Configured from the KAI_* environment on first use."""
    global _GUARD
    if _GUARD is None:
        with _GUARD_LOCK:
            if _GUARD is None:
                _GUARD = DeviceGuard()
    return _GUARD


def configure_device_guard(**kwargs) -> DeviceGuard:
    """Install a freshly-configured singleton (daemon flags, tests)."""
    global _GUARD
    with _GUARD_LOCK:
        _GUARD = DeviceGuard(**kwargs)
    return _GUARD


def reset_device_guard() -> None:
    """Drop the singleton so the next use re-reads the environment."""
    global _GUARD
    with _GUARD_LOCK:
        _GUARD = None
