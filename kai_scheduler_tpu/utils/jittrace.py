"""KAI_JITTRACE runtime compile-budget auditor.

kaijit (``tools/kaijit/``) proves the STATIC side of the compilation
contract: every jit boundary's shape inputs are bucketed, every
``static_argnames`` value domain is bounded.  This shim records the
DYNAMIC side: with ``KAI_JITTRACE=1``, every jitted kernel in ``ops/``
and ``parallel/`` is wrapped with a proxy that journals the **abstract
signature** of each call — dtype and shape per array operand, the
VALUE (capped repr) per static arg, a weak-type tag per python scalar.
The set of distinct signatures per kernel is exactly XLA's compilation
key set: each new signature is a retrace, and on TPU a retrace is
seconds of silicon time in the middle of a scheduling cycle.

``docs/scale-tests/compile_budget.json`` pins the per-kernel ceiling a
fleet run may reach (``tools/fleet_budget.py`` enforces it);
``chaos_matrix --compile`` arms the sweep and joins the journals
against the static surface via :func:`validate_observed` — a runtime
compile from a kernel the static model never discovered is an analyzer
gap and fails loud, exactly like locktrace's contradiction check.

Env contract (mirrors utils/locktrace.py):

- ``KAI_JITTRACE=1``     wrap the kernel surface (the package
                         ``__init__`` honors this at import)
- ``KAI_JITTRACE_OUT``   dump the journal as JSON at process exit

Metrics (``jittrace_signatures_recorded_total``,
``jittrace_calls_total``) publish via :func:`sync_metrics`, called from
the render path and never from inside a kernel call.
"""

from __future__ import annotations

import _thread
import atexit
import functools
import importlib
import json
import os

_PKG = "kai_scheduler_tpu"

# repr() of a static-arg value is the compile key; cap it so a
# pathological object cannot bloat the journal.
_REPR_CAP = 80


def _abstract(value, static: bool) -> str:
    """One operand's contribution to the compilation key."""
    if static:
        r = repr(value)
        return "s:" + (r if len(r) <= _REPR_CAP else r[:_REPR_CAP] + "…")
    shape = getattr(value, "shape", None)
    dtype = getattr(value, "dtype", None)
    if shape is not None and dtype is not None:
        dims = ",".join(str(d) for d in shape)
        return f"{dtype}[{dims}]"
    if value is None:
        return "None"
    if isinstance(value, (bool, int, float, complex, str, bytes)):
        # Non-static python scalars trace as weak-typed constants: the
        # VALUE is not part of the compilation key, the type is.
        return f"py:{type(value).__name__}"
    if isinstance(value, (tuple, list)):
        inner = ",".join(_abstract(v, False) for v in value[:8])
        more = "…" if len(value) > 8 else ""
        return f"({inner}{more})"
    return f"obj:{type(value).__name__}"


def signature_of(args: tuple, kwargs: dict, params: tuple,
                 static_argnames: frozenset) -> str:
    """The abstract call signature — the journal's unit of account."""
    parts = []
    for i, a in enumerate(args):
        name = params[i] if i < len(params) else f"arg{i}"
        parts.append(f"{name}={_abstract(a, name in static_argnames)}")
    for name in sorted(kwargs):
        parts.append(
            f"{name}={_abstract(kwargs[name], name in static_argnames)}")
    return ", ".join(parts)


class JitTracer:
    def __init__(self):
        # Raw lock: journal mutation must not touch traced locks.
        self._guard = _thread.allocate_lock()
        self.signatures: dict[str, set] = {}   # kernel -> {signature}
        self.calls: dict[str, int] = {}        # kernel -> call count
        self.wrapped: list[str] = []           # kernels under trace
        self._published = {"signatures": 0, "calls": 0}
        self.installed = False

    def note_call(self, kernel: str, sig: str) -> None:
        with self._guard:
            self.signatures.setdefault(kernel, set()).add(sig)
            self.calls[kernel] = self.calls.get(kernel, 0) + 1

    def dump(self) -> dict:
        with self._guard:
            return {
                "version": 1,
                "kernels": {k: sorted(v)
                            for k, v in sorted(self.signatures.items())},
                "calls": dict(sorted(self.calls.items())),
                "wrapped": sorted(self.wrapped),
            }

    def reset(self) -> None:
        with self._guard:
            self.signatures.clear()
            self.calls.clear()
            self._published = {"signatures": 0, "calls": 0}

    def stats(self) -> dict:
        """Raw journal sizes for /healthz (mirrors LockTracer.stats)."""
        with self._guard:
            return {
                "kernels_wrapped": len(self.wrapped),
                "kernels_called": len(self.calls),
                "signatures_recorded": sum(
                    len(v) for v in self.signatures.values()),
                "calls": sum(self.calls.values()),
            }


TRACER = JitTracer()


def sync_metrics() -> None:
    """Publish journal sizes as counters (delta since last sync)."""
    from .metrics import METRICS
    with TRACER._guard:
        sigs = sum(len(v) for v in TRACER.signatures.values())
        calls = sum(TRACER.calls.values())
        d_sigs = sigs - TRACER._published["signatures"]
        d_calls = calls - TRACER._published["calls"]
        TRACER._published = {"signatures": sigs, "calls": calls}
    if d_sigs > 0:
        METRICS.inc("jittrace_signatures_recorded_total", d_sigs)
    if d_calls > 0:
        METRICS.inc("jittrace_calls_total", d_calls)


# -- static surface (shared with kaijit) ------------------------------------

def discover_surface(root: str | None = None) -> dict:
    """The whole-package kernel surface as a ``kaijit --surface``
    payload — the SAME discovery both analyzers run
    (tools/kailint/jitsurface.py), so the runtime journal and the
    static model cannot drift."""
    import ast

    from ..tools.kailint.engine import iter_python_files, package_relative
    from ..tools.kailint.jitsurface import (collect_module_surface,
                                            surface_payload)
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    surfaces, errors = {}, []
    for fpath in iter_python_files([root]):
        rel = package_relative(fpath)
        try:
            with open(fpath, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=fpath)
        except (OSError, SyntaxError, UnicodeDecodeError) as exc:
            errors.append(f"{fpath}: {exc}")
            continue
        module = rel[:-3].replace("/", ".")
        surface = collect_module_surface(tree, src.splitlines(),
                                         module, rel)
        if surface is not None:
            surfaces[module] = surface
    return surface_payload(surfaces, errors)


def _wrap(fn, kernel: str, params: tuple, static: frozenset):
    @functools.wraps(fn)
    def traced(*args, **kwargs):
        TRACER.note_call(kernel,
                         signature_of(args, kwargs, params, static))
        return fn(*args, **kwargs)

    traced.__kai_jittrace__ = kernel
    traced.__wrapped__ = fn
    return traced


def install(surface: dict | None = None) -> int:
    """Wrap every directly-compiled kernel the static surface names.

    Imports each ops/parallel module and replaces the module attribute
    with a journaling proxy; later ``from ..ops.x import k`` imports and
    module-global lookups inside host wrappers both resolve through the
    module attribute, so they call the proxy.  References captured into
    containers at module-import time (before install) stay untraced —
    the compile-budget manifest's ``require_observed`` floor is
    calibrated against what the proxies actually see.

    Returns the number of kernels wrapped.  Idempotent."""
    if TRACER.installed:
        return len(TRACER.wrapped)
    surface = surface or discover_surface()
    wrapped = []
    for qualname, decl in sorted(surface.get("kernels", {}).items()):
        if not decl.get("jitted"):
            continue
        module_name, _, fn_name = qualname.rpartition(".")
        try:
            mod = importlib.import_module(module_name)
        except Exception:
            continue  # an unimportable module can't compile anything
        fn = getattr(mod, fn_name, None)
        if fn is None or getattr(fn, "__kai_jittrace__", None):
            continue
        proxy = _wrap(fn, qualname, tuple(decl.get("params", ())),
                      frozenset(decl.get("static_argnames", ())))
        setattr(mod, fn_name, proxy)
        wrapped.append(qualname)
    TRACER.wrapped = wrapped
    TRACER.installed = True
    return len(wrapped)


def uninstall() -> None:
    """Restore the original module attributes (unit tests only)."""
    for qualname in TRACER.wrapped:
        module_name, _, fn_name = qualname.rpartition(".")
        mod = importlib.import_module(module_name)
        fn = getattr(mod, fn_name, None)
        if fn is not None and getattr(fn, "__kai_jittrace__", None):
            setattr(mod, fn_name, fn.__wrapped__)
    TRACER.wrapped = []
    TRACER.installed = False


def _dump_to(path: str) -> None:
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(TRACER.dump(), fh, indent=2, sort_keys=True)
            fh.write("\n")
    except OSError:
        pass  # a failed dump must not fail the traced process


def install_from_env() -> bool:
    """Honor ``KAI_JITTRACE=1`` (the package ``__init__`` hook)."""
    if os.environ.get("KAI_JITTRACE", "") in ("", "0", "false"):
        return False
    install()
    out = os.environ.get("KAI_JITTRACE_OUT")
    if out:
        atexit.register(_dump_to, out)
    return True


# -- offline merge -----------------------------------------------------------

def load_budget(path: str) -> dict:
    """``docs/scale-tests/compile_budget.json``: ``{"default_max": N,
    "kernels": {qualname: ceiling}, "require_observed": [qualname]}``.
    Shape-corrupt files raise ValueError (a gate that cannot read its
    contract must fail, not pass vacuously)."""
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if not isinstance(data, dict) or \
            not isinstance(data.get("kernels"), dict) or \
            not isinstance(data.get("default_max"), int):
        raise ValueError(f"{path}: not a compile-budget manifest "
                         f"(expected default_max + kernels mapping)")
    return data


def validate_observed(surface: dict, dumps: list,
                      budget: dict | None = None) -> dict:
    """Join merged ``KAI_JITTRACE_OUT`` journals against the static
    surface (and optionally the compile-budget manifest).

    - a journaled kernel ABSENT from the static surface is
      **unexplained** — the analyzer's discovery has a gap, fail loud;
    - per-kernel distinct-signature counts take the MAX across journals
      (signature strings are process-local; a union across seeds would
      double-count reprs that differ only by object identity);
    - with a budget: counts above the kernel's ceiling are **breaches**,
      and ``require_observed`` kernels missing from every journal mean
      the sweep never exercised them (**uncovered** — a budget nobody
      spends proves nothing)."""
    static = {q for q, d in surface.get("kernels", {}).items()
              if d.get("jitted")}
    counts: dict[str, int] = {}
    calls: dict[str, int] = {}
    for dump in dumps:
        for kernel, sigs in dump.get("kernels", {}).items():
            counts[kernel] = max(counts.get(kernel, 0), len(sigs))
        for kernel, n in dump.get("calls", {}).items():
            calls[kernel] = calls.get(kernel, 0) + n
    unexplained = sorted(k for k in counts if k not in static)
    breaches, uncovered = [], []
    if budget is not None:
        default_max = budget.get("default_max", 0)
        ceilings = budget.get("kernels", {})
        for kernel in sorted(counts):
            ceiling = ceilings.get(kernel, default_max)
            if counts[kernel] > ceiling:
                breaches.append({"kernel": kernel,
                                 "signatures": counts[kernel],
                                 "ceiling": ceiling})
        uncovered = sorted(k for k in budget.get("require_observed", ())
                           if k not in counts)
    return {
        "kernels": dict(sorted(counts.items())),
        "calls": dict(sorted(calls.items())),
        "unexplained": unexplained,
        "breaches": breaches,
        "uncovered": uncovered,
        "ok": (bool(counts) and not unexplained and not breaches
               and not uncovered),
    }
