"""Distributed leader election over coordination Lease objects.

Mirrors the reference scheduler's leader election
(``cmd/scheduler/app/server.go:196-240``: resourcelock.LeasesResourceLock
with LeaseDuration 15s / RenewDeadline 10s / RetryPeriod 2s): candidates
race to create-or-take a ``Lease`` object through the API (in-memory or
HTTP — any object store with create/get/update + Conflict on stale
resourceVersion), the holder renews on a timer, and a candidate takes over
once ``renewTime + leaseDurationSeconds`` has elapsed.  Because the lease
lives in the shared API store, election works across processes and hosts —
unlike the flock elector in ``server.py``, which only serializes schedulers
on one machine.
"""

from __future__ import annotations

import copy
import threading
import time

from ..controllers.kubeapi import Conflict, NotFound

LEASE_KIND = "Lease"
DEFAULT_NAMESPACE = "kai-system"


class TransientRenewError(Exception):
    """Renewal failed for a reason that may heal (apiserver unreachable);
    the holder keeps retrying until its lease would have expired anyway."""


class LeaseElector:
    def __init__(self, api, name: str, identity: str,
                 namespace: str = DEFAULT_NAMESPACE,
                 lease_duration: float = 15.0,
                 retry_period: float = 2.0,
                 clock=time.time):
        self.api = api
        self.name = name
        self.identity = identity
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self.clock = clock
        self._renew_thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.is_leader = False

    # -- one acquisition attempt ------------------------------------------
    def try_acquire(self) -> bool:
        now = self.clock()
        spec = {"holderIdentity": self.identity,
                "leaseDurationSeconds": self.lease_duration,
                "acquireTime": now, "renewTime": now}
        try:
            lease = self.api.get(LEASE_KIND, self.name, self.namespace)
        except NotFound:
            try:
                self.api.create({"kind": LEASE_KIND,
                                 "metadata": {"name": self.name,
                                              "namespace": self.namespace},
                                 "spec": spec})
                return True
            except Conflict:
                return False
        # Work on a copy: mutating the store's own dict would bypass the
        # resourceVersion conflict check that makes the CAS race safe
        # (in-memory get() returns the live stored object).
        lease = copy.deepcopy(lease)
        holder = lease["spec"].get("holderIdentity")
        renew = float(lease["spec"].get("renewTime", 0))
        duration = float(lease["spec"].get("leaseDurationSeconds",
                                           self.lease_duration))
        if holder == self.identity:
            pass  # re-acquire our own lease (restart with same identity)
        elif holder and now < renew + duration:
            return False  # current holder is live
        lease["spec"].update(spec)
        try:
            self.api.update(lease)
            return True
        except (Conflict, NotFound):
            return False

    def renew(self) -> bool:
        """Refresh renewTime; False if the lease was stolen (we must stop
        leading immediately, like losing the apiserver lease).  Raises
        TransientRenewError on transport failures — the renewal loop keeps
        retrying those until the lease itself would have expired."""
        try:
            try:
                lease = self.api.get(LEASE_KIND, self.name, self.namespace)
            except NotFound:
                return self.try_acquire()
            lease = copy.deepcopy(lease)
            if lease["spec"].get("holderIdentity") != self.identity:
                return False
            lease["spec"]["renewTime"] = self.clock()
            try:
                self.api.update(lease)
                return True
            except Conflict:
                return False
            except NotFound:
                return self.try_acquire()
        except Exception as exc:  # transport error: apiserver unreachable
            raise TransientRenewError(str(exc)) from exc

    # -- blocking/looping API ---------------------------------------------
    def acquire(self, timeout: float | None = None) -> bool:
        """Block until leadership is won (or timeout); then start the
        background renewal loop.  Re-entrant after release(): a candidate
        that stood down may re-enter the election."""
        self._stop.clear()
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._stop.is_set():
            if self.try_acquire():
                self.is_leader = True
                self._start_renewal()
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(self.retry_period)
        return False

    def _start_renewal(self) -> None:
        self._stop.clear()

        def loop():
            last_success = time.monotonic()
            while not self._stop.wait(self.retry_period):
                try:
                    ok = self.renew()
                except TransientRenewError:
                    # Keep retrying while our lease is still live; once it
                    # would have expired another candidate may hold it, so
                    # stand down (renewDeadline semantics, server.go:60-63).
                    if time.monotonic() - last_success < self.lease_duration:
                        continue
                    ok = False
                if not ok:
                    self.is_leader = False
                    return
                last_success = time.monotonic()

        self._renew_thread = threading.Thread(target=loop, daemon=True)
        self._renew_thread.start()

    def release(self) -> None:
        """Stop renewing and hand the lease off immediately."""
        self._stop.set()
        if self._renew_thread is not None:
            self._renew_thread.join(timeout=self.retry_period * 2)
        if self.is_leader:
            try:
                lease = self.api.get(LEASE_KIND, self.name, self.namespace)
                if lease["spec"].get("holderIdentity") == self.identity:
                    lease["spec"]["holderIdentity"] = ""
                    lease["spec"]["renewTime"] = 0
                    self.api.update(lease)
            except (NotFound, Conflict):
                pass
        self.is_leader = False
