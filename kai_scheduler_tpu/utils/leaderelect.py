"""Distributed leader election over coordination Lease objects.

Mirrors the reference scheduler's leader election
(``cmd/scheduler/app/server.go:196-240``: resourcelock.LeasesResourceLock
with LeaseDuration 15s / RenewDeadline 10s / RetryPeriod 2s): candidates
race to create-or-take a ``Lease`` object through the API (in-memory or
HTTP — any object store with create/get/update + Conflict on stale
resourceVersion), the holder renews on a timer, and a candidate takes over
once the holder has failed to renew for a full lease duration.  Because
the lease lives in the shared API store, election works across processes
and hosts — unlike the flock elector in ``server.py``, which only
serializes schedulers on one machine.

Two hardening properties beyond the basic race:

**Monotonic timekeeping.**  Wall clocks on different hosts disagree and
jump (NTP steps); deciding expiry by comparing *our* wall clock against
the holder's ``renewTime`` stamp turns every clock step into a spurious
takeover or a stuck election.  Instead, expiry is *observation-based*
(the client-go algorithm): a candidate records when the lease's
``(holderIdentity, renewTime)`` pair last *changed* on its own monotonic
clock, and takes over only after the pair has been frozen for a full
``lease_duration``.  The wall-clock stamps remain in the Lease purely as
human-readable debugging state.  ``clock=`` stays injectable for tests
(it then drives both stamps and deadlines); ``monotonic=`` can be
injected separately.

**Fencing epochs.**  Every successful acquisition increments a
monotonically increasing ``epoch`` stored in the Lease spec.  Mutating
writes from the leader carry that epoch, and the API store rejects any
write whose epoch is older than the Lease's current one
(``kubeapi.Fenced``) — so a deposed leader that is slow to notice (GC
pause, partition) can never corrupt state.  ``retry_period`` sleeps are
jittered so a fleet of candidates doesn't thundering-herd the Lease
object the instant it expires.
"""

from __future__ import annotations

import copy
import random
import threading
import time

from ..controllers.kubeapi import FENCE_NAMESPACE, Conflict, NotFound

LEASE_KIND = "Lease"
DEFAULT_NAMESPACE = FENCE_NAMESPACE


class TransientRenewError(Exception):
    """Renewal failed for a reason that may heal (apiserver unreachable);
    the holder keeps retrying until its lease would have expired anyway."""


class LeaseElector:
    def __init__(self, api, name: str, identity: str,
                 namespace: str = DEFAULT_NAMESPACE,
                 lease_duration: float = 15.0,
                 retry_period: float = 2.0,
                 clock=time.time, monotonic=None):
        self.api = api
        self.name = name
        self.identity = identity
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.retry_period = retry_period
        self.clock = clock
        # Internal deadlines run on a monotonic clock.  When a test
        # injects a fake wall clock, that clock drives deadlines too
        # (the fake stands for all of time); production gets
        # time.monotonic regardless of wall-clock steps.
        if monotonic is not None:
            self.mono = monotonic
        elif clock is time.time:
            self.mono = time.monotonic
        else:
            self.mono = clock
        # Deterministic per-identity jitter: candidates spread over
        # [1.0, 1.5) * retry_period instead of herding the Lease.
        self._jitter_rng = random.Random(hash(identity) & 0xFFFFFFFF)
        # Last observed (holder, renewTime) pair and WHEN (on self.mono)
        # it was first seen — the observation-based expiry state.
        self._observed: tuple | None = None
        self._observed_at = 0.0
        self._renew_thread: threading.Thread | None = None
        self._stop = threading.Event()
        # Election-state lock: is_leader/epoch/_observed are written by
        # BOTH the candidate (acquire/release, main thread) and the
        # renewal loop (its own thread).  The phases mostly alternate,
        # but release() only joins the loop with a TIMEOUT — a renew
        # wedged in a slow API call can complete after release cleared
        # the state, so the writes must serialize (kairace KRC001).  API
        # round trips stay OUTSIDE the lock (KAI006).
        self._state_lock = threading.Lock()
        # Incarnation generation, bumped by every release(): a renew
        # wedged in a slow API call can resume AFTER release cleared
        # the state — and after a subsequent acquire() re-cleared
        # _stop, so the stop flag alone cannot fence it out.  Late
        # results carry the generation they started under and are
        # dropped on mismatch (epoch adoption AND the old renewal
        # loop itself, which must not keep running beside the new
        # incarnation's).
        self._gen = 0
        self.is_leader = False
        # Fencing epoch of our CURRENT leadership incarnation; 0 while
        # not leading.  Writes carrying an older epoch than the Lease's
        # are rejected by the store (kubeapi.Fenced).
        self.epoch = 0

    def _jittered(self, period: float) -> float:
        return period * (1.0 + 0.5 * self._jitter_rng.random())

    # -- one acquisition attempt ------------------------------------------
    def _holder_expired(self, lease: dict) -> bool:
        """Observation-based expiry: the holder is dead only once its
        (holderIdentity, renewTime) pair has been frozen for a full
        lease_duration on OUR monotonic clock.  A fresh observation
        always starts the timer — never trust wall-clock math across
        hosts."""
        spec = lease.get("spec", {})
        pair = (spec.get("holderIdentity"), spec.get("renewTime"))
        now = self.mono()
        with self._state_lock:
            if self._observed != pair:
                self._observed = pair
                self._observed_at = now
                return False
        duration = float(spec.get("leaseDurationSeconds",
                                  self.lease_duration))
        return now - self._observed_at >= duration

    def try_acquire(self) -> bool:
        gen = self._gen
        now = self.clock()
        spec = {"holderIdentity": self.identity,
                "leaseDurationSeconds": self.lease_duration,
                "acquireTime": now, "renewTime": now}
        try:
            lease = self.api.get(LEASE_KIND, self.name, self.namespace)
        except NotFound:
            try:
                self.api.create({"kind": LEASE_KIND,
                                 "metadata": {"name": self.name,
                                              "namespace": self.namespace},
                                 "spec": dict(spec, epoch=1)})
                return self._adopt_epoch(1, gen)
            except Conflict:
                return False
        # Work on a copy: mutating the store's own dict would bypass the
        # resourceVersion conflict check that makes the CAS race safe
        # (in-memory get() returns the live stored object).
        lease = copy.deepcopy(lease)
        holder = lease["spec"].get("holderIdentity")
        if holder == self.identity:
            pass  # re-acquire our own lease (restart with same identity)
        elif holder and not self._holder_expired(lease):
            return False  # current holder is live (by our observation)
        # Every acquisition — takeover, released lease, or our own
        # restart — is a new leadership incarnation: bump the fencing
        # epoch so writes from the previous incarnation are rejected.
        epoch = int(lease["spec"].get("epoch", 0) or 0) + 1
        lease["spec"].update(spec)
        lease["spec"]["epoch"] = epoch
        try:
            self.api.update(lease)
            return self._adopt_epoch(epoch, gen)
        except (Conflict, NotFound):
            return False

    def _adopt_epoch(self, epoch: int, gen: int) -> bool:
        """Record a won incarnation — UNLESS release() already ran: a
        renew wedged in a slow API call can re-enter try_acquire after
        the candidate stood down, and a resurrected epoch would let the
        old incarnation's writes pass the fence.  (The store-side lease
        then sits unrenewed until it expires, which is the normal
        takeover path.)  The _stop check alone is not enough: a
        release() + re-acquire() pair CLEARS _stop again, so the late
        adoption also carries the generation its try_acquire started
        under and is dropped if any release ran in between.  Returns
        whether the epoch was adopted — a dropped adoption makes
        try_acquire report False (the lease CAS landed, but WE are not
        leading: nobody would renew it, and a True here would hand the
        caller a leadership whose fenced writes all bounce on epoch 0)."""
        with self._state_lock:
            if gen == self._gen and not self._stop.is_set():
                self.epoch = epoch
                return True
            return False

    def renew(self) -> bool:
        """Refresh renewTime; False if the lease was stolen (we must stop
        leading immediately, like losing the apiserver lease).  Raises
        TransientRenewError on transport failures — the renewal loop keeps
        retrying those until the lease itself would have expired."""
        try:
            try:
                lease = self.api.get(LEASE_KIND, self.name, self.namespace)
            except NotFound:
                return self.try_acquire()
            lease = copy.deepcopy(lease)
            if lease["spec"].get("holderIdentity") != self.identity:
                return False
            lease["spec"]["renewTime"] = self.clock()
            try:
                self.api.update(lease)
                return True
            except Conflict:
                return False
            except NotFound:
                return self.try_acquire()
        except Exception as exc:  # transport error: apiserver unreachable
            raise TransientRenewError(str(exc)) from exc

    # -- blocking/looping API ---------------------------------------------
    def acquire(self, timeout: float | None = None) -> bool:
        """Block until leadership is won (or timeout); then start the
        background renewal loop.  Re-entrant after release(): a candidate
        that stood down may re-enter the election."""
        self._stop.clear()
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._stop.is_set():
            gen = self._gen
            if self.try_acquire():
                with self._state_lock:
                    if gen != self._gen or self._stop.is_set():
                        # release() (the documented cross-thread stop
                        # path) landed between our winning CAS and here:
                        # the stand-down wins — reporting True would
                        # hand back a leadership release() already
                        # cleared (epoch 0, no renewal), and clearing
                        # _stop below would erase the stop request.
                        return False
                    self.is_leader = True
                # Same race, later window: release() can land between
                # the locked is_leader write above and here.  Renewal
                # only arms if the generation still matches — and a
                # True with no renewal loop would be a dead leadership
                # (is_leader already re-cleared, epoch 0), so the
                # arming result IS the acquire result.
                return self._start_renewal(gen)
            if deadline is not None and time.monotonic() >= deadline:
                return False
            time.sleep(self._jittered(self.retry_period))
        return False

    def _start_renewal(self, gen: int) -> bool:
        """Arm the renewal loop for the incarnation won under ``gen``.
        False when release() raced the acquisition (generation moved):
        the stand-down wins, _stop stays set, no loop starts."""
        with self._state_lock:
            if gen != self._gen:
                return False
            self._stop.clear()

        def loop():
            last_success = time.monotonic()
            while not self._stop.wait(self._jittered(self.retry_period)):
                if self._gen != gen:
                    # release() + re-acquire() happened while this loop
                    # slept or was wedged: the NEW incarnation has its
                    # own renewal thread — this one must die, not renew
                    # beside it.
                    return
                try:
                    ok = self.renew()
                except TransientRenewError:
                    # Keep retrying while our lease is still live; once it
                    # would have expired another candidate may hold it, so
                    # stand down (renewDeadline semantics, server.go:60-63).
                    if time.monotonic() - last_success < self.lease_duration:
                        continue
                    ok = False
                if self._stop.is_set() or self._gen != gen:
                    # release() ran while this renew was in flight: the
                    # candidate already cleared the election state — a
                    # late renew result must not touch it.
                    return
                if not ok:
                    with self._state_lock:
                        if self._gen == gen:
                            self.is_leader = False
                    return
                last_success = time.monotonic()

        self._renew_thread = threading.Thread(target=loop, daemon=True)
        self._renew_thread.start()
        return True

    def release(self) -> None:
        """Stop renewing and hand the lease off immediately."""
        self._stop.set()
        if self._renew_thread is not None:
            self._renew_thread.join(timeout=self.retry_period * 2)
        if self.is_leader:
            try:
                lease = self.api.get(LEASE_KIND, self.name, self.namespace)
                if lease["spec"].get("holderIdentity") == self.identity:
                    lease = copy.deepcopy(lease)
                    lease["spec"]["holderIdentity"] = ""
                    lease["spec"]["renewTime"] = 0
                    self.api.update(lease)
            except (NotFound, Conflict):
                pass
        with self._state_lock:
            self._gen += 1
            self.is_leader = False
            self.epoch = 0
