"""Feature gates: cluster-conditional and operator-set capability toggles.

Mirrors pkg/common/feature_gates/feature_gates.go: the reference holds a
mutable gate set (k8s featureutil.DefaultMutableFeatureGate) and flips the
DynamicResourceAllocation gate from API-server discovery — DRA engages only
when the server is >= 1.26 AND serves resource.k8s.io at >= v1beta1
(feature_gates.go:22-95).  Here the gate set is an explicit object threaded
through configuration instead of process-global mutable state: the
scheduler config carries a gate map, ``build_plugins`` consults it at
registration time, and the operator reconciles gate values from the Config
CRD into every shard.
"""

from __future__ import annotations

# Gates with in-tree wiring.  Values are the DEFAULTS when neither the
# config map nor auto-detection says otherwise.
DYNAMIC_RESOURCE_ALLOCATION = "DynamicResourceAllocation"
TOPOLOGY_AWARE_SCHEDULING = "TopologyAwareScheduling"
MIN_RUNTIME_PROTECTION = "MinRuntimeProtection"

KNOWN_GATES = {
    DYNAMIC_RESOURCE_ALLOCATION: True,
    TOPOLOGY_AWARE_SCHEDULING: True,
    MIN_RUNTIME_PROTECTION: True,
}

# Plugins whose REGISTRATION is controlled by a gate (plugins absent from
# this map are unconditional).  Mirrors how the reference's DRA gate
# decides whether the upstream DRA manager participates at all.
PLUGIN_GATES = {
    "dynamicresources": DYNAMIC_RESOURCE_ALLOCATION,
    "topology": TOPOLOGY_AWARE_SCHEDULING,
    "minruntime": MIN_RUNTIME_PROTECTION,
}

# Minimum server support for DRA (feature_gates.go:19,83-95).
_DRA_MIN_MINOR = 26
_DRA_GROUP = "resource.k8s.io"
_DRA_MIN_VERSION = "v1beta1"


class FeatureGates:
    """An explicit, immutable-by-convention gate set.

    ``overrides`` (config/CLI) win over auto-detected values, which win
    over KNOWN_GATES defaults.  Unknown gate names are allowed (plugins
    registered by downstream code may define their own) and default to
    the caller-supplied fallback."""

    def __init__(self, overrides: dict | None = None,
                 detected: dict | None = None):
        self._detected = dict(detected or {})
        self._overrides = {k: bool(v) for k, v in (overrides or {}).items()}

    def enabled(self, name: str, default: bool = True) -> bool:
        if name in self._overrides:
            return self._overrides[name]
        if name in self._detected:
            return self._detected[name]
        return KNOWN_GATES.get(name, default)

    def plugin_enabled(self, plugin_name: str) -> bool:
        gate = PLUGIN_GATES.get(plugin_name)
        return True if gate is None else self.enabled(gate)

    def as_dict(self) -> dict:
        out = dict(KNOWN_GATES)
        out.update(self._detected)
        out.update(self._overrides)
        return out

    @classmethod
    def from_string(cls, spec: str) -> "FeatureGates":
        """Parse the kubelet-style ``Gate1=true,Gate2=false`` flag form."""
        return cls(parse_gate_string(spec))


def parse_gate_string(spec: str) -> dict:
    """``Gate1=true,Gate2=false`` -> {name: bool} (overrides only)."""
    overrides = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, value = part.partition("=")
        overrides[name.strip()] = value.strip().lower() in (
            "1", "true", "yes", "on", "")
    return overrides


def _parse_minor(minor: str) -> int:
    """K8s minor versions carry vendor suffixes ('26+', '27-gke.400')."""
    digits = ""
    for ch in minor:
        if ch.isdigit():
            digits += ch
        else:
            break
    return int(digits) if digits else -1


def _kube_aware_at_least(version: str, floor: str) -> bool:
    """Compare 'v1beta1'-style versions the way K8s orders them:
    GA (v1, v2, ...) > beta > alpha; higher major wins within a class."""
    def rank(v: str):
        v = v.lstrip("v")
        for stage, weight in (("alpha", 0), ("beta", 1)):
            if stage in v:
                major, _, rev = v.partition(stage)
                return (weight, int(major or 0), int(rev or 0))
        try:
            return (2, int(v), 0)
        except ValueError:
            return (-1, 0, 0)
    return rank(version) >= rank(floor)


def detect_dra(api) -> bool:
    """Is DRA usable against this API server?  (feature_gates.go:30-80.)

    Best-effort duck typing over the API client: a client exposing
    ``server_version()`` -> {"major","minor"} and ``server_groups()`` ->
    {group: [versions]} gets the reference's full check; the in-memory
    substrate (no discovery surface) counts as supporting everything —
    matching the embedded deployment, where DRA objects are first-class.
    """
    version_fn = getattr(api, "server_version", None)
    groups_fn = getattr(api, "server_groups", None)
    if version_fn is None or groups_fn is None:
        return True
    try:
        version = version_fn()
        if int(version.get("major", 0)) < 1:
            return False
        if _parse_minor(str(version.get("minor", ""))) < _DRA_MIN_MINOR:
            return False
        groups = groups_fn()
    except Exception:
        return False
    versions = groups.get(_DRA_GROUP)
    if not versions:
        return False
    return any(_kube_aware_at_least(v, _DRA_MIN_VERSION) for v in versions)


def gates_for(config, api=None) -> FeatureGates:
    """Build the effective gate set for one scheduler/shard config:
    config-map overrides over auto-detected values (the config's
    ``detected_gates`` layer, refreshed by the operator on every fleet
    rebuild, plus optional live API detection)."""
    detected = dict(getattr(config, "detected_gates", None) or {})
    if api is not None:
        detected[DYNAMIC_RESOURCE_ALLOCATION] = detect_dra(api)
    overrides = getattr(config, "feature_gates", None) or {}
    return FeatureGates(overrides, detected)
