"""Crash-safe bind journal: a write-ahead intent log for statement commits.

The scheduler's durable output is the statement commit — BindRequest
creates and evictions pushed through the cache executor.  A scheduler
that dies *between* deciding and writing (or mid-way through a gang's
BindRequest fan-out) leaves the cluster in a state no component can
distinguish from "never decided": phantom fractional-GPU reservations
keep real capacity hostage, half-committed gangs deadlock (arxiv
2603.22691 — any partial commit of a gang is a full-job loss).

This module gives commits the classic WAL discipline:

  1. append one ``intent`` record per durable side effect (fsync'd as a
     batch before the first API write);
  2. perform the API writes;
  3. append a ``done`` record per completed write (buffered — losing a
     ``done`` only costs an idempotent re-check on restart, never
     correctness).

On startup the reconcile pass (``ClusterCache.startup_reconcile``)
replays the journal against live API state: intents without a matching
``done`` are checked against the store, orphaned reservation pods are
garbage-collected, and the journal is compacted.

Record wire format — one record per line, torn-write safe:

    <crc32 hex, 8 chars> <canonical JSON>\n

``replay()`` verifies each line's CRC and STOPS at the first corrupt or
truncated line (a torn tail from a crash mid-append); everything before
it is trusted.  Records carry a monotonically increasing ``txid`` that
survives restarts (max replayed + 1).
"""

from __future__ import annotations

import json
import os
import threading
import zlib

from .logging import LOG
from .metrics import METRICS


class SimulatedCrash(RuntimeError):
    """Raised by the ``crash-after-journal`` fault between the journal
    append and the API commit — the in-process stand-in for ``kill -9``
    at the worst possible instant (the chaos suite's acceptance case)."""


def _encode(record: dict) -> bytes:
    payload = json.dumps(record, sort_keys=True,
                         separators=(",", ":")).encode()
    crc = zlib.crc32(payload) & 0xFFFFFFFF
    return b"%08x " % crc + payload + b"\n"


def _decode(line: bytes) -> dict | None:
    """Parse one journal line; None on any corruption (bad CRC, torn
    JSON, short line)."""
    if len(line) < 10 or line[8:9] != b" ":
        return None
    try:
        crc = int(line[:8], 16)
    except ValueError:
        return None
    payload = line[9:].rstrip(b"\n")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        return None
    try:
        rec = json.loads(payload)
    except ValueError:
        return None
    return rec if isinstance(rec, dict) else None


class CommitLog:
    """File-backed append-only intent journal (one writer per file)."""

    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._records = self._replay_file()
        self._txid = 1 + max((r.get("txid", 0) for r in self._records),
                             default=0)
        self._fh = open(self.path, "ab")

    # -- durability --------------------------------------------------------
    def _replay_file(self) -> list[dict]:
        records: list[dict] = []
        if not os.path.exists(self.path):
            return records
        valid_bytes = 0
        with open(self.path, "rb") as fh:
            for lineno, line in enumerate(fh, 1):
                rec = _decode(line)
                if rec is None:
                    # Torn tail (crash mid-append) or bit rot: everything
                    # after the first bad line is untrusted — stop, never
                    # skip-and-continue past corruption, and TRUNCATE the
                    # file to the valid prefix so the next append starts
                    # a clean line instead of gluing onto the torn one.
                    LOG.warning("commitlog %s: corrupt record at line %d; "
                                "truncating to the valid prefix",
                                self.path, lineno)
                    METRICS.inc("commitlog_corrupt_records")
                    with open(self.path, "r+b") as trunc:
                        trunc.truncate(valid_bytes)
                    break
                valid_bytes += len(line)
                records.append(rec)
        return records

    def _flush(self) -> None:
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())

    # -- append API --------------------------------------------------------
    def append(self, record: dict, flush: bool = True) -> int:
        """Append one record; returns its txid.  ``flush=False`` buffers
        (used for ``done`` markers, where loss is harmless)."""
        with self._lock:
            record = dict(record)
            record["txid"] = self._txid
            self._txid += 1
            self._fh.write(_encode(record))
            if flush:
                self._flush()
            self._records.append(record)
            return record["txid"]

    def append_intents(self, intents: list[dict]) -> list[int]:
        """Append a batch of intent records with ONE fsync — the gang
        commit's atomic journal point: either every member's intent is
        durable before the first API write, or none are."""
        with self._lock:
            txids = []
            for intent in intents:
                rec = dict(intent)
                rec["t"] = "intent"
                rec["txid"] = self._txid
                self._txid += 1
                self._fh.write(_encode(rec))
                self._records.append(rec)
                txids.append(rec["txid"])
            self._flush()
            return txids

    def mark_done(self, txid: int) -> None:
        """The API write for ``txid`` completed; buffered (no fsync) —
        a lost done record re-checks one intent on restart, idempotently."""
        self.append({"t": "done", "intent": txid}, flush=False)

    def flush_buffered(self) -> None:
        """Push buffered done markers to the OS (no fsync): cheap, and
        bounds what a crash can force the next reconcile to re-check."""
        with self._lock:
            self._fh.flush()

    # -- replay API --------------------------------------------------------
    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records)

    def pending_intents(self) -> list[dict]:
        """Intents with no matching done record — the writes whose fate
        the restart reconcile pass must determine from live API state."""
        with self._lock:
            done = {r.get("intent") for r in self._records
                    if r.get("t") == "done"}
            return [r for r in self._records
                    if r.get("t") == "intent" and r["txid"] not in done]

    def compact(self, keep: list[dict] | None = None) -> None:
        """Rewrite the file with only ``keep`` (default: nothing).  Run
        after a reconcile pass resolved every pending intent."""
        with self._lock:
            keep = list(keep or [])
            self._fh.close()
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as fh:
                for rec in keep:
                    fh.write(_encode(rec))
                fh.flush()
                # fsync-under-lock IS the contract here: compact must
                # exclude concurrent appends until the durable rewrite
                # replaces the file, or an append lands in the old inode
                # and is silently dropped.
                # kailint: disable=KAI006 — WAL compact serializes against appends by design
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
            self._records = keep
            self._fh = open(self.path, "ab")

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.flush()
                self._fh.close()
            except ValueError:  # already closed
                pass


def bind_intent(pod_uid: str, pod_name: str, namespace: str,
                node_name: str, gpu_groups: list, epoch: int | None) -> dict:
    """The intent record for one BindRequest create (Statement.commit)."""
    return {"kind": "bind", "pod_uid": pod_uid, "pod_name": pod_name,
            "namespace": namespace, "node": node_name,
            "gpu_groups": list(gpu_groups or []), "epoch": epoch}


def evict_intent(pod_uid: str, pod_name: str, namespace: str,
                 epoch: int | None) -> dict:
    """The intent record for one eviction (Statement.commit)."""
    return {"kind": "evict", "pod_uid": pod_uid, "pod_name": pod_name,
            "namespace": namespace, "epoch": epoch}
