"""Declarative cluster spec builder.

Mirrors the reference's pkg/scheduler/test_utils (TestTopologyBasic +
BuildSession): a dict-driven spec builds nodes/queues/podgroups into a
ClusterInfo, or a full live Session over it.  Shared by the test suite and
the offline simulators (cmd/fairshare-simulator-style harnesses).
"""

from __future__ import annotations

from ..api import (ClusterInfo, NodeInfo, PodGroupInfo,
                                   PodInfo, PodSet, PodStatus, QueueInfo,
                                   QueueQuota, resources as rs)
from ..api.resources import ResourceRequirements
from ..framework import SchedulerConfig, Session


def _terms(raw) -> list:
    from ..api import AffinityTerm
    return [AffinityTerm(dict(r["selector"]), r["topology_key"],
                         float(r.get("weight", 1.0)),
                         [dict(e) for e in r.get("expressions", ())],
                         list(r.get("namespaces", ["default"])))
            for r in (raw or ())]


def build_cluster(spec: dict) -> ClusterInfo:
    """spec = {nodes: {name: {cpu, mem, gpu, labels, taints, gpu_memory}},
    queues: {name: {deserved, limit, oqw, parent, priority}},
    jobs: {name: {queue, min_available, priority, preemptible, pod_sets,
                  tasks: [{name, cpu, mem, gpu, gpu_fraction, status, node,
                           subgroup, selector, tolerations}]}}}"""
    nodes = {}
    for name, n in spec.get("nodes", {}).items():
        nodes[name] = NodeInfo(
            name,
            rs.vec_from_spec(n.get("cpu", "32"), n.get("mem", "256Gi"),
                             n.get("gpu", 8)),
            labels=n.get("labels"), taints=set(n.get("taints", ())),
            gpu_memory_per_device=rs.parse_memory(n["gpu_memory"])
            if "gpu_memory" in n else 16 * 2 ** 30,
            max_pods=n.get("max_pods", 110),
            mig_capacity=n.get("mig_capacity"))

    queues = {}
    for name, q in spec.get("queues", {"default": {}}).items():
        queues[name] = QueueInfo(
            name, parent=q.get("parent"), priority=q.get("priority", 0),
            creation_ts=q.get("creation_ts", 0.0),
            quota=QueueQuota.from_spec(
                deserved=q.get("deserved"), limit=q.get("limit"),
                over_quota_weight=q.get("oqw", 1.0)),
            preempt_min_runtime=q.get("preempt_min_runtime"),
            reclaim_min_runtime=q.get("reclaim_min_runtime"))
    for name, q in queues.items():
        if q.parent and name not in queues[q.parent].children:
            queues[q.parent].children.append(name)

    podgroups = {}
    _JOB_KEYS = {"queue", "min_available", "priority", "preemptible",
                 "creation_ts", "topology", "required_topology_level",
                 "preferred_topology_level", "pod_sets", "tasks",
                 "last_start_ts", "staleness_grace_seconds"}
    _TASK_KEYS = {"uid", "name", "subgroup", "status", "node", "selector",
                  "rank",
                  "tolerations", "cpu", "mem", "gpu", "gpu_fraction",
                  "gpu_memory", "mig", "gpu_group", "nominated",
                  "resource_claims", "affinity", "anti_affinity",
                  "labels", "host_ports", "configmaps", "pvcs",
                  "affinity_terms", "anti_affinity_terms",
                  "preferred_affinity_terms",
                  "preferred_anti_affinity_terms", "node_affinity",
                  "node_affinity_preferred"}
    for name, j in spec.get("jobs", {}).items():
        unknown = set(j) - _JOB_KEYS
        if unknown:
            # Loud, not silent: a constraint typo'd or placed at job
            # level (e.g. node_affinity belongs on each task) would
            # otherwise vanish and the test/simulation would assert
            # against an unconstrained schedule.
            raise ValueError(
                f"job {name!r}: unknown spec keys {sorted(unknown)} "
                f"(per-task constraints go inside 'tasks' entries)")
        pg = PodGroupInfo(
            name, name, queue_id=j.get("queue", "default"),
            priority=j.get("priority", 0),
            min_available=j.get("min_available", 1),
            preemptible=j.get("preemptible", True),
            creation_ts=j.get("creation_ts", 0.0),
            staleness_grace_seconds=j.get("staleness_grace_seconds",
                                          60.0),
            topology_name=j.get("topology"),
            required_topology_level=j.get("required_topology_level"),
            preferred_topology_level=j.get("preferred_topology_level"))
        pg.last_start_ts = j.get("last_start_ts")
        if "pod_sets" in j:
            pg.set_pod_sets([
                PodSet(ps["name"], ps["min_available"],
                       topology_name=ps.get("topology"),
                       required_topology_level=ps.get(
                           "required_topology_level"),
                       preferred_topology_level=ps.get(
                           "preferred_topology_level"))
                for ps in j["pod_sets"]])
        for i, t in enumerate(j.get("tasks", [])):
            unknown = set(t) - _TASK_KEYS
            if unknown:
                raise ValueError(
                    f"job {name!r} task {i}: unknown spec keys "
                    f"{sorted(unknown)}")
            task = PodInfo(
                uid=t.get("uid", f"{name}-{i}"),
                name=t.get("name", f"{name}-{i}"),
                subgroup=t.get("subgroup", "default"),
                status=PodStatus[t.get("status", "PENDING").upper()],
                node_name=t.get("node", ""),
                rank=int(t.get("rank", -1)),
                node_selector=t.get("selector", {}),
                tolerations=set(t.get("tolerations", ())),
                res_req=ResourceRequirements.from_spec(
                    t.get("cpu", "1"), t.get("mem", "1Gi"), t.get("gpu", 0),
                    gpu_fraction=t.get("gpu_fraction", 0.0),
                    gpu_memory=t.get("gpu_memory"),
                    mig=t.get("mig")))
            if t.get("gpu_group"):
                task.gpu_group = t["gpu_group"]
            if t.get("nominated"):
                task.nominated_node = t["nominated"]
            task.resource_claims = list(t.get("resource_claims", ()))
            task.pod_affinity_peers = list(t.get("affinity", ()))
            task.pod_anti_affinity_peers = list(t.get("anti_affinity", ()))
            # Full (anti-)affinity terms: {selector, topology_key[, weight]}
            # dicts, mirroring matchLabels + topologyKey.
            task.labels = dict(t.get("labels", {}))
            task.host_ports = {(pp.get("protocol", "TCP"), pp["port"])
                               if isinstance(pp, dict) else ("TCP", pp)
                               for pp in t.get("host_ports", ())}
            task.required_configmaps = list(t.get("configmaps", ()))
            task.pvc_names = list(t.get("pvcs", ()))
            task.node_affinity_required = [
                {"expressions": list(term.get("expressions", ())),
                 "fields": list(term.get("fields", ()))}
                for term in t.get("node_affinity", ())]
            task.node_affinity_preferred = [
                {"weight": float(term.get("weight", 1)),
                 "expressions": list(term.get("expressions", ())),
                 "fields": list(term.get("fields", ()))}
                for term in t.get("node_affinity_preferred", ())]
            task.affinity_terms = _terms(t.get("affinity_terms"))
            task.anti_affinity_terms = _terms(t.get("anti_affinity_terms"))
            task.preferred_affinity_terms = _terms(
                t.get("preferred_affinity_terms"))
            task.preferred_anti_affinity_terms = _terms(
                t.get("preferred_anti_affinity_terms"))
            pg.add_task(task)
        podgroups[name] = pg

    # Schedule-time CSI storage: raw manifest lists, run through the same
    # snapshot filter chain as the live cache (api/storage_info.py).
    storage = spec.get("storage") or {}
    storage_classes = storage_claims = storage_capacities = None
    pvcs = {(k if isinstance(k, tuple) else ("default", k)): dict(v)
            for k, v in spec.get("pvcs", {}).items()}
    if storage:
        from ..api.storage_info import build_storage_snapshot
        storage_classes, storage_claims, storage_capacities = \
            build_storage_snapshot(
                storage.get("csi_drivers", []), storage.get("classes", []),
                storage.get("claims", []), storage.get("capacities", []))
        # Every claim manifest is also a PVC for the existence prefilter
        # (the live cache derives both from the same list).
        for pvc in storage.get("claims", []):
            md = pvc["metadata"]
            pvcs.setdefault(
                (md.get("namespace", "default"), md["name"]),
                {"bound_node": (md.get("annotations") or {}).get(
                    "volume.kubernetes.io/selected-node")})

    return ClusterInfo(
        nodes, podgroups, queues,
        topologies=spec.get("topologies", {}),
        now=spec.get("now", 1000.0),
        resource_claims=spec.get("resource_claims", {}),
        config_maps={(ns_name if isinstance(ns_name, tuple)
                      else ("default", ns_name))
                     for ns_name in spec.get("config_maps", ())},
        pvcs=pvcs,
        resource_slices=spec.get("resource_slices", {}),
        device_classes=spec.get("device_classes", {}),
        storage_classes=storage_classes,
        storage_claims=storage_claims,
        storage_capacities=storage_capacities)


def build_session(spec: dict, config: SchedulerConfig | None = None
                  ) -> Session:
    cluster = build_cluster(spec)
    ssn = Session(cluster, config or SchedulerConfig(),
                  queue_usage=spec.get("queue_usage"))
    return ssn.open()


def run_action(ssn: Session, action_name: str = "allocate") -> None:
    from ..actions import build_actions
    for action in build_actions([action_name]):
        action.execute(ssn)


def placements(ssn: Session) -> dict:
    """task uid -> (node_name, status_name) for all placed tasks."""
    out = {}
    for pg in ssn.cluster.podgroups.values():
        for t in pg.pods.values():
            if t.node_name:
                out[t.uid] = (t.node_name, t.status.name)
    return out


def assert_placements(ssn: Session, expected: dict) -> None:
    """expected: uid -> node name, or uid -> (node, status)."""
    actual = placements(ssn)
    for uid, want in expected.items():
        assert uid in actual, f"task {uid} not placed; placed={actual}"
        node, status = actual[uid]
        if isinstance(want, tuple):
            assert (node, status) == want, \
                f"{uid}: got {(node, status)}, want {want}"
        else:
            assert node == want, f"{uid}: got {node}, want {want}"
