"""Upstream-predicate plugin: NodePorts, schedule-time VolumeBinding,
ConfigMap, MaxNodePoolResources.

Mirrors the reference's upstream-plugin adapters
(pkg/scheduler/k8s_internal/predicates/predicates.go:70-167 wires
NodePorts/VolumeBinding; config_maps.go and maxNodeResources.go are its
own PreFilter-only predicates) re-designed for the tensor path: node-level
filters contribute hard [T,N] masks (session.hard_node_mask_fns), and
cluster-level PreFilters run once per job through
session.pre_predicate_fns, failing fast with the reference's
unschedulable-message shapes.
"""

from __future__ import annotations

import numpy as np

from ..api import resources as rs
from ..framework.session import SchedulableResult
from .base import Plugin, register_plugin


@register_plugin("predicates")
class UpstreamPredicatesPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        self.ssn = ssn
        # MaxNodePoolResources: element-wise max over the shard's nodes
        # (maxNodeResources.go:41-43 SetMaxResource).
        nodes = list(ssn.cluster.nodes.values())
        self.max_alloc = (np.max([n.allocatable for n in nodes], axis=0)
                          if nodes else rs.zeros())
        self.max_mig: dict[str, float] = {}
        for n in nodes:
            for profile, count in n.mig_capacity.items():
                self.max_mig[profile] = max(
                    self.max_mig.get(profile, 0.0), count)
        self._ports_cache = (-1, None)  # (mutation_count, ports)
        # Node-affinity mask/score caches: node labels are immutable for
        # the session, so each distinct term spec evaluates once.
        self._node_aff_cache: dict = {}
        ssn.pre_predicate_fns.append(self.pre_predicate)
        ssn.hard_node_mask_fns.append(self.node_masks)
        ssn.extra_score_fns.append(self.preferred_node_affinity_scores)

    # -- PreFilters (cluster-level, once per task) -------------------------
    def pre_predicate(self, task) -> SchedulableResult:
        res = self._max_node_resources(task)
        if not res.schedulable:
            return res
        res = self._configmaps_exist(task)
        if not res.schedulable:
            return res
        return self._pvcs_exist(task)

    def _max_node_resources(self, task) -> SchedulableResult:
        """maxNodeResources.go PreFilter: no single node in the pool can
        ever fit the request -> unschedulable without scanning nodes."""
        req = task.res_req.to_vec(mig_as_gpu=False)
        for i, name in enumerate(rs.RESOURCE_NAMES):
            if req[i] > self.max_alloc[i] + 1e-9:
                return SchedulableResult(
                    False, "MaxNodePoolResources",
                    f"pod {task.namespace}/{task.name} requires "
                    f"{req[i]:g} {name}; max available in a single node "
                    f"in this node-pool is {self.max_alloc[i]:g}")
        for profile, count in task.res_req.mig_resources.items():
            if count > self.max_mig.get(profile, 0.0) + 1e-9:
                return SchedulableResult(
                    False, "MaxNodePoolResources",
                    f"no node in this node-pool has {count:g} x {profile}")
        return SchedulableResult()

    def _configmaps_exist(self, task) -> SchedulableResult:
        """config_maps.go PreFilter: every required (non-optional)
        ConfigMap must exist."""
        missing = [cm for cm in task.required_configmaps
                   if (task.namespace, cm) not in self.ssn.cluster.config_maps]
        if missing:
            return SchedulableResult(
                False, "ConfigMap",
                f"Missing required configmaps: {missing}")
        return SchedulableResult()

    def _pvcs_exist(self, task) -> SchedulableResult:
        """volume_binding.go filter, cluster-level half: referenced PVCs
        must exist (unbound WaitForFirstConsumer ones bind later), and
        none may be mid-garbage-collection with its dead owner pod
        (isTaskStorageAllocatable's deleted-claims hard failure,
        node_info.go:212-215)."""
        missing = [name for name in task.pvc_names
                   if (task.namespace, name) not in self.ssn.cluster.pvcs]
        if missing:
            return SchedulableResult(
                False, "VolumeBinding",
                f"pod {task.namespace}/{task.name} references missing "
                f"PersistentVolumeClaims: {missing}")
        deleted = task.deleted_storage_claim_names()
        if deleted:
            return SchedulableResult(
                False, "VolumeBinding",
                f"task has deleted storage claims: {deleted}")
        return SchedulableResult()

    # -- node affinity (upstream NodeAffinity, predicates.go:70-167) -------
    def _node_affinity_mask(self, terms: list) -> np.ndarray:
        """[N] bool: nodes whose labels satisfy the required
        nodeSelectorTerms.  Node labels are session-immutable, so each
        distinct spec evaluates once; padding rows stay False."""
        key = repr(terms)
        cached = self._node_aff_cache.get(key)
        if cached is not None:
            return cached
        from ..api.pod_info import node_affinity_matches
        names = self.ssn.snapshot.node_names
        nodes = self.ssn.cluster.nodes
        mask = np.zeros(self.ssn.node_idle.shape[0], bool)
        for i, name in enumerate(names):
            node = nodes.get(name)
            if node is not None and node_affinity_matches(
                    terms, node.labels or {}, name):
                mask[i] = True
        self._node_aff_cache[key] = mask
        return mask

    def preferred_node_affinity_scores(self, tasks):
        """Weighted preferred-term boosts (the NodeAffinity score plugin).
        Scale 10 per weight unit: the smallest step the grouped kernel's
        uniform-extras contract allows (extras must be multiples of 10,
        framework/session.py homogeneous gate)."""
        out = None
        for i, task in enumerate(tasks):
            prefs = getattr(task, "node_affinity_preferred", None) or []
            if not prefs:
                continue
            if out is None:
                out = np.zeros((len(tasks), self.ssn.node_idle.shape[0]))
            for term in prefs:
                spec = [{"expressions": term.get("expressions") or [],
                         "fields": term.get("fields") or []}]
                out[i] += (float(term.get("weight", 1)) * 10.0
                           * self._node_affinity_mask(spec))
        return out

    # -- node-level filters as hard masks ----------------------------------
    def node_masks(self, tasks):
        needs = any(t.host_ports or t.pvc_names
                    or t.node_affinity_required for t in tasks)
        if not needs:
            return None
        n = self.ssn.node_idle.shape[0]
        out = np.ones((len(tasks), n), bool)
        port_masks = None
        for i, task in enumerate(tasks):
            if task.node_affinity_required:
                out[i] &= self._node_affinity_mask(
                    task.node_affinity_required)
            if task.host_ports:
                if port_masks is None:
                    port_masks = self._ports_by_node()
                for port in task.host_ports:
                    occupied = port_masks.get(port)
                    if occupied is not None:
                        out[i] &= ~occupied
            for pvc_name in task.pvc_names:
                pvc = self.ssn.cluster.pvcs.get(
                    (task.namespace, pvc_name))
                bound = (pvc or {}).get("bound_node")
                if bound:
                    # Local/bound volume: the pod must follow it
                    # (volume_binding.go node-affinity filter).
                    idx = self.ssn.node_index(bound)
                    keep = np.zeros(n, bool)
                    if idx >= 0:
                        keep[idx] = True
                    out[i] &= keep
            if task.needs_storage_scheduling():
                out[i] &= self._storage_mask(task, n)
        return out

    def _storage_mask(self, task, n: int) -> np.ndarray:
        """[N] bool: nodes whose accessible CSI capacities can host the
        task's pending claims (releasing-permissive ceiling — the exact
        idle-vs-releasing split is enforced by NodeInfo checks on the
        sequential host path).  Feasibility is computed once per
        *capacity* (few), then mapped onto nodes (many); the pod-infos
        dict is memoized per mutation tick (it is O(total pods))."""
        cluster = self.ssn.cluster
        pending = task.pending_claims_by_class()
        feasible_caps: dict[str, set] = {}
        for cls, claims in pending.items():
            feasible_caps[cls] = {
                cap.uid for cap in cluster.storage_capacities.values()
                if cap.storage_class == cls
                and cap.are_pvcs_allocatable_on_releasing_or_idle(
                    claims, self._all_pod_infos())}
        keep = np.zeros(n, bool)
        for name in cluster.node_order:
            node = cluster.nodes[name]
            ok = True
            for cls in pending:
                caps = node.accessible_capacities.get(cls)
                if not caps or not any(c.uid in feasible_caps[cls]
                                       for c in caps):
                    ok = False
                    break
            if ok and 0 <= node.idx < n:
                keep[node.idx] = True
        return keep

    def _all_pod_infos(self) -> dict:
        tick = self.ssn.mutation_count
        cached = getattr(self, "_pods_cache", None)
        if cached is not None and cached[0] == tick:
            return cached[1]
        out = {}
        for pg in self.ssn.cluster.podgroups.values():
            out.update(pg.pods)
        self._pods_cache = (tick, out)
        return out

    def _ports_by_node(self) -> dict:
        """(protocol, hostPort) -> [N] bool occupied-node mask
        (nodeports.go: Fits against NodeInfo.UsedPorts), memoized per
        session mutation tick.  Boolean rows keep the per-task mask a few
        numpy ops instead of an O(N) Python scan."""
        tick = self.ssn.mutation_count
        if self._ports_cache[0] == tick:
            return self._ports_cache[1]
        n = self.ssn.node_idle.shape[0]
        out: dict = {}
        hints = getattr(self.ssn.cluster, "columnar_hints", None)
        if hints and hints.get("no_host_ports"):
            # Columnar snapshot: no pod in the population carries a host
            # port — identical (empty) occupancy, no O(pods) walk.
            self._ports_cache = (tick, out)
            return out
        for pg in self.ssn.cluster.podgroups.values():
            for t in pg.pods.values():
                if not t.host_ports or not t.node_name:
                    continue
                if not t.is_active_allocated():
                    continue
                idx = self.ssn.node_index(t.node_name)
                if idx < 0:
                    continue
                for port in t.host_ports:
                    mask = out.get(port)
                    if mask is None:
                        mask = out[port] = np.zeros(n, bool)
                    mask[idx] = True
        self._ports_cache = (tick, out)
        return out
