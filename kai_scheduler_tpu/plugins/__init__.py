"""Policy plugins (SURVEY.md §2.3): each registers callbacks into the
Session; score-term plugins configure the device kernel instead of running
per-node callbacks."""

from .base import Plugin, build_plugins, register_plugin, registered_plugins

# Import for registration side effects.
from . import dynamicresources  # noqa: F401
from . import minruntime  # noqa: F401
from . import ordering  # noqa: F401
from . import placement  # noqa: F401
from . import podaffinity  # noqa: F401
from . import predicates_ext  # noqa: F401
from . import proportion  # noqa: F401
from . import snapshot_plugin  # noqa: F401
from . import topology  # noqa: F401

__all__ = ["Plugin", "build_plugins", "register_plugin",
           "registered_plugins"]
