"""Dynamic Resource Allocation (DRA) plugin — structured device claims.

Mirrors pkg/scheduler/plugins/dynamicresources/dynamicresources.go:59-87
plus the upstream DRA manager's structured allocation: tasks reference
ResourceClaims (deviceClassName + count); device inventory comes from
per-node ResourceSlices (``cluster.resource_slices``); the scheduler picks
concrete free devices, assumes them in-session (rolled back with the
statement), and writes ResourceClaimAllocations onto the BindRequest
(dynamicresources.go:252 allocateResourceClaim) so the binder can publish
``claim.status.allocation``.

Claim states the schedulability check honors:
- already allocated (status.allocation / legacy "node"): the task must
  follow the allocation's node;
- unallocated: the candidate node must hold >= count FREE devices of the
  claim's class (free = slice inventory minus devices assumed or
  allocated to other claims);
- unknown claim name: unschedulable.
"""

from __future__ import annotations

from .base import Plugin, register_plugin


def _device_name(dev) -> str:
    return dev["name"] if isinstance(dev, dict) else dev


def _qty(value) -> float | None:
    """Quantity -> float via the shared helper (cache_builder parse time
    and match time must agree on suffix handling)."""
    from ..api import resources as rs
    return rs.parse_quantity(value)


def _lookup(mapping: dict, key: str, fallback_key, driver=None) -> object:
    """Qualified-key lookup with a domain-scoped bare-name fallback: CEL
    addresses attributes as domain/name; flat inventories may key by
    name alone, but the fallback only applies when the device's driver
    matches the selector's domain (or records no driver at all) — a
    bare "family" on an NVIDIA device must not satisfy an
    attributes["gpu.amd.com"].family selector."""
    if key in mapping:
        return mapping[key]
    if fallback_key is None:
        return None
    domain = key.split("/", 1)[0] if "/" in key else None
    if driver is None or domain is None or driver == domain:
        return mapping.get(fallback_key)
    return None


def _device_matches(dev, selectors: list) -> bool:
    """Structured selector match: attribute equality/membership +
    capacity minimums (incl. the translated CEL subset of upstream
    DeviceClass/request selectors).  Unsupported entries match
    nothing."""
    if not selectors:
        return True
    attrs = dev.get("attributes", {}) if isinstance(dev, dict) else {}
    caps = dev.get("capacity", {}) if isinstance(dev, dict) else {}
    driver = attrs.get("driver")
    for sel in selectors:
        if "attribute" in sel:
            have = _lookup(attrs, sel["attribute"],
                           sel.get("fallback_attribute"), driver)
            if "any_of" in sel:
                if have is None or have not in sel["any_of"]:
                    return False
                continue
            want = sel.get("value")
            # A value-less selector is malformed: match nothing (a None
            # "want" would otherwise equal the None of attribute-less
            # devices and over-match).
            if want is None or have != want:
                return False
        elif "capacity" in sel:
            have = _qty(_lookup(caps, sel["capacity"],
                                sel.get("fallback_capacity"), driver))
            want = _qty(sel.get("min"))
            if have is None or want is None or have < want:
                return False
        else:
            return False
    return True


@register_plugin("dynamicresources")
class DynamicResourcesPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        self.ssn = ssn
        self.claims = getattr(ssn.cluster, "resource_claims", {})
        self.slices = getattr(ssn.cluster, "resource_slices", {})
        self.device_classes = getattr(ssn.cluster, "device_classes", {})
        if not self.claims:
            return
        # In-session assumed allocations: claim -> {"node", "devices"}
        # (rolled back with the statement via the deallocate handler).
        self.assumed: dict[str, dict] = {}
        # Devices already promised on each node: node -> {device names}.
        self.devices_taken: dict[str, set] = {}
        for name, claim in self.claims.items():
            alloc = self._allocation(claim)
            if alloc and alloc.get("node"):
                self.devices_taken.setdefault(
                    alloc["node"], set()).update(alloc.get("devices", ()))
        ssn.allocate_handlers.append(self.on_allocate)
        ssn.deallocate_handlers.append(self.on_deallocate)
        ssn.bind_request_mutators = getattr(ssn, "bind_request_mutators",
                                            [])
        ssn.bind_request_mutators.append(self.mutate_bind_request)

    @staticmethod
    def _allocation(claim: dict) -> dict | None:
        alloc = claim.get("allocation")
        if alloc:
            return alloc
        if claim.get("node"):  # legacy shape
            return {"node": claim["node"], "devices": []}
        return None

    @staticmethod
    def _requests(claim: dict) -> list:
        """[(device_class, count, selectors)] — multi-request claims
        supported; the legacy single device_class/count shape maps to one
        entry."""
        reqs = claim.get("requests")
        if reqs:
            return [(r.get("device_class", r.get("deviceClassName", "")),
                     int(r.get("count", 1)),
                     r.get("selectors") or []) for r in reqs]
        return [(claim.get("device_class", ""),
                 int(claim.get("count", 1)),
                 claim.get("selectors") or [])]

    def task_claims(self, task) -> list:
        return getattr(task, "resource_claims", []) or []

    def _free_devices(self, node_name: str, device_class: str,
                      selectors: list = ()) -> list:
        """Names of free node devices satisfying the class's structured
        selectors plus the request's own.  A class with selectors draws
        from every pool on the node (upstream classes select devices,
        they don't name pools); a selector-less class keeps the legacy
        pool-keyed-by-class inventory."""
        per_node = self.slices.get(node_name, {})
        cls_sel = (self.device_classes.get(device_class) or {}) \
            .get("selectors") or []
        if cls_sel:
            inventory = [d for pool in per_node.values() for d in pool]
        else:
            inventory = per_node.get(device_class, [])
        sels = list(cls_sel) + list(selectors)
        taken = self.devices_taken.get(node_name, set())
        return [_device_name(d) for d in inventory
                if _device_name(d) not in taken
                and _device_matches(d, sels)]

    def _pick_devices(self, node_name: str, claim: dict,
                      extra_taken: set = frozenset()) -> list | None:
        """Concrete-device choice for one unallocated claim on a node,
        never reusing a device across the claim's requests (nor any in
        ``extra_taken``).  Requests assign scarcest-first — the request
        with the fewest matching free devices picks before looser ones —
        so a selector-less request cannot starve a selective one of its
        only match (upstream's structured allocator backtracks; the
        scarcest-first order is exact for nested/disjoint selector sets,
        the shapes DeviceClasses produce).  None = doesn't fit."""
        candidates = []
        for cls, count, selectors in self._requests(claim):
            free = [d for d in self._free_devices(node_name, cls,
                                                  selectors)
                    if d not in extra_taken]
            if len(free) < count:
                return None
            candidates.append((len(free), count, free))
        chosen: list = []
        for _, count, free in sorted(candidates, key=lambda c: c[0]):
            usable = [d for d in free if d not in chosen]
            if len(usable) < count:
                return None
            chosen += usable[:count]
        return chosen

    def claims_schedulable(self, task, node_name: str) -> bool:
        """PreFilter: every referenced claim must be satisfiable on the
        node — already there, assumed there, or coverable by free slice
        devices.  Uses the SAME picker as allocation, so the check and
        the later assumption can never diverge."""
        local_taken: set = set()
        for name in self.task_claims(task):
            claim = self.claims.get(name)
            if claim is None:
                return False
            alloc = self.assumed.get(name) or self._allocation(claim)
            if alloc is not None:
                if alloc.get("node") != node_name:
                    return False
                continue
            # No slice inventory published (legacy/simplified clusters):
            # any node can host an unallocated claim.
            if self.slices:
                devices = self._pick_devices(node_name, claim,
                                             extra_taken=local_taken)
                if devices is None:
                    return False
                local_taken.update(devices)
        return True

    def on_allocate(self, task) -> None:
        for name in self.task_claims(task):
            claim = self.claims.get(name)
            if claim is None:
                continue
            assumed = self.assumed.get(name)
            if assumed is not None:
                # Shareable claim: another task already holds the
                # assumption; this task becomes a co-user.
                assumed["users"].add(task.uid)
                continue
            if self._allocation(claim) is not None:
                continue
            devices = self._pick_devices(task.node_name, claim)
            if devices is None:
                if self.slices:
                    # The prefilter and this picker share one code path,
                    # so this is unreachable unless a caller placed a DRA
                    # task without consulting claims_schedulable —
                    # publishing an empty allocation would start the
                    # workload without its devices, so fail loudly.
                    raise RuntimeError(
                        f"claim {name!r} does not fit node "
                        f"{task.node_name!r} at allocation time; "
                        f"claims_schedulable was not consulted")
                devices = []  # no inventory published: node-only assume
            self.assumed[name] = {"node": task.node_name,
                                  "devices": devices,
                                  "users": {task.uid}}
            self.devices_taken.setdefault(task.node_name,
                                          set()).update(devices)

    def on_deallocate(self, task, prev_status) -> None:
        for name in self.task_claims(task):
            assumed = self.assumed.get(name)
            if assumed is None:
                continue
            assumed["users"].discard(task.uid)
            # The assumption (and its devices) release only once NO
            # placed task still rides the claim.
            if not assumed["users"]:
                del self.assumed[name]
                self.devices_taken.get(assumed["node"],
                                       set()).difference_update(
                    assumed["devices"])

    def mutate_bind_request(self, task, bind_request) -> None:
        claims = self.task_claims(task)
        if not claims:
            return
        bind_request.resource_claims = list(claims)
        # Structured allocations ride the BindRequest
        # (ResourceClaimAllocations, bindrequest_types.go).
        def alloc_of(name):
            assumed = self.assumed.get(name)
            if assumed is not None:
                return {"node": assumed["node"],
                        "devices": list(assumed["devices"])}
            return (self._allocation(self.claims.get(name, {}))
                    or {"node": task.node_name, "devices": []})

        bind_request.claim_allocations = [
            {"name": name, **alloc_of(name)} for name in claims]
