"""Dynamic Resource Allocation (DRA) plugin — structured device claims.

Mirrors pkg/scheduler/plugins/dynamicresources/dynamicresources.go:59-87
plus the upstream DRA manager's structured allocation: tasks reference
ResourceClaims (deviceClassName + count); device inventory comes from
per-node ResourceSlices (``cluster.resource_slices``); the scheduler picks
concrete free devices, assumes them in-session (rolled back with the
statement), and writes ResourceClaimAllocations onto the BindRequest
(dynamicresources.go:252 allocateResourceClaim) so the binder can publish
``claim.status.allocation``.

Claim states the schedulability check honors:
- already allocated (status.allocation / legacy "node"): the task must
  follow the allocation's node;
- unallocated: the candidate node must hold >= count FREE devices of the
  claim's class (free = slice inventory minus devices assumed or
  allocated to other claims);
- unknown claim name: unschedulable.
"""

from __future__ import annotations

from .base import Plugin, register_plugin


@register_plugin("dynamicresources")
class DynamicResourcesPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        self.ssn = ssn
        self.claims = getattr(ssn.cluster, "resource_claims", {})
        self.slices = getattr(ssn.cluster, "resource_slices", {})
        if not self.claims:
            return
        # In-session assumed allocations: claim -> {"node", "devices"}
        # (rolled back with the statement via the deallocate handler).
        self.assumed: dict[str, dict] = {}
        # Devices already promised on each node: node -> {device names}.
        self.devices_taken: dict[str, set] = {}
        for name, claim in self.claims.items():
            alloc = self._allocation(claim)
            if alloc and alloc.get("node"):
                self.devices_taken.setdefault(
                    alloc["node"], set()).update(alloc.get("devices", ()))
        ssn.allocate_handlers.append(self.on_allocate)
        ssn.deallocate_handlers.append(self.on_deallocate)
        ssn.bind_request_mutators = getattr(ssn, "bind_request_mutators",
                                            [])
        ssn.bind_request_mutators.append(self.mutate_bind_request)

    @staticmethod
    def _allocation(claim: dict) -> dict | None:
        alloc = claim.get("allocation")
        if alloc:
            return alloc
        if claim.get("node"):  # legacy shape
            return {"node": claim["node"], "devices": []}
        return None

    @staticmethod
    def _requests(claim: dict) -> list:
        """[(device_class, count)] — multi-request claims supported;
        the legacy single device_class/count shape maps to one entry."""
        reqs = claim.get("requests")
        if reqs:
            return [(r.get("device_class", r.get("deviceClassName", "")),
                     int(r.get("count", 1))) for r in reqs]
        return [(claim.get("device_class", ""),
                 int(claim.get("count", 1)))]

    def task_claims(self, task) -> list:
        return getattr(task, "resource_claims", []) or []

    def _free_devices(self, node_name: str, device_class: str) -> list:
        inventory = self.slices.get(node_name, {}).get(device_class, [])
        taken = self.devices_taken.get(node_name, set())
        return [d for d in inventory if d not in taken]

    def claims_schedulable(self, task, node_name: str) -> bool:
        """PreFilter: every referenced claim must be satisfiable on the
        node — already there, assumed there, or coverable by free slice
        devices.  Demand accumulates PER device class across the task's
        unallocated claims."""
        needed: dict[str, int] = {}
        for name in self.task_claims(task):
            claim = self.claims.get(name)
            if claim is None:
                return False
            alloc = self.assumed.get(name) or self._allocation(claim)
            if alloc is not None:
                if alloc.get("node") != node_name:
                    return False
                continue
            # No slice inventory published (legacy/simplified clusters):
            # any node can host an unallocated claim.
            if self.slices:
                for cls, count in self._requests(claim):
                    needed[cls] = needed.get(cls, 0) + count
                    if needed[cls] > len(self._free_devices(node_name,
                                                            cls)):
                        return False
        return True

    def on_allocate(self, task) -> None:
        for name in self.task_claims(task):
            claim = self.claims.get(name)
            if claim is None:
                continue
            assumed = self.assumed.get(name)
            if assumed is not None:
                # Shareable claim: another task already holds the
                # assumption; this task becomes a co-user.
                assumed["users"].add(task.uid)
                continue
            if self._allocation(claim) is not None:
                continue
            devices: list = []
            for cls, count in self._requests(claim):
                devices += self._free_devices(task.node_name, cls)[:count]
            self.assumed[name] = {"node": task.node_name,
                                  "devices": devices,
                                  "users": {task.uid}}
            self.devices_taken.setdefault(task.node_name,
                                          set()).update(devices)

    def on_deallocate(self, task, prev_status) -> None:
        for name in self.task_claims(task):
            assumed = self.assumed.get(name)
            if assumed is None:
                continue
            assumed["users"].discard(task.uid)
            # The assumption (and its devices) release only once NO
            # placed task still rides the claim.
            if not assumed["users"]:
                del self.assumed[name]
                self.devices_taken.get(assumed["node"],
                                       set()).difference_update(
                    assumed["devices"])

    def mutate_bind_request(self, task, bind_request) -> None:
        claims = self.task_claims(task)
        if not claims:
            return
        bind_request.resource_claims = list(claims)
        # Structured allocations ride the BindRequest
        # (ResourceClaimAllocations, bindrequest_types.go).
        def alloc_of(name):
            assumed = self.assumed.get(name)
            if assumed is not None:
                return {"node": assumed["node"],
                        "devices": list(assumed["devices"])}
            return (self._allocation(self.claims.get(name, {}))
                    or {"node": task.node_name, "devices": []})

        bind_request.claim_allocations = [
            {"name": name, **alloc_of(name)} for name in claims]
