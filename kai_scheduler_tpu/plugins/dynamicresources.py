"""Dynamic Resource Allocation (DRA) plugin.

Mirrors pkg/scheduler/plugins/dynamicresources/dynamicresources.go:59-87:
tasks may reference ResourceClaims; a claim must be allocatable (or already
allocated to a compatible node) for the task to schedule, claims are
assumed/unassumed in-session as statements allocate/rollback, and the
claim names ride the BindRequest so the binder can write the allocation
status at bind time (allocateResourceClaim :252).

Claims live in the info model as ``task.resource_claims``: a list of claim
names resolved against ``cluster.resource_claims`` ({name: {"device_class",
"allocated", "node"}}).
"""

from __future__ import annotations

from .base import Plugin, register_plugin


@register_plugin("dynamicresources")
class DynamicResourcesPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        self.ssn = ssn
        self.claims = getattr(ssn.cluster, "resource_claims", {})
        if not self.claims:
            return
        # In-session assumed allocations: claim -> node (rolled back with
        # the statement via the deallocate handler).
        self.assumed: dict[str, str] = {}
        ssn.allocate_handlers.append(self.on_allocate)
        ssn.deallocate_handlers.append(self.on_deallocate)
        ssn.bind_request_mutators = getattr(ssn, "bind_request_mutators",
                                            [])
        ssn.bind_request_mutators.append(self.mutate_bind_request)

    def task_claims(self, task) -> list:
        return getattr(task, "resource_claims", []) or []

    def claims_schedulable(self, task, node_name: str) -> bool:
        """PrePredicate analog: every referenced claim must be free, already
        assumed on this node, or bound to this node."""
        for name in self.task_claims(task):
            claim = self.claims.get(name)
            if claim is None:
                return False
            node = claim.get("node") or self.assumed.get(name)
            if node and node != node_name:
                return False
        return True

    def on_allocate(self, task) -> None:
        for name in self.task_claims(task):
            self.assumed[name] = task.node_name

    def on_deallocate(self, task, prev_status) -> None:
        for name in self.task_claims(task):
            self.assumed.pop(name, None)

    def mutate_bind_request(self, task, bind_request) -> None:
        claims = self.task_claims(task)
        if claims:
            bind_request.resource_claims = list(claims)
