"""snapshot plugin: dump full cluster + config state for offline replay.

Mirrors pkg/scheduler/plugins/snapshot/snapshot.go:79 (/get-snapshot): the
serialized state feeds tools/snapshot_tool.py, which replays a production
cycle deterministically.
"""

from __future__ import annotations

import json

import numpy as np

from .base import Plugin, register_plugin


def _jsonable(x):
    if isinstance(x, np.ndarray):
        return x.tolist()
    return x


@register_plugin("snapshot")
class SnapshotPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        ssn.snapshot_dump = lambda: dump_cluster(ssn)


def dump_cluster(ssn) -> dict:
    cluster = ssn.cluster
    return {
        "now": cluster.now,
        "config": {
            "actions": list(ssn.config.actions),
            "plugins": [p.name for p in ssn.config.plugins],
            "k_value": ssn.config.k_value,
        },
        "nodes": [{
            "name": n.name,
            "allocatable": n.allocatable.tolist(),
            "labels": n.labels,
            "taints": sorted(n.taints),
            "gpu_memory_per_device": n.gpu_memory_per_device,
            "max_pods": n.max_pods,
        } for n in cluster.nodes.values()],
        "queues": [{
            "uid": q.uid, "name": q.name, "parent": q.parent,
            "priority": q.priority, "creation_ts": q.creation_ts,
            "deserved": q.quota.deserved.tolist(),
            "limit": q.quota.limit.tolist(),
            "over_quota_weight": q.quota.over_quota_weight.tolist(),
        } for q in cluster.queues.values()],
        "podgroups": [{
            "uid": pg.uid, "name": pg.name, "namespace": pg.namespace,
            "queue": pg.queue_id, "priority": pg.priority,
            "preemptible": pg.preemptible,
            "pod_sets": [{"name": ps.name,
                          "min_available": ps.min_available}
                         for ps in pg.pod_sets.values()],
            "pods": [{
                "uid": t.uid, "name": t.name, "status": t.status.name,
                "node": t.node_name, "subgroup": t.subgroup,
                "req": t.req_vec().tolist(),
                "node_selector": t.node_selector,
                "tolerations": sorted(t.tolerations),
            } for t in pg.pods.values()],
        } for pg in cluster.podgroups.values()],
    }


def dump_json(ssn) -> str:
    return json.dumps(dump_cluster(ssn), default=_jsonable, indent=1)
