"""Topology-aware scheduling (TAS) plugin — placeholder registration.

The full domain-tree kernel (per-level segment aggregation of allocatable
capacity, domain filtering and bin-pack ordering over node-sets, mirroring
pkg/scheduler/plugins/topology/) lands with ops/topology.py; this module
keeps the plugin name registered so configs carry it from day one.
"""

from __future__ import annotations

from .base import Plugin, register_plugin


@register_plugin("topology")
class TopologyPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        if not ssn.cluster.topologies:
            return
        try:
            from ..ops.topology import TopologySession
        except ImportError:  # kernel not built yet: degrade to no-op
            return
        self._topo = TopologySession(ssn)
        ssn.subset_nodes_fns.append(self._topo.subset_nodes)
        ssn.extra_score_fns.append(self._topo.extra_scores)
