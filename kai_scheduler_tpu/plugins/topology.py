"""Topology-aware scheduling (TAS) plugin.

Registers ops/topology.TopologySession's domain filtering as the
SubsetNodes extension point and its preferred-level boosts as score terms
(mirroring pkg/scheduler/plugins/topology/topology_plugin.go:43-50).
"""

from __future__ import annotations

from .base import Plugin, register_plugin


@register_plugin("topology")
class TopologyPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        if not ssn.cluster.topologies:
            return
        from ..ops.topology import TopologySession
        self._topo = TopologySession(ssn)
        ssn.subset_nodes_fns.append(self._topo.subset_nodes)
        ssn.extra_score_fns.append(self._topo.extra_scores)
        # Rank-aware gang placement (ops/rankplace.py): reorder an
        # interchangeable chunk's placements so consecutive MPI ranks
        # land topology-adjacent.  A pure post-fill permutation — the
        # fill plan's node multiset (and thus every capacity/feasibility
        # verdict) is untouched.
        ssn.rank_assign_fns.append(self._topo.assign_ranks)
