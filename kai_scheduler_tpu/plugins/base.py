"""Plugin protocol + registry.

Mirrors pkg/scheduler/framework/plugins.go:31-63 (RegisterPluginBuilder) and
the plugin interface (framework/interface.go:40-55): a plugin registers
callbacks into the Session at open; tensor-term plugins additionally
contribute score arrays to the device kernel.
"""

from __future__ import annotations

from typing import Callable

_REGISTRY: dict[str, Callable] = {}


class Plugin:
    name = "plugin"

    def __init__(self, args: dict | None = None):
        self.args = args or {}

    def on_session_open(self, ssn) -> None:  # pragma: no cover - interface
        pass

    def on_session_close(self, ssn) -> None:
        pass


def register_plugin(name: str):
    def deco(cls):
        cls.name = name
        _REGISTRY[name] = cls
        return cls
    return deco


def build_plugins(config, api=None) -> list[Plugin]:
    """Instantiate the configured plugins, honoring feature gates: a
    plugin whose gate is off is not registered at all (the reference's
    DRA gate decides whether the upstream DRA machinery participates —
    pkg/common/feature_gates/feature_gates.go:22)."""
    gates = None
    gates_fn = getattr(config, "gates", None)
    if gates_fn is not None:
        gates = gates_fn(api)
    plugins = []
    for pc in config.plugins:
        builder = _REGISTRY.get(pc.name)
        if builder is None:
            continue
        if gates is not None and not gates.plugin_enabled(pc.name):
            continue
        plugins.append(builder(pc.args))
    return plugins


def registered_plugins() -> list[str]:
    return sorted(_REGISTRY)
