"""Placement-policy plugins whose scoring lives in the device kernel.

nodeplacement (binpack/spread strategy selection), nodeavailability,
resourcetype, gpupack/gpuspread/gpusharingorder, nominatednode, predicates.
The score formulas themselves are in ops/scoring.py — these plugins
configure which terms apply, mirroring how the reference's plugins register
NodeOrderFns that the session sums (session_plugins.go).
"""

from __future__ import annotations

import numpy as np

from ..ops.scoring import BINPACK, NOMINATED_NODE, SPREAD
from ..api.pod_status import PodStatus
from .base import Plugin, register_plugin


@register_plugin("nodeplacement")
class NodePlacementPlugin(Plugin):
    """Strategy per resource type from args (nodeplacement.go:39-44)."""

    def on_session_open(self, ssn) -> None:
        gpu = self.args.get("gpu", ssn.config.gpu_placement_strategy)
        cpu = self.args.get("cpu", ssn.config.cpu_placement_strategy)
        ssn.gpu_strategy = SPREAD if gpu == "spread" else BINPACK
        ssn.cpu_strategy = SPREAD if cpu == "spread" else BINPACK


@register_plugin("nodeavailability")
class NodeAvailabilityPlugin(Plugin):
    """Availability term is always-on in the kernel; this plugin exists for
    config parity (nodeavailability.go)."""


@register_plugin("resourcetype")
class ResourceTypePlugin(Plugin):
    """Resource-type matching term is always-on in the kernel."""


# "predicates" is registered by plugins/predicates_ext.py: selector/taint/
# capacity masks live in the kernel; the plugin carries the upstream
# adapters (NodePorts, VolumeBinding filter, ConfigMap,
# MaxNodePoolResources).


@register_plugin("gpupack")
class GpuPackPlugin(Plugin):
    """Prefer packing fractions onto the fullest shared GPU
    (gpupack plugin); this is NodeInfo.find_gpu_groups_for_task's default."""

    def on_session_open(self, ssn) -> None:
        ssn.gpu_group_pack = True


@register_plugin("gpuspread")
class GpuSpreadPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        ssn.gpu_group_pack = False


@register_plugin("gpusharingorder")
class GpuSharingOrderPlugin(Plugin):
    """Prefer already-shared devices over minting new sharing groups —
    encoded in find_gpu_groups_for_task (existing groups first)."""


@register_plugin("nominatednode")
class NominatedNodePlugin(Plugin):
    """Sticky boost: a pipelined task re-scored in a later cycle strongly
    prefers the node it was nominated to (nominatednode plugin)."""

    def on_session_open(self, ssn) -> None:
        self.ssn = ssn
        ssn.extra_score_fns.append(self.extra_scores)

    def extra_scores(self, tasks):
        n = self.ssn.node_idle.shape[0]
        out = None
        for i, t in enumerate(tasks):
            nominated = t.nominated_node or (
                t.node_name if t.status == PodStatus.PIPELINED else "")
            if nominated:
                idx = self.ssn.node_index(nominated)
                if idx >= 0:
                    if out is None:
                        out = np.zeros((len(tasks), n))
                    out[i, idx] = NOMINATED_NODE
        return out
