"""minruntime plugin: protect young victims from preemption/reclaim.

Mirrors pkg/scheduler/plugins/minruntime/minruntime.go:78-205: victims whose
gangs started running less than the queue's (or global default) minimum
runtime ago are filtered out of preempt/reclaim victim sets, and scenarios
containing protected victims are rejected.
"""

from __future__ import annotations

from .base import Plugin, register_plugin


@register_plugin("minruntime")
class MinRuntimePlugin(Plugin):
    def __init__(self, args=None):
        super().__init__(args)
        self.default_preempt = float(self.args.get("preempt_min_runtime", 0)
                                     if args else 0)
        self.default_reclaim = float(self.args.get("reclaim_min_runtime", 0)
                                     if args else 0)

    def on_session_open(self, ssn) -> None:
        self.ssn = ssn
        ssn.preempt_victim_filters.append(self.filter_preempt)
        ssn.reclaim_victim_filters.append(self.filter_reclaim)
        ssn.preempt_scenario_validators.append(self.validate_preempt)
        ssn.reclaim_scenario_validators.append(self.validate_reclaim)

    def _protected(self, job, min_runtime: float) -> bool:
        if min_runtime <= 0 or job.last_start_ts is None:
            return False
        return (self.ssn.cluster.now - job.last_start_ts) < min_runtime

    def _min_runtime(self, job, kind: str) -> float:
        q = self.ssn.cluster.queues.get(job.queue_id)
        # Queue-level override wins over the shard default (:148-205).
        while q is not None:
            val = (q.preempt_min_runtime if kind == "preempt"
                   else q.reclaim_min_runtime)
            if val is not None:
                return val
            q = self.ssn.cluster.queues.get(q.parent) if q.parent else None
        return self.default_preempt if kind == "preempt" \
            else self.default_reclaim

    def filter_preempt(self, preemptor, victims):
        return [v for v in victims
                if not self._protected(v, self._min_runtime(v, "preempt"))]

    def filter_reclaim(self, reclaimer, victims):
        return [v for v in victims
                if not self._protected(v, self._min_runtime(v, "reclaim"))]

    def validate_preempt(self, scenario) -> bool:
        return all(not self._protected(v, self._min_runtime(v, "preempt"))
                   for v, _ in scenario.victims)

    def validate_reclaim(self, scenario) -> bool:
        return all(not self._protected(v, self._min_runtime(v, "reclaim"))
                   for v, _ in scenario.victims)
