"""Ordering plugins: priority, elastic, task order, subgroup order,
kubeflow/ray master-first.

Mirrors pkg/scheduler/plugins/{priority,elastic,taskorder,subgrouporder,
kubeflow,ray}: pure comparator registrations — all ordering policy stays
host-side; only placement mechanics run on device.
"""

from __future__ import annotations

import re

from .base import Plugin, register_plugin


@register_plugin("priority")
class PriorityPlugin(Plugin):
    """Jobs with higher PriorityClass value first (priority/priority.go)."""

    def on_session_open(self, ssn) -> None:
        ssn.add_job_order_fn(self.job_order, lambda job: -job.priority)

    @staticmethod
    def job_order(l, r) -> int:
        if l.priority != r.priority:
            return -1 if l.priority > r.priority else 1
        return 0


def _below_min(job) -> int:
    return 0 if job.num_active_used() < sum(
        ps.min_available for ps in job.pod_sets.values()) else 1


@register_plugin("elastic")
class ElasticPlugin(Plugin):
    """Jobs below minAvailable schedule before jobs at/above it
    (elastic/elastic.go:21-25) — grow starved gangs first."""

    def on_session_open(self, ssn) -> None:
        ssn.add_job_order_fn(self.job_order, _below_min)

    @staticmethod
    def job_order(l, r) -> int:
        l_below = l.num_active_used() < sum(
            ps.min_available for ps in l.pod_sets.values())
        r_below = r.num_active_used() < sum(
            ps.min_available for ps in r.pod_sets.values())
        if l_below and not r_below:
            return -1
        if r_below and not l_below:
            return 1
        return 0


_TRAILING_INT = re.compile(r"(\d+)$")


def pod_index_key(task) -> tuple:
    """Order tasks by trailing ordinal (worker-0, worker-1, ...) for
    deterministic gang placement (taskorder plugin)."""
    m = _TRAILING_INT.search(task.name)
    return (0, int(m.group(1))) if m else (1, 0)


@register_plugin("taskorder")
class TaskOrderPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        ssn.task_order_fns.append(pod_index_key)


@register_plugin("subgrouporder")
class SubGroupOrderPlugin(Plugin):
    """Deterministic podset ordering within a gang (subgrouporder plugin)."""

    def on_session_open(self, ssn) -> None:
        ssn.pod_set_order_fns.append(lambda ps: ps.name)


MASTER_HINTS = ("master", "launcher", "head", "ps", "chief", "driver")


def master_first_key(task) -> int:
    """Framework-aware ordering: coordinator pods before workers
    (kubeflow/kubeflow.go, ray/ray.go)."""
    name = f"{task.subgroup} {task.name}".lower()
    return 0 if any(h in name for h in MASTER_HINTS) else 1


@register_plugin("kubeflow")
class KubeflowPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        ssn.task_order_fns.insert(0, master_first_key)


@register_plugin("ray")
class RayPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        if master_first_key not in ssn.task_order_fns:
            ssn.task_order_fns.insert(0, master_first_key)
