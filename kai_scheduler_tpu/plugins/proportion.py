"""Proportion plugin: hierarchical DRF fairness, quota gates, reclaim rules.

The policy heart of the scheduler, mirroring
pkg/scheduler/plugins/proportion/ (proportion.go:99-124 registrations):

- builds per-queue attributes (deserved/limit/over-quota-weight, allocated,
  allocated-non-preemptible, request, historical usage) with parent-chain
  roll-ups (proportion.go:378-401);
- computes hierarchical fair share on-device via ops.fairshare;
- registers the DRF queue-order comparator (queue_order/queue_order.go:19),
  queue capacity gates (capacity_policy/), reclaim legality
  (reclaimable/reclaimable.go + strategies.go), and allocate/deallocate
  event handlers that keep queue shares current as statements mutate state.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..api import resources as rs
from ..api.podgroup_info import PodGroupInfo
from ..framework.session import SchedulableResult
from ..ops import fairshare as fsops
from .base import Plugin, register_plugin

UNLIMITED = rs.UNLIMITED
NO_FAIR_SHARE_DRF_MULTIPLIER = 1000.0


@dataclass
class QueueAttributes:
    uid: str
    name: str
    parent: str | None
    children: list
    priority: int
    creation_ts: float
    deserved: np.ndarray
    limit: np.ndarray
    over_quota_weight: np.ndarray
    allocated: np.ndarray = field(default_factory=rs.zeros)
    allocated_non_preemptible: np.ndarray = field(default_factory=rs.zeros)
    request: np.ndarray = field(default_factory=rs.zeros)
    usage: np.ndarray = field(default_factory=rs.zeros)
    fair_share: np.ndarray = field(default_factory=rs.zeros)
    # Mutation stamp + sort-key memo: with a large backlog of identical
    # pending jobs, the DRF queue key is recomputed per requeue although
    # nothing changed — version bumps on every _walk touch.
    version: int = 0
    sort_key_cache: tuple | None = None

    def clone(self) -> "QueueAttributes":
        return QueueAttributes(
            self.uid, self.name, self.parent, list(self.children),
            self.priority, self.creation_ts, self.deserved.copy(),
            self.limit.copy(), self.over_quota_weight.copy(),
            self.allocated.copy(), self.allocated_non_preemptible.copy(),
            self.request.copy(), self.usage.copy(), self.fair_share.copy())

    def allocatable_share(self) -> np.ndarray:
        """GetAllocatableShare (resource_share.go:52-62)."""
        base = np.maximum(self.deserved, self.fair_share)
        capped = np.where(self.limit == UNLIMITED, base,
                          np.minimum(self.limit, base))
        return np.where(self.deserved == UNLIMITED, self.limit, capped)

    def dominant_share(self, total: np.ndarray,
                       extra_allocated: np.ndarray | None = None) -> float:
        """GetDominantResourceShare (queue_resource_share.go:142-162)."""
        allocated = self.allocated.copy()
        if extra_allocated is not None:
            allocated = allocated + extra_allocated
        alloc_share = self.allocatable_share()
        alloc_share = np.where(alloc_share == UNLIMITED, total, alloc_share)
        vals = np.where(alloc_share > 0,
                        allocated / np.where(alloc_share > 0, alloc_share, 1),
                        allocated * NO_FAIR_SHARE_DRF_MULTIPLIER)
        return float(vals.max())


def _less(a: np.ndarray, b: np.ndarray) -> bool:
    """ResourceQuantities.Less: strictly less in EVERY dimension
    (resource_quantities.go:50-57) — one equal dimension (e.g. cpu fair
    share == cpu allocated) already defeats it.  The over-utilized queue
    check rides on this exact semantic."""
    b_eff = np.where(b == UNLIMITED, np.inf, b)
    a_eff = np.where(a == UNLIMITED, np.inf, a)
    return bool(np.all(a_eff < b_eff - 1e-9))


def _less_equal(a: np.ndarray, b: np.ndarray) -> bool:
    b_eff = np.where(b == UNLIMITED, np.inf, b)
    a_eff = np.where(a == UNLIMITED, np.inf, a)
    return bool(np.all(a_eff <= b_eff + 1e-9))


@register_plugin("proportion")
class ProportionPlugin(Plugin):
    def __init__(self, args=None):
        super().__init__(args)
        self.queues: dict[str, QueueAttributes] = {}
        self.total = rs.zeros()
        self.saturation_multiplier = 1.0
        self.min_gpu_mem = 0.0

    # -- session wiring ----------------------------------------------------
    def on_session_open(self, ssn) -> None:
        self.ssn = ssn
        self.total = ssn.cluster.total_allocatable()
        self.saturation_multiplier = ssn.config.saturation_multiplier
        self._build_queue_attributes(ssn)
        self._set_fair_share(ssn)
        ssn.queue_order_fns.append(self.queue_order_fn)
        ssn.queue_key_fn = self.queue_sort_key
        ssn.over_capacity_fns.append(self.is_job_over_queue_capacity)
        ssn.non_preemptible_over_quota_fns.append(
            self.is_non_preemptible_over_quota)
        ssn.can_reclaim_fns.append(self.can_reclaim_resources)
        ssn.reclaim_scenario_validators.append(self.reclaim_scenario_valid)
        ssn.allocate_handlers.append(self.on_allocate)
        ssn.deallocate_handlers.append(self.on_deallocate)
        ssn.job_solution_start_fns.append(self.on_job_solution_start)
        self.sim_queues: dict[str, QueueAttributes] = self.queues
        ssn.proportion = self  # expose queue attrs to actions/metrics

    def on_job_solution_start(self) -> None:
        """Clone queue state before a scenario simulation so the validator
        reads pre-eviction attributes (proportion.go:131-136)."""
        self.sim_queues = {qid: q.clone() for qid, q in self.queues.items()}

    @staticmethod
    def _qattr_store(cache) -> dict | None:
        """Persistent per-cache QueueAttributes store (the churn-ring
        queue-axis trim): attribute objects and gauge last-writes
        survive across cycles so a 10k-queue fleet rebuilds only DIRTY
        queues and re-emits only CHANGED gauges.  Single-writer: the
        scheduler thread inside on_session_open (same contract as the
        cache's mirrors)."""
        store = getattr(cache, "_proportion_store", None)
        if store is None:
            store = {"attrs": {}, "sig": {}, "usage_sig": {},
                     "gauges": {}}
            try:
                cache._proportion_store = store
            except Exception:
                return None
        return store

    @staticmethod
    def _queue_sig(q) -> tuple:
        """Value signature of everything a QueueAttributes derives from
        the QueueInfo: any change (spec edit, re-parent, children drift,
        even an in-place quota tweak the per-cycle copy would hide from
        identity checks) rebuilds the entry."""
        return (q.parent, q.priority, q.creation_ts, tuple(q.children),
                q.quota.deserved.tobytes(), q.quota.limit.tobytes(),
                q.quota.over_quota_weight.tobytes())

    def _build_queue_attributes(self, ssn) -> None:
        from ..utils.metrics import METRICS
        cluster = ssn.cluster
        # Usage staleness (docs/DEGRADATION.md): a stale snapshot means
        # the recorder/scraper stopped feeding data — the documented
        # degraded mode IGNORES usage (zeros, the no-penalty division)
        # and counts the cycle, instead of trusting decayed-to-zero
        # values as authoritative history.
        usage_stale = bool(getattr(ssn.queue_usage, "stale", False))
        if usage_stale:
            METRICS.inc("usage_stale_cycles_total")
        store = self._qattr_store(ssn.cache)
        attrs = store["attrs"] if store is not None else {}
        sigs = store["sig"] if store is not None else {}
        usage_sigs = store["usage_sig"] if store is not None else {}
        reused = rebuilt = 0
        self.queues = {}
        for qid, q in cluster.queues.items():
            usage_row = None if usage_stale \
                else ssn.queue_usage.get(qid)
            sig = self._queue_sig(q)
            at = attrs.get(qid)
            if at is not None and sigs.get(qid) == sig:
                # Clean queue: reset the per-cycle accumulators in
                # place instead of re-deriving the whole object (the
                # 10k-queue churn ring re-paid construction + three
                # array conversions per queue per cycle).
                at.allocated[:] = 0.0
                at.allocated_non_preemptible[:] = 0.0
                at.request[:] = 0.0
                usage_sig = None if usage_row is None \
                    else usage_row.tobytes()
                if usage_sigs.get(qid) != usage_sig:
                    at.usage = (rs.zeros() if usage_row is None
                                else np.asarray(usage_row, float))
                    usage_sigs[qid] = usage_sig
                # The reset is a state change: stale DRF sort keys must
                # not survive it.
                at.version += 1
                reused += 1
            else:
                at = QueueAttributes(
                    uid=qid, name=q.name, parent=q.parent,
                    children=list(q.children), priority=q.priority,
                    creation_ts=q.creation_ts,
                    deserved=np.asarray(q.quota.deserved, float).copy(),
                    limit=np.asarray(q.quota.limit, float).copy(),
                    over_quota_weight=np.asarray(
                        q.quota.over_quota_weight, float).copy(),
                    usage=(rs.zeros() if usage_row is None
                           else np.asarray(usage_row, float)))
                attrs[qid] = at
                sigs[qid] = sig
                usage_sigs[qid] = None if usage_row is None \
                    else usage_row.tobytes()
                rebuilt += 1
            self.queues[qid] = at
        if store is not None and len(attrs) > len(self.queues):
            for gone in set(attrs) - set(self.queues):
                attrs.pop(gone, None)
                sigs.pop(gone, None)
                usage_sigs.pop(gone, None)
                store["gauges"].pop(gone, None)
        if reused:
            METRICS.inc("queue_attrs_reused_total", reused)
        if rebuilt:
            METRICS.inc("queue_attrs_rebuilt_total", rebuilt)
        # Roll allocated/non-preemptible/request up the parent chain
        # (proportion.go:347-401).  Pending gpu-memory requests are charged
        # gpu_memory / MinNodeGPUMemory devices rather than a whole GPU.
        min_gpu_mem = self.min_gpu_mem = cluster.min_node_gpu_memory()
        batch = getattr(cluster, "columnar_batch", None)
        if batch is not None and self._roll_up_columnar(batch):
            return
        for pg in cluster.podgroups.values():
            if pg.queue_id not in self.queues:
                continue
            for t in pg.pods.values():
                # Placed tasks resolve gpu-memory against their node's
                # per-GPU memory; pending ones against the cluster minimum.
                req = t.req_vec(cluster.task_gpu_memory_context(t)
                                if t.node_name else min_gpu_mem)
                if t.is_active_allocated():
                    self._walk(pg.queue_id, "allocated", req)
                    self._walk(pg.queue_id, "request", req)
                    if not pg.is_preemptible():
                        self._walk(pg.queue_id, "allocated_non_preemptible",
                                   req)
                elif t.status.name == "PENDING":
                    # Only Pending (not Gated) demand counts toward Request
                    # (proportion.go updateQueuesCurrentResourceUsage) —
                    # unschedulable gated pods must not inflate fair share.
                    self._walk(pg.queue_id, "request", req)

    def _roll_up_columnar(self, batch: dict) -> bool:
        """Vectorized ``_walk`` roll-up over the columnar snapshot batch
        (DESIGN §11): per pod, its request is added to its queue and
        every ancestor — expressed as one ``np.add.at`` per attribute
        over ancestor-expanded indices in pod order, which applies the
        exact same sequential float folds as the per-pod walk (each
        accumulator starts at zero and receives its adds in the same
        order), so fair-share inputs are bit-identical.  The batch only
        exists on simple-pod columnar snapshots, where every request
        vector is context-free (no gpu-memory/MIG resolution)."""
        q_uids = batch["q_uids"]
        if list(self.queues) != q_uids:
            return False  # queue view drifted: take the object walk
        qidx = np.asarray(batch["qidx"])
        reqs = batch["reqs"]
        n_q = len(q_uids)
        if n_q == 0 or qidx.size == 0:
            return True
        anc = batch.get("queue_anc")
        if anc is None or anc.shape[0] != n_q:
            # The batch's ancestor table (built with the queue columns,
            # aligned with q_uids) is the one source of chains; without
            # it — or on a shape drift — the object walk is the truth.
            return False
        depth = anc.shape[1]
        valid = qidx >= 0
        exp = anc[np.where(valid, qidx, 0)]       # [P, D]
        exp[~valid] = -1
        flat = exp.reshape(-1)
        ok = flat >= 0
        rep = np.repeat(reqs, depth, axis=0)
        active = np.asarray(batch["active"])
        pending = np.asarray(batch["pending"])
        non_preempt = active & ~np.asarray(batch["preemptible"])
        versions = np.zeros(n_q, np.int64)
        for attr, mask in (("allocated", active),
                           ("request", active | pending),
                           ("allocated_non_preemptible", non_preempt)):
            m = np.repeat(mask, depth) & ok
            if not m.any():
                continue
            mat = np.zeros((n_q, reqs.shape[1]))
            np.add.at(mat, flat[m], rep[m])
            counts = np.bincount(flat[m], minlength=n_q)
            versions += counts
            for i in np.nonzero(counts)[0].tolist():
                # Accumulators start at rs.zeros(), so the add.at fold
                # (same adds, same order, from zero) IS the walked value.
                setattr(self.queues[q_uids[i]], attr, mat[i])
        for i in np.nonzero(versions)[0].tolist():
            self.queues[q_uids[i]].version += int(versions[i])
        return True

    def _walk(self, qid: str, attr: str, req: np.ndarray) -> None:
        q = self.queues.get(qid)
        while q is not None:
            setattr(q, attr, getattr(q, attr) + req)
            q.version += 1
            q = self.queues.get(q.parent) if q.parent else None

    def _set_fair_share(self, ssn) -> None:
        """Run the hierarchical division kernel (proportion.go:403-440).

        Two paths behind ``config.fused_fairshare`` (bit-identical,
        property-tested):
        - ``forest`` (default): ONE jitted dispatch for the whole queue
          hierarchy, with the host prep (hierarchy build, dense level
          layout, weight-tensor upload) cached across cycles keyed on
          the queue set + weights (ops/fairshare.prepared_forest) — a
          steady 10k-queue forest pays one hash and one dispatch;
        - ``levels``: the pre-forest per-level dispatch loop, kept as
          the A/B baseline and parity reference.
        """
        import time as _time

        from ..utils.metrics import METRICS
        from ..utils.tracing import TRACER
        qids = sorted(self.queues)
        index = {qid: i for i, qid in enumerate(qids)}
        n = len(qids)
        if n == 0:
            return
        parent = np.array([index.get(self.queues[q].parent, -1)
                           if self.queues[q].parent else -1
                           for q in qids], np.int64)
        priority = np.array([self.queues[q].priority for q in qids])
        creation = np.array([self.queues[q].creation_ts for q in qids])
        stack = lambda attr: np.stack(
            [getattr(self.queues[q], attr) for q in qids])
        deserved, limit = stack("deserved"), stack("limit")
        oqw = stack("over_quota_weight")
        request, usage = stack("request"), stack("usage")
        mode = getattr(ssn.config, "fused_fairshare", "forest")
        validate = lambda r: getattr(r, "shape", (0,))[0] >= n
        t_step = _time.perf_counter()
        # Guarded like every other device dispatch: session open must
        # degrade to the CPU fallback on a dead device, not wedge the
        # cycle before its first action.
        with TRACER.span("fairshare", kind="fairshare", queues=n,
                         mode=mode) as sp:
            if mode == "forest":
                # The prep (hierarchy build + layout/weight uploads)
                # lives INSIDE the guarded thunk: its jnp.asarray calls
                # touch the device, and on a guard fallback the thunk
                # re-runs on the CPU backend AFTER fallback_calls
                # bumped — so prepared_forest's GuardWatch drops the
                # dead-device cache entry and rebuilds host-side.
                info: dict = {}

                def forest_thunk():
                    prep = fsops.prepared_forest(
                        parent, priority, creation, qids, deserved,
                        limit, oqw, out_info=info)
                    info["prep"] = prep
                    return fsops.fair_share_forest(
                        self.total, ssn.config.k_value, prep, request,
                        usage)

                fair = ssn.dispatch_kernel(forest_thunk,
                                           label="fair_share",
                                           validate=validate)
                prep = info.get("prep")
                if prep is not None:
                    sp.set(levels=prep.spec.num_levels,
                           bands=prep.spec.num_bands,
                           prep_reused=bool(info.get("reused")))
            else:
                hier = fsops.QueueHierarchy.build(parent, priority,
                                                  creation, qids)
                fair = ssn.dispatch_kernel(
                    lambda: fsops.fair_share_levels(
                        self.total, ssn.config.k_value, hier, deserved,
                        limit, oqw, request, usage),
                    label="fair_share", validate=validate)
        # The fair-share STEP cost (prep + division dispatch, not the
        # attribute stacking above): the number the churn bench's A/B
        # rows and the fleet-budget ceiling gate on.
        ssn.phase_timings["fairshare"] = _time.perf_counter() - t_step
        store = self._qattr_store(ssn.cache)
        gauges = store["gauges"] if store is not None else {}
        deduped = 0
        for qid, i in index.items():
            self.queues[qid].fair_share = fair[i]
            # Queue fair-share/usage gauges (metrics.UpdateQueueFairShare,
            # resource_division.go:44-90).  Deduped against the per-cache
            # last-written values: at 10k queues the three unconditional
            # writes per queue per cycle (label formatting included) were
            # a named churn-ring bottleneck, while steady-state values
            # barely move.
            q = self.queues[qid]
            vals = (float(q.fair_share[rs.RES_GPU]),
                    float(q.fair_share[rs.RES_CPU])
                    / rs.MILLI_CPU_TO_CORES,
                    float(q.allocated[rs.RES_GPU]))
            if gauges.get(qid) == vals:
                deduped += 1
                continue
            gauges[qid] = vals
            METRICS.set_gauge("queue_fair_share_gpu", vals[0], queue=qid)
            METRICS.set_gauge("queue_fair_share_cpu_cores", vals[1],
                              queue=qid)
            METRICS.set_gauge("queue_allocated_gpus", vals[2], queue=qid)
        if deduped:
            METRICS.inc("queue_gauge_writes_deduped_total", deduped)

    # -- event handlers (proportion.go:446-476) ----------------------------
    def on_allocate(self, task) -> None:
        pg = self.ssn.cluster.podgroups.get(task.job_id)
        if pg is None or pg.queue_id not in self.queues:
            return
        # Same gpu-memory normalization as the roll-up, or within-cycle
        # allocated totals drift from the snapshot's accounting.
        req = task.req_vec(self.ssn.cluster.task_gpu_memory_context(task)
                           if task.node_name else self.min_gpu_mem)
        self._walk(pg.queue_id, "allocated", req)
        if not pg.is_preemptible():
            self._walk(pg.queue_id, "allocated_non_preemptible", req)

    def on_deallocate(self, task, prev_status) -> None:
        pg = self.ssn.cluster.podgroups.get(task.job_id)
        if pg is None or pg.queue_id not in self.queues:
            return
        req = -task.req_vec(self.ssn.cluster.task_gpu_memory_context(task)
                            if task.node_name else self.min_gpu_mem)
        self._walk(pg.queue_id, "allocated", req)
        if not pg.is_preemptible():
            self._walk(pg.queue_id, "allocated_non_preemptible", req)

    def queue_sort_key(self, qid: str, peek_job) -> tuple:
        """Scalar key mirroring queue_order_fn's comparator stages, for
        bulk-mode sorting (pairwise numpy comparisons are too slow at
        thousands of queues x jobs).  The allocatable-share tie-break
        collapses to a sum — a total-order approximation of the partial
        order the comparator uses."""
        q = self.queues[qid]
        req = _job_req(peek_job)
        stamp = (q.version, req.tobytes())
        if q.sort_key_cache is not None and q.sort_key_cache[0] == stamp:
            return q.sort_key_cache[1]
        over = _less(q.fair_share, q.allocated)
        with_job = q.allocated + req
        starved = _less_equal(with_job, q.deserved)
        viol = _zero_share_violation(q, with_job)
        share_with_job = q.dominant_share(self.total, req)
        share0 = q.dominant_share(self.total)
        alloc_sum = float(np.where(q.allocatable_share() == UNLIMITED,
                                   self.total,
                                   q.allocatable_share()).sum())
        # +alloc_sum: the smaller allocatable share wins the tie-break,
        # matching queue_order_fn and prioritizeBasedOnAllocatableShare
        # (queue_order.go).
        key = (over, not starved, -q.priority, viol, share_with_job,
               share0, alloc_sum, q.creation_ts)
        q.sort_key_cache = (stamp, key)
        return key

    # -- queue ordering (queue_order/queue_order.go:19-242) ----------------
    def queue_order_fn(self, l: str, r: str, l_job, r_job,
                       l_victims, r_victims) -> int:
        lq, rq = self.queues[l], self.queues[r]

        l_over = _less(lq.fair_share, lq.allocated)
        r_over = _less(rq.fair_share, rq.allocated)
        if not l_over and r_over:
            return -1
        if l_over and not r_over:
            return 1

        l_with_job = lq.allocated + _job_req(l_job)
        r_with_job = rq.allocated + _job_req(r_job)
        l_starved = _less_equal(l_with_job, lq.deserved)
        r_starved = _less_equal(r_with_job, rq.deserved)
        if l_starved and not r_starved:
            return -1
        if r_starved and not l_starved:
            return 1

        if lq.priority != rq.priority:
            return -1 if lq.priority > rq.priority else 1

        l_viol = _zero_share_violation(lq, l_with_job)
        r_viol = _zero_share_violation(rq, r_with_job)
        if l_viol and not r_viol:
            return 1
        if r_viol and not l_viol:
            return -1

        l_share = lq.dominant_share(
            self.total, _job_req(l_job) - _victims_req(l_victims))
        r_share = rq.dominant_share(
            self.total, _job_req(r_job) - _victims_req(r_victims))
        if l_share != r_share:
            return -1 if l_share < r_share else 1

        l_share0 = lq.dominant_share(self.total)
        r_share0 = rq.dominant_share(self.total)
        if l_share0 != r_share0:
            return -1 if l_share0 < r_share0 else 1

        la, ra = lq.allocatable_share(), rq.allocatable_share()
        if _less(la, ra):
            return -1
        if _less(ra, la):
            return 1

        return -1 if lq.creation_ts <= rq.creation_ts else 1

    # -- capacity gates (capacity_policy/) ---------------------------------
    def is_job_over_queue_capacity(self, job: PodGroupInfo,
                                   tasks) -> SchedulableResult:
        res = self._over_limit(job, tasks)
        if not res.schedulable:
            return res
        return self.is_non_preemptible_over_quota(job, tasks)

    def _over_limit(self, job, tasks) -> SchedulableResult:
        req = _tasks_req(tasks)
        q = self.queues.get(job.queue_id)
        while q is not None:
            over = (q.limit != UNLIMITED) & (req > 1e-9) \
                & (q.limit < q.allocated + req - 1e-9)
            if np.any(over):
                i = int(np.argmax(over))
                return SchedulableResult(
                    False, "OverLimit",
                    f"queue {q.name} over limit on "
                    f"{rs.RESOURCE_NAMES[i]}: limit {q.limit[i]:g}, "
                    f"allocated {q.allocated[i]:g}, requested {req[i]:g}")
            q = self.queues.get(q.parent) if q.parent else None
        return SchedulableResult()

    def is_non_preemptible_over_quota(self, job, tasks) -> SchedulableResult:
        if job.is_preemptible():
            return SchedulableResult()
        req = _tasks_req(tasks)
        q = self.queues.get(job.queue_id)
        while q is not None:
            deserved = np.where(q.deserved == UNLIMITED, np.inf, q.deserved)
            if np.any(q.allocated_non_preemptible + req > deserved + 1e-9):
                return SchedulableResult(
                    False, "NonPreemptibleOverQuota",
                    f"non-preemptible job over quota in queue {q.name}")
            q = self.queues.get(q.parent) if q.parent else None
        return SchedulableResult()

    # -- reclaim legality (reclaimable/) -----------------------------------
    def can_reclaim_resources(self, job: PodGroupInfo) -> bool:
        """CanReclaimResources (reclaimable.go:30-55)."""
        q = self.queues.get(job.queue_id)
        if q is None:
            return False
        req = job.tasks_to_allocate_init_resource()
        if not _less_equal(q.allocated + req, q.fair_share):
            return False
        if job.is_preemptible():
            return True
        return _less_equal(q.allocated_non_preemptible + req, q.deserved)

    def reclaim_scenario_valid(self, scenario) -> bool:
        """Reclaimable (reclaimable.go:57-165): simulate post-reclaim
        allocations and check the strategy + sibling saturation rules."""
        queues = self.sim_queues  # pre-simulation clone (OnJobSolutionStart)
        reclaimer = scenario.pending_job
        victims_by_queue: dict[str, list[np.ndarray]] = {}
        for vjob, vtasks in scenario.victims:
            victims_by_queue.setdefault(vjob.queue_id, []).extend(
                t.req_vec() for t in vtasks)

        req = _tasks_req(scenario.pending_tasks)
        remaining: dict[str, np.ndarray] = {}
        involved: dict[str, set] = {}

        def rem(qid):
            if qid not in remaining:
                remaining[qid] = queues[qid].allocated.copy()
            return remaining[qid]

        for qid, reqs in victims_by_queue.items():
            if qid not in queues:
                return False
            reclaimee = queues[qid]
            involved.setdefault(qid, set())
            for v in reqs:
                involved[qid] |= {i for i in range(rs.NUM_RES) if v[i] > 0}
                if not self._fits_reclaim_strategy(req, reclaimer, reclaimee,
                                                   rem(qid)):
                    return False
                # subtract up the chain
                q = reclaimee
                while q is not None:
                    rem(q.uid)
                    remaining[q.uid] = remaining[q.uid] - v
                    involved.setdefault(q.uid, set()).update(involved[qid])
                    q = queues.get(q.parent) if q.parent else None

        # Reclaiming queue chain must stay within boundaries (:134-190).
        involved_reclaimer = {i for i in range(rs.NUM_RES) if req[i] > 0}
        q = queues.get(reclaimer.queue_id)
        while q is not None:
            my_remaining = remaining.get(q.uid, q.allocated.copy()) + req
            for sib_id in list(remaining):
                sib = queues.get(sib_id)
                if sib is None or sib.parent != q.parent or sib.uid == q.uid:
                    continue
                inv = involved.get(sib_id, set()) | involved_reclaimer
                if not self._saturation_lower(
                        inv, my_remaining, q.fair_share,
                        remaining.get(sib_id, sib.allocated), sib.fair_share):
                    return False
            if not reclaimer.is_preemptible():
                deserved = np.where(q.deserved == UNLIMITED, np.inf,
                                    q.deserved)
                if np.any(q.allocated_non_preemptible + req > deserved + 1e-9):
                    return False
            q = queues.get(q.parent) if q.parent else None
        return True

    def _fits_reclaim_strategy(self, reclaimer_req, reclaimer_job, reclaimee,
                               reclaimee_remaining) -> bool:
        """strategies.go: MaintainFairShare OR GuaranteeDeservedQuota."""
        # Maintain fair share: reclaimee currently over its allocatable share.
        if not _less_equal(reclaimee_remaining, reclaimee.allocatable_share()):
            return True
        # Guarantee deserved quota: reclaimer stays under quota, reclaimee
        # above quota in at least one resource.
        rq = self.sim_queues.get(reclaimer_job.queue_id)
        if rq is None:
            return False
        if not _less_equal(rq.allocated + reclaimer_req, rq.deserved):
            return False
        return not _less_equal(reclaimee_remaining, reclaimee.deserved)

    def _saturation_lower(self, involved, rec_alloc, rec_fair, sib_alloc,
                          sib_fair) -> bool:
        """isFairShareSaturationLowerPerResource (reclaimable.go:195-218)."""
        for i in involved:
            rf, sf = rec_fair[i], sib_fair[i]
            if rf == UNLIMITED and sf == UNLIMITED:
                continue
            ratio_rec = _saturation_ratio(rec_alloc[i], rf)
            ratio_sib = _saturation_ratio(sib_alloc[i], sf)
            if (ratio_rec > 1 and sf > 0
                    and ratio_rec * self.saturation_multiplier >= ratio_sib):
                return False
        return True


def _saturation_ratio(allocated: float, fair: float) -> float:
    if fair == 0:
        return np.inf if allocated > 0 else 0.0
    if fair == UNLIMITED:
        return 0.0
    return allocated / fair


def _job_req(job) -> np.ndarray:
    if job is None:
        return rs.zeros()
    return job.tasks_to_allocate_init_resource()


def _victims_req(victims) -> np.ndarray:
    if not victims:
        return rs.zeros()
    total = rs.zeros()
    for vjob in victims:
        for t in vjob.pods.values():
            if t.is_active_allocated():
                total += t.req_vec()
    return total


def _tasks_req(tasks) -> np.ndarray:
    total = rs.zeros()
    for t in tasks:
        total += t.req_vec()
    return total


def _zero_share_violation(q: QueueAttributes,
                          allocated_with_job: np.ndarray) -> bool:
    alloc_share = q.allocatable_share()
    return bool(np.any((alloc_share == 0) & (allocated_with_job > 0)))
