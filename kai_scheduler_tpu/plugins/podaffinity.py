"""Pod-affinity plugin: full inter-pod (anti-)affinity semantics.

Mirrors the reference's use of the upstream InterPodAffinity plugin
(pkg/scheduler/k8s_internal/predicates/predicates.go:70-167 wires
PreFilter/Filter; pkg/scheduler/api/pod_affinity/ keeps per-node pod
affinity metadata) re-designed for the tensor path: every
(selector, topologyKey) term becomes a [N] node mask via domain
occupancy — "does this node's domain contain a pod matching the
selector" — computed once per proposal from the live cluster state.

Semantics covered:
- REQUIRED pod affinity: the task may only go where a matching pod's
  domain is (bootstrap rule: if no pod matches anywhere but the task's
  own labels match the term, any node is allowed — the upstream rule that
  lets the first pod of a self-affine group schedule).
- REQUIRED pod anti-affinity: domains containing matching pods are
  excluded; SYMMETRY is honored — an existing pod's anti-affinity term
  also repels an incoming task that matches it (upstream
  haveAffinityTermsWithPods symmetry).
- Self-gang anti-affinity (every member repels its siblings —
  spread-one-per-domain): enforced inside the allocation kernel via
  ``task_anti_domain`` rows (ops/allocate.py gang_blocked carry), since
  the static mask cannot see in-gang placements.
- PREFERRED terms contribute ±weight-scaled score on matching domains.
- Legacy coarse peers (``pod_affinity_peers`` job-uid lists) keep their
  score behavior.
"""

from __future__ import annotations

import numpy as np

from .base import Plugin, register_plugin

AFFINITY_SCORE = 50.0  # between placement (<=9+10) and availability (100)
HOSTNAME_KEY = "kubernetes.io/hostname"


@register_plugin("podaffinity")
class PodAffinityPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        self.ssn = ssn
        self._domain_cache: dict = {}
        ssn.extra_score_fns.append(self.extra_scores)
        ssn.hard_node_mask_fns.append(self.hard_masks)
        ssn.anti_domain_fns.append(self.anti_domains)

    # -- domain encoding ---------------------------------------------------
    def _domains(self, topology_key: str) -> tuple[np.ndarray, int]:
        """[N] int32 domain id per node for one topology key (-1 = node
        lacks the label).  hostname is every node its own domain.
        Node labels are immutable within a session, so memoized."""
        cached = self._domain_cache.get(topology_key)
        if cached is not None:
            return cached
        cluster = self.ssn.cluster
        names = self.ssn.snapshot.node_names
        n = self.ssn.node_idle.shape[0]
        dom = np.full(n, -1, np.int32)
        ids: dict[str, int] = {}
        for i, name in enumerate(names):
            node = cluster.nodes.get(name)
            if node is None:
                continue
            if topology_key == HOSTNAME_KEY:
                value = name
            else:
                value = node.labels.get(topology_key)
            if value is None:
                continue
            dom[i] = ids.setdefault(value, len(ids))
        self._domain_cache[topology_key] = (dom, len(ids))
        return dom, len(ids)

    def _active_pods(self):
        """(labels, node_idx, anti_terms, job_id) for every active
        allocated pod currently on a snapshot node."""
        out = []
        for pg in self.ssn.cluster.podgroups.values():
            for task in pg.pods.values():
                if not task.is_active_allocated() or not task.node_name:
                    continue
                idx = self.ssn.node_index(task.node_name)
                if idx < 0:
                    continue
                out.append((task.labels, idx,
                            getattr(task, "anti_affinity_terms", []),
                            task.job_id))
        return out

    def _term_mask(self, term, pods, exclude_job: str | None = None
                   ) -> np.ndarray:
        """[N] bool: nodes whose domain holds a pod matching the term."""
        dom, n_dom = self._domains(term.topology_key)
        if n_dom == 0:
            return np.zeros(self.ssn.node_idle.shape[0], bool)
        has = np.zeros(n_dom, bool)
        for labels, idx, _anti, job_id in pods:
            if exclude_job is not None and job_id == exclude_job:
                continue
            if dom[idx] >= 0 and term.matches(labels):
                has[dom[idx]] = True
        mask = np.zeros(dom.shape[0], bool)
        valid = dom >= 0
        mask[valid] = has[dom[valid]]
        return mask

    # -- hard masks (required terms) ---------------------------------------
    def hard_masks(self, tasks):
        needs = any(
            getattr(t, "affinity_terms", None)
            or getattr(t, "anti_affinity_terms", None)
            for t in tasks)
        pods = None
        sym_repellers = None
        if not needs:
            # Symmetry can constrain label-bearing tasks even without own
            # terms — only scan when some existing pod has anti terms.
            pods = self._active_pods()
            if not any(anti for _l, _i, anti, _j in pods):
                return None
        if pods is None:
            pods = self._active_pods()

        n = self.ssn.node_idle.shape[0]
        out = np.ones((len(tasks), n), bool)
        touched = False
        for i, task in enumerate(tasks):
            row = out[i]
            for term in getattr(task, "affinity_terms", []) or []:
                mask = self._term_mask(term, pods)
                if not mask.any() and term.matches(task.labels):
                    continue  # bootstrap: first self-affine pod
                row &= mask
                touched = True
            for term in getattr(task, "anti_affinity_terms", []) or []:
                # Own gang's already-running pods are handled here too
                # (RemovePod on evicted victims keeps them out of `pods`).
                row &= ~self._term_mask(term, pods)
                touched = True
            # Anti-affinity symmetry: existing pods' anti terms repel a
            # matching incoming task from their domains.
            if sym_repellers is None:
                sym_repellers = [
                    (labels, idx, term)
                    for labels, idx, anti, _j in pods for term in anti]
            for _labels, idx, term in sym_repellers:
                if term.matches(task.labels):
                    dom, n_dom = self._domains(term.topology_key)
                    if dom[idx] >= 0:
                        row &= ~(dom == dom[idx])
                        touched = True
        return out if touched else None

    # -- self-gang anti-affinity domains -----------------------------------
    def anti_domains(self, tasks):
        """(dom [T,N], marks [T], avoids [T]) for in-gang REQUIRED
        anti-affinity: a term some chunk member carries that some chunk
        member's labels match.  One term per chunk (multiple distinct
        in-gang terms are rare; the first active one wins — cross-gang
        enforcement still comes from hard_masks)."""
        term = None
        for task in tasks:
            for t2 in getattr(task, "anti_affinity_terms", []) or []:
                if any(t2.matches(x.labels) for x in tasks):
                    term = t2
                    break
            if term is not None:
                break
        if term is None:
            return None
        dom, n_dom = self._domains(term.topology_key)
        if n_dom == 0:
            return None
        doms = np.tile(dom, (len(tasks), 1))
        marks = np.array([term.matches(t.labels) for t in tasks])
        avoids = np.array([
            any(t3.topology_key == term.topology_key
                and t3.selector == term.selector
                and t3.expressions == term.expressions
                for t3 in getattr(t, "anti_affinity_terms", []) or [])
            for t in tasks])
        return doms, marks, avoids

    # -- scores (preferred terms + legacy peers) ---------------------------
    def _job_nodes(self, job_uid: str) -> set:
        pg = self.ssn.cluster.podgroups.get(job_uid)
        if pg is None:
            return set()
        return {self.ssn.node_index(t.node_name)
                for t in pg.pods.values()
                if t.is_active_allocated() and t.node_name}

    def extra_scores(self, tasks):
        n = self.ssn.node_idle.shape[0]
        out = None
        pods = None
        for i, task in enumerate(tasks):
            peers = getattr(task, "pod_affinity_peers", None) or []
            anti = getattr(task, "pod_anti_affinity_peers", None) or []
            pref = getattr(task, "preferred_affinity_terms", None) or []
            pref_anti = getattr(task, "preferred_anti_affinity_terms",
                                None) or []
            if not (peers or anti or pref or pref_anti):
                continue
            if out is None:
                out = np.zeros((len(tasks), n))
            for uid in peers:
                for idx in self._job_nodes(uid):
                    if idx >= 0:
                        out[i, idx] += AFFINITY_SCORE
            for uid in anti:
                for idx in self._job_nodes(uid):
                    if idx >= 0:
                        out[i, idx] -= AFFINITY_SCORE
            if pref or pref_anti:
                if pods is None:
                    pods = self._active_pods()
                for term in pref:
                    out[i] += (term.weight * AFFINITY_SCORE
                               * self._term_mask(term, pods))
                for term in pref_anti:
                    out[i] -= (term.weight * AFFINITY_SCORE
                               * self._term_mask(term, pods))
        return out
