"""Pod-affinity plugin: full inter-pod (anti-)affinity semantics.

Mirrors the reference's use of the upstream InterPodAffinity plugin
(pkg/scheduler/k8s_internal/predicates/predicates.go:70-167 wires
PreFilter/Filter; pkg/scheduler/api/pod_affinity/ keeps per-node pod
affinity metadata) re-designed for the tensor path: every
(selector, topologyKey, namespaces) term becomes a [N] node mask via
domain occupancy — "does this node's domain contain a pod matching the
selector" — computed from the live cluster state and memoized on the
session's mutation tick.

Semantics covered:
- REQUIRED pod affinity: the task may only go where a matching pod's
  domain is.  When the match can come from the task's own gang (a chunk
  member matches the term), enforcement moves INTO the allocation kernel
  (ops/allocate.py task_aff_domain: union-of-marker-domains + the
  upstream first-pod bootstrap rule), since a static mask cannot see
  in-gang placements.
- REQUIRED pod anti-affinity: domains containing matching pods are
  excluded; SYMMETRY is honored — an existing pod's anti-affinity term
  also repels an incoming task that matches it.  In-gang spread runs in
  the kernel (task_anti_domain marker/avoider carry).
- Namespace scoping: a term matches only pods in its resolved namespace
  list (the owner pod's own namespace unless the manifest listed some).
- PREFERRED terms contribute ±weight-scaled score on matching domains.
- Legacy coarse peers (``pod_affinity_peers`` job-uid lists) keep their
  score behavior.
"""

from __future__ import annotations

import numpy as np

from .base import Plugin, register_plugin

AFFINITY_SCORE = 50.0  # between placement (<=9+10) and availability (100)
HOSTNAME_KEY = "kubernetes.io/hostname"


def _same_term(a, b) -> bool:
    return (a.topology_key == b.topology_key and a.selector == b.selector
            and a.expressions == b.expressions
            and a.namespaces == b.namespaces)


@register_plugin("podaffinity")
class PodAffinityPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        self.ssn = ssn
        self._domain_cache: dict = {}
        self._pods_cache = (-1, None)  # (mutation_count, pods)
        ssn.extra_score_fns.append(self.extra_scores)
        ssn.hard_node_mask_fns.append(self.hard_masks)
        ssn.anti_domain_fns.append(self.anti_domains)
        ssn.affinity_domain_fns.append(self.affinity_domains)

    # -- domain encoding ---------------------------------------------------
    def _domains(self, topology_key: str) -> tuple[np.ndarray, int]:
        """[N] int32 domain id per node for one topology key (-1 = node
        lacks the label).  hostname is every node its own domain.
        Node labels are immutable within a session, so memoized."""
        cached = self._domain_cache.get(topology_key)
        if cached is not None:
            return cached
        cluster = self.ssn.cluster
        names = self.ssn.snapshot.node_names
        n = self.ssn.node_idle.shape[0]
        dom = np.full(n, -1, np.int32)
        ids: dict[str, int] = {}
        for i, name in enumerate(names):
            node = cluster.nodes.get(name)
            if node is None:
                continue
            if topology_key == HOSTNAME_KEY:
                value = name
            else:
                value = node.labels.get(topology_key)
            if value is None:
                continue
            dom[i] = ids.setdefault(value, len(ids))
        self._domain_cache[topology_key] = (dom, len(ids))
        return dom, len(ids)

    def _active_pods(self):
        """(labels, namespace, node_idx, anti_terms, job_id) for every
        active allocated pod on a snapshot node; memoized per session
        mutation tick (statements bump it on every state change)."""
        tick = self.ssn.mutation_count
        if self._pods_cache[0] == tick:
            return self._pods_cache[1]
        out = []
        for pg in self.ssn.cluster.podgroups.values():
            for task in pg.pods.values():
                if not task.is_active_allocated() or not task.node_name:
                    continue
                idx = self.ssn.node_index(task.node_name)
                if idx < 0:
                    continue
                out.append((task.labels, task.namespace, idx,
                            getattr(task, "anti_affinity_terms", []),
                            task.job_id))
        self._pods_cache = (tick, out)
        return out

    def _term_mask(self, term, pods) -> np.ndarray:
        """[N] bool: nodes whose domain holds a pod matching the term."""
        dom, n_dom = self._domains(term.topology_key)
        if n_dom == 0:
            return np.zeros(self.ssn.node_idle.shape[0], bool)
        has = np.zeros(n_dom, bool)
        for labels, ns, idx, _anti, _job in pods:
            if dom[idx] >= 0 and term.matches(labels, ns):
                has[dom[idx]] = True
        mask = np.zeros(dom.shape[0], bool)
        valid = dom >= 0
        mask[valid] = has[dom[valid]]
        return mask

    @staticmethod
    def _in_gang(term, tasks) -> bool:
        """Can the term be satisfied/violated by the chunk itself?"""
        return any(term.matches(x.labels, x.namespace) for x in tasks)

    def _selected_in_gang_affinity(self, tasks):
        """The ONE in-gang required-affinity term the kernel enforces
        dynamically (affinity_domains); deterministic first-by-task-order
        so hard_masks and affinity_domains agree on which term that is."""
        for task in tasks:
            for t2 in getattr(task, "affinity_terms", []) or []:
                if self._in_gang(t2, tasks):
                    return t2
        return None

    # -- hard masks (required terms vs EXISTING pods) ----------------------
    def hard_masks(self, tasks):
        has_own_terms = any(
            getattr(t, "affinity_terms", None)
            or getattr(t, "anti_affinity_terms", None)
            for t in tasks)
        pods = self._active_pods()
        if not has_own_terms and not any(
                anti for _l, _n, _i, anti, _j in pods):
            return None

        n = self.ssn.node_idle.shape[0]
        out = np.ones((len(tasks), n), bool)
        touched = False
        sym_repellers = [
            (labels, ns, idx, term)
            for labels, ns, idx, anti, _j in pods for term in anti]
        selected = self._selected_in_gang_affinity(tasks)
        for i, task in enumerate(tasks):
            row = out[i]
            for term in getattr(task, "affinity_terms", []) or []:
                if selected is not None and _same_term(term, selected):
                    continue  # enforced in-kernel via affinity_domains
                if self._in_gang(term, tasks):
                    # A second distinct in-gang term: the kernel carries
                    # only one, so enforce it statically against existing
                    # pods with the first-pod bootstrap escape.
                    mask = self._term_mask(term, pods)
                    if not mask.any() and term.matches(task.labels,
                                                       task.namespace):
                        continue
                    row &= mask
                    touched = True
                    continue
                row &= self._term_mask(term, pods)
                touched = True
            for term in getattr(task, "anti_affinity_terms", []) or []:
                row &= ~self._term_mask(term, pods)
                touched = True
            # Anti-affinity symmetry: existing pods' anti terms repel a
            # matching incoming task from their domains.
            for _labels, _ns, idx, term in sym_repellers:
                if term.matches(task.labels, task.namespace):
                    dom, n_dom = self._domains(term.topology_key)
                    if dom[idx] >= 0:
                        row &= ~(dom == dom[idx])
                        touched = True
        return out if touched else None

    # -- in-gang REQUIRED anti-affinity ------------------------------------
    def anti_domains(self, tasks):
        """(dom [T,N], marks [T], avoids [T]) for a required anti term
        some chunk member carries that some chunk member matches.  One
        term per chunk (multiple distinct in-gang terms are rare; the
        first active one wins — cross-gang enforcement still comes from
        hard_masks)."""
        term = None
        for task in tasks:
            for t2 in getattr(task, "anti_affinity_terms", []) or []:
                if self._in_gang(t2, tasks):
                    term = t2
                    break
            if term is not None:
                break
        if term is None:
            return None
        dom, n_dom = self._domains(term.topology_key)
        if n_dom == 0:
            return None
        doms = np.tile(dom, (len(tasks), 1))
        marks = np.array([term.matches(t.labels, t.namespace)
                          for t in tasks])
        avoids = np.array([
            any(_same_term(t3, term)
                for t3 in getattr(t, "anti_affinity_terms", []) or [])
            for t in tasks])
        return doms, marks, avoids

    # -- in-gang REQUIRED affinity -----------------------------------------
    def affinity_domains(self, tasks):
        """(dom [T,N], marks, avoids, static_ok [T,N], bootstrap [T]) for
        a required affinity term satisfiable by the chunk itself: avoiders
        must share a domain with a matching pod — pre-existing
        (static_ok), placed by this gang (kernel union), or themselves
        under the upstream first-pod bootstrap rule."""
        term = self._selected_in_gang_affinity(tasks)
        if term is None:
            return None
        dom, n_dom = self._domains(term.topology_key)
        if n_dom == 0:
            return None
        pods = self._active_pods()
        static_row = self._term_mask(term, pods)
        t_count = len(tasks)
        doms = np.tile(dom, (t_count, 1))
        static_ok = np.tile(static_row, (t_count, 1))
        marks = np.array([term.matches(t.labels, t.namespace)
                          for t in tasks])
        avoids = np.array([
            any(_same_term(t3, term)
                for t3 in getattr(t, "affinity_terms", []) or [])
            for t in tasks])
        no_existing = not static_row.any()
        bootstrap = marks & avoids & no_existing
        return doms, marks, avoids, static_ok, bootstrap

    # -- scores (preferred terms + legacy peers) ---------------------------
    def _job_nodes(self, job_uid: str) -> set:
        pg = self.ssn.cluster.podgroups.get(job_uid)
        if pg is None:
            return set()
        return {self.ssn.node_index(t.node_name)
                for t in pg.pods.values()
                if t.is_active_allocated() and t.node_name}

    def extra_scores(self, tasks):
        n = self.ssn.node_idle.shape[0]
        out = None
        pods = None
        for i, task in enumerate(tasks):
            peers = getattr(task, "pod_affinity_peers", None) or []
            anti = getattr(task, "pod_anti_affinity_peers", None) or []
            pref = getattr(task, "preferred_affinity_terms", None) or []
            pref_anti = getattr(task, "preferred_anti_affinity_terms",
                                None) or []
            if not (peers or anti or pref or pref_anti):
                continue
            if out is None:
                out = np.zeros((len(tasks), n))
            for uid in peers:
                for idx in self._job_nodes(uid):
                    if idx >= 0:
                        out[i, idx] += AFFINITY_SCORE
            for uid in anti:
                for idx in self._job_nodes(uid):
                    if idx >= 0:
                        out[i, idx] -= AFFINITY_SCORE
            if pref or pref_anti:
                if pods is None:
                    pods = self._active_pods()
                for term in pref:
                    out[i] += (term.weight * AFFINITY_SCORE
                               * self._term_mask(term, pods))
                for term in pref_anti:
                    out[i] -= (term.weight * AFFINITY_SCORE
                               * self._term_mask(term, pods))
        return out
