"""Pod-affinity plugin: inter-pod affinity/anti-affinity score terms.

Mirrors pkg/scheduler/plugins/podaffinity (NodeOrder + predicate assist) at
the granularity the tensor path supports: tasks carry
``pod_affinity_peers`` (job uids to co-locate with) and
``pod_anti_affinity_peers`` (job uids to avoid); nodes hosting peers gain
or lose score.  Gang-internal affinity (co-locating a job's own pods) is
served by bin-pack already.
"""

from __future__ import annotations

import numpy as np

from .base import Plugin, register_plugin

AFFINITY_SCORE = 50.0  # between placement (<=9+10) and availability (100)


@register_plugin("podaffinity")
class PodAffinityPlugin(Plugin):
    def on_session_open(self, ssn) -> None:
        self.ssn = ssn
        ssn.extra_score_fns.append(self.extra_scores)

    def _job_nodes(self, job_uid: str) -> set:
        pg = self.ssn.cluster.podgroups.get(job_uid)
        if pg is None:
            return set()
        return {self.ssn.node_index(t.node_name)
                for t in pg.pods.values()
                if t.is_active_allocated() and t.node_name}

    def extra_scores(self, tasks):
        n = self.ssn.node_idle.shape[0]
        out = None
        for i, task in enumerate(tasks):
            peers = getattr(task, "pod_affinity_peers", None) or []
            anti = getattr(task, "pod_anti_affinity_peers", None) or []
            if not peers and not anti:
                continue
            if out is None:
                out = np.zeros((len(tasks), n))
            for uid in peers:
                for idx in self._job_nodes(uid):
                    if idx >= 0:
                        out[i, idx] += AFFINITY_SCORE
            for uid in anti:
                for idx in self._job_nodes(uid):
                    if idx >= 0:
                        out[i, idx] -= AFFINITY_SCORE
        return out
