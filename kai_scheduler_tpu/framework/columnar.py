"""Columnar host state: struct-of-arrays pod/node manifests.

ROADMAP item 1 taken to its conclusion (Kant's incremental-state
argument, arxiv 2510.01256; Tesserae's persistent placement state, arxiv
2508.04953): the scheduler's host state lives in arrays end to end.  The
device arena (framework/arena.py) already keeps the *packed* snapshot
resident across cycles and patches it by rv-diffed deltas; this module
extends the same pattern UPSTREAM of object construction — the watched
store itself is mirrored as NumPy record batches (one row per pod,
interned-string vocab tables for names), maintained O(delta) from watch
events by ``ClusterCache`` (controllers/cache_builder.py).

``ClusterCache.snapshot()`` uses the columns for an array-native fast
path (DESIGN §11): per-node used/releasing accounting, pod-room counts,
queue aggregates, per-group status counters, and the pack-time
vocabulary scans all become vectorized segment reductions over these
columns — in the SAME accumulation order as the per-object walks they
replace (``np.add.at`` applies updates sequentially in index order, so
float sums stay bit-identical) — and per-cycle ``PodInfo`` views
materialize from row templates (``materialize_row``, the
``PodInfo.from_columns`` seam) via ``PodInfo.instantiate_fast`` instead
of the copy-protocol path.  The fast path is bit-identical to the object
path and falls back to it wholesale on resync / vocab overflow /
feature-bearing pods (``columnar_fallback_total``, gated to zero on the
warm fleet shape by tools/fleet_budget.py).

Single-writer contract: every column mutation happens on the scheduler
thread inside ``ClusterCache._apply_changes`` / ``_refresh_full`` (watch
hooks only enqueue keys; kairace KRC003 checks the annotations below).
"""

from __future__ import annotations

import os

import numpy as np

from ..api import resources as rs
from ..api.pod_status import PodStatus

# Row flags: which parse-time features a pod carries.  SELECTOR/TOLS
# stay on the fast path (the codec handles them; they only disable the
# pack-time vocabulary shortcut); COMPLEX forces the wholesale fallback
# — the pod needs accounting the vectorized path does not model
# (fractional/MIG/gpu-memory devices, sharing groups, storage linking,
# affinity/predicate inventories).
FLAG_SELECTOR = 1
FLAG_TOLERATIONS = 2
FLAG_COMPLEX = 4

# Statuses folded into the vectorized node/queue accounting masks
# (api/pod_status.py): parse-time statuses only — ALLOCATED/PIPELINED
# never appear in a freshly built snapshot.
_ACTIVE_ALLOCATED = int(PodStatus.ALLOCATED | PodStatus.PIPELINED
                        | PodStatus.BINDING | PodStatus.BOUND
                        | PodStatus.RUNNING)
_RELEASING = int(PodStatus.RELEASING)
_PENDING = int(PodStatus.PENDING)


class VocabOverflow(Exception):
    """The interned-string table hit its cap; the store is no longer
    authoritative and the snapshot must take the object path."""


class StringVocab:
    """Interned strings <-> dense int32 ids (the node/group name codec).

    Ids are append-only: a deleted node's id stays reserved so pod rows
    referencing it never dangle.  Overflow (cap hit) latches sticky —
    the owning store reports it and the snapshot falls back wholesale
    until a rebuild resets the vocabulary.
    """

    __slots__ = ("ids", "strs", "cap", "overflowed")

    def __init__(self, cap: int | None = None):
        self.ids: dict[str, int] = {}
        self.strs: list[str] = []
        self.cap = cap or int(os.environ.get(
            "KAI_COLUMNAR_VOCAB_CAP", str(1 << 20)))
        self.overflowed = False

    def intern(self, s: str | None) -> int:
        if not s:
            return -1
        i = self.ids.get(s)
        if i is None:
            if len(self.strs) >= self.cap:
                self.overflowed = True
                raise VocabOverflow(s)
            i = len(self.strs)
            self.ids[s] = i
            self.strs.append(s)
        return i

    def str_of(self, i: int) -> str:
        return self.strs[i] if i >= 0 else ""


class ColumnarPods:
    """Struct-of-arrays pod manifests: one row per (namespace, name) key.

    Columns are parallel NumPy arrays over a capacity-doubling row arena
    with a free list; object columns carry the strings/templates the
    per-cycle views need.  Everything here is derived at watch-delta
    apply time from the SAME parse (`ClusterCache._parse_pod`) the
    object path uses, so a materialized view is the object path's pod.
    """

    # kairace: single-writer=main
    def __init__(self):
        self.node_vocab = StringVocab()
        self.group_vocab = StringVocab()
        self.subgroup_vocab = StringVocab()
        cap = 64
        # -- record batch ------------------------------------------------
        self.status = np.zeros(cap, np.int32)     # PodStatus int value
        self.node_id = np.full(cap, -1, np.int32)   # node_vocab id
        self.group_id = np.full(cap, -1, np.int32)  # group_vocab id
        self.subgroup_id = np.full(cap, -1, np.int32)
        self.req = np.zeros((cap, rs.NUM_RES))    # to_vec(mig_as_gpu=False)
        self.flags = np.zeros(cap, np.int32)
        self.tol_len = np.zeros(cap, np.int32)    # len(tolerations)
        self.rank = np.full(cap, -1, np.int32)    # MPI gang rank, -1 none
        self.uid = np.empty(cap, object)
        self.rv = np.empty(cap, object)           # _sig_rv change signature
        self.tmpl = np.empty(cap, object)         # parsed PodInfo template
        # -- row index ---------------------------------------------------
        self.rows: dict = {}        # (ns, name) -> row
        self.uid_rows: dict = {}    # uid -> row
        self.free: list[int] = []
        self.n_alloc = 0            # high-water row mark
        # Bumped on any membership change (add/remove/row reuse): cached
        # per-snapshot orderings key on it.
        self.version = 0

    # -- maintenance (scheduler thread only) -----------------------------
    def _grow(self) -> None:
        cap = self.status.shape[0] * 2
        for name in ("status", "node_id", "group_id", "subgroup_id",
                     "flags", "tol_len", "rank"):
            old = getattr(self, name)
            fresh = np.full(cap, -1, np.int32) \
                if name.endswith("_id") or name == "rank" \
                else np.zeros(cap, np.int32)
            fresh[:old.shape[0]] = old
            setattr(self, name, fresh)
        req = np.zeros((cap, self.req.shape[1]))
        req[:self.req.shape[0]] = self.req
        self.req = req
        for name in ("uid", "rv", "tmpl"):
            old = getattr(self, name)
            fresh = np.empty(cap, object)
            fresh[:old.shape[0]] = old
            setattr(self, name, fresh)

    @staticmethod
    def _flags_of(tmpl) -> int:
        r = tmpl.res_req
        complex_pod = bool(
            tmpl.affinity_terms or tmpl.anti_affinity_terms
            or tmpl.preferred_affinity_terms
            or tmpl.preferred_anti_affinity_terms
            or tmpl.node_affinity_required or tmpl.node_affinity_preferred
            or tmpl.host_ports or tmpl.required_configmaps
            or tmpl.pvc_names or tmpl.resource_claims
            or tmpl.gpu_group or tmpl.accepted_resource_types is not None
            or r.mig_resources or r.gpu_fraction > 0.0
            or r.gpu_memory_bytes > 0.0)
        return ((FLAG_SELECTOR if tmpl.node_selector else 0)
                | (FLAG_TOLERATIONS if tmpl.tolerations else 0)
                | (FLAG_COMPLEX if complex_pod else 0))

    def upsert(self, key: tuple, rv_sig, tmpl,
               group: str | None) -> str | None:
        """Fold one parsed pod into the columns.  ``tmpl`` is the parse
        result (never mutated after this point); ``group`` is the
        pod-group label (None = ungrouped, excluded from snapshots).
        Returns the uid this key PREVIOUSLY held when it differs (a
        same-name recreate) — the caller must account it as removed."""
        replaced = None
        row = self.rows.get(key)
        if row is None:
            if self.free:
                row = self.free.pop()
            else:
                row = self.n_alloc
                if row >= self.status.shape[0]:
                    self._grow()
                self.n_alloc += 1
            self.rows[key] = row
            self.version += 1
        else:
            old_uid = self.uid[row]
            if old_uid != tmpl.uid:
                self.uid_rows.pop(old_uid, None)
                replaced = old_uid
        self.status[row] = int(tmpl.status)
        self.node_id[row] = self.node_vocab.intern(tmpl.node_name)
        self.group_id[row] = self.group_vocab.intern(group)
        self.subgroup_id[row] = self.subgroup_vocab.intern(tmpl.subgroup)
        self.req[row] = tmpl.res_req.to_vec(mig_as_gpu=False)
        self.flags[row] = self._flags_of(tmpl)
        self.tol_len[row] = len(tmpl.tolerations)
        self.rank[row] = tmpl.rank
        self.uid[row] = tmpl.uid
        self.rv[row] = rv_sig
        self.tmpl[row] = tmpl
        self.uid_rows[tmpl.uid] = row
        return replaced

    def remove(self, key: tuple) -> str | None:
        """Drop one pod's row; returns its uid (for vanish accounting)."""
        row = self.rows.pop(key, None)
        if row is None:
            return None
        uid = self.uid[row]
        self.uid_rows.pop(uid, None)
        self.tmpl[row] = None
        self.uid[row] = None
        self.rv[row] = None
        self.group_id[row] = -1
        self.node_id[row] = -1
        self.status[row] = 0
        self.flags[row] = 0
        self.rank[row] = -1
        self.free.append(row)
        self.version += 1
        return uid

    def clear(self) -> None:
        """Wholesale invalidation (watch resync): rebuilt at the next
        priming refresh, vocabularies reset with it."""
        self.__init__()

    # -- snapshot-side reads ---------------------------------------------
    def row_of_uid(self, uid: str) -> int | None:
        return self.uid_rows.get(uid)

    @property
    def overflowed(self) -> bool:
        return (self.node_vocab.overflowed or self.group_vocab.overflowed
                or self.subgroup_vocab.overflowed)

    def live_rows(self, ordered_keys: list) -> np.ndarray:
        """Row indices in snapshot iteration order (the cache's
        name-sorted pod order)."""
        rows = self.rows
        return np.fromiter((rows[k] for k in ordered_keys), np.int64,
                           count=len(ordered_keys))

    def complex_count(self, rows: np.ndarray) -> int:
        return int(np.count_nonzero(
            self.flags[rows] & FLAG_COMPLEX)) if rows.size else 0

    def projection_digest(self) -> int:
        """Order-insensitive 64-bit digest of the fold-identity
        projection — one (ns, name, uid, rv-signature) tuple per live
        row, XOR-folded (utils/antientropy.py).  The anti-entropy check
        compares this against the SAME projection of the Pod mirror: a
        row the O(delta) fold missed, kept past its delete, or left at
        a stale signature disagrees here, and the snapshot gate
        quarantines the columnar fast path until the store is rebuilt
        and two consecutive digests come back clean.  Non-string
        signatures (stores that stamp no resourceVersion) project as
        None on both sides — they are sentinels unequal by identity,
        not content."""
        from ..utils.antientropy import obj_hash64
        h = 0
        for (ns, name), row in self.rows.items():
            rv = self.rv[row]
            h ^= obj_hash64([ns, name, self.uid[row],
                             rv if isinstance(rv, str) else None])
        return h

    def stats(self) -> dict:
        return {
            "rows": len(self.rows),
            "capacity": int(self.status.shape[0]),
            "node_vocab": len(self.node_vocab.strs),
            "group_vocab": len(self.group_vocab.strs),
            "vocab_overflowed": self.overflowed,
        }


def materialize_row(pods: ColumnarPods, row: int):
    """``PodInfo.from_columns``: the per-cycle object view of one row.

    The row template is the same parse the object path caches, so the
    fast instantiate is field-for-field the object path's pod."""
    return pods.tmpl[row].instantiate_fast()
