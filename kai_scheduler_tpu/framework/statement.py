"""Transactional statement: the in-session operation log.

Mirrors pkg/scheduler/framework/statement.go: every mutation an action makes
(Allocate/Pipeline/Evict) goes through here so preemption scenarios can
checkpoint (:44), roll back (:48), convert allocations to pipelines (:483),
and finally commit side effects (:536 — bind requests and evictions).

The statement is also the single writer of the session's dense node-state
mirrors: each op updates both the host object graph (NodeInfo/PodGroupInfo)
and the packed numpy arrays the device kernels consume, keeping the two
views exactly in sync.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING

from ..api.cluster_info import BindRequest
from ..api.pod_info import PodInfo
from ..api.pod_status import PodStatus

if TYPE_CHECKING:
    from .session import Session


@dataclass
class _Op:
    kind: str                      # allocate | pipeline | evict
    task: PodInfo
    node_name: str = ""
    prev_status: PodStatus = PodStatus.PENDING
    prev_node: str = ""
    prev_gpu_group: str = ""
    gpu_group: str = ""
    # Fast-path ops: accounting went through the native table directly
    # (one batched call), so undo must route there too.
    native_req: object = None      # np.ndarray when native-applied
    node_idx: int = -1


class Statement:
    def __init__(self, session: "Session"):
        self.session = session
        self.ops: list[_Op] = []
        self.committed = False
        # Deferred-sync mode for bulk application: node-state mirror
        # pushes collapse to one sync per touched node instead of one per
        # task (the dominant host cost at 100k-node scale).
        self._defer: "set | None" = None

    def _sync(self, node) -> None:
        if self._defer is not None:
            self._defer.add(node.name)
        else:
            self.session.sync_node(node)

    def apply_bulk(self, placements) -> None:
        """Apply [(task, node_name, pipelined)] with one mirror sync per
        touched node.  Semantically identical to per-task allocate()/
        pipeline() — the op log and handlers still fire per task, so
        checkpoint/rollback and queue accounting are unchanged.

        Plain tasks (no fractional GPU, no MIG, no storage claims) take
        the NATIVE batch path: per-task Python does only the object-graph
        bookkeeping (op log, job status, handlers, pod_infos) while the
        resource accounting for the whole batch lands in ONE
        statestore.cpp call, with NodeInfo.used/releasing views updated
        for free (framework/session.py row binding)."""
        # Callers pass generators; materialize once so the native attempt
        # and the generic fallback iterate the same complete list (a
        # partially-consumed generator would silently drop placements and
        # break gang atomicity).
        placements = list(placements)
        if self._apply_bulk_native(placements):
            return
        self._defer = set()
        try:
            for task, node_name, pipelined in placements:
                if pipelined:
                    self.pipeline(task, node_name)
                else:
                    self.allocate(task, node_name)
        finally:
            touched, self._defer = self._defer, None
            for name in touched:
                self.session.sync_node(self.session.cluster.nodes[name])

    def _apply_bulk_native(self, placements) -> bool:
        """Try the batched native path; False -> caller uses the generic
        per-task path (non-plain task, no native table, unbound views)."""
        import numpy as np
        ssn = self.session
        table = getattr(ssn, "_native", None)
        if table is None or not placements:
            return False
        nodes = ssn.cluster.nodes
        rows = []
        for task, node_name, pipelined in placements:
            node = nodes[node_name]
            if (task.is_fractional or task.res_req.mig_resources
                    or task.needs_storage_scheduling() or node.idx < 0
                    or node.idx >= table.n_nodes
                    or node.used.base is None):  # view not bound
                return False
            rows.append((task, node, pipelined))
        n = len(rows)
        idx = np.empty(n, np.int64)
        reqs = np.empty((n, table.n_res), np.float64)
        statuses = np.empty(n, np.int32)
        ops = []
        for i, (task, node, pipelined) in enumerate(rows):
            status = (PodStatus.PIPELINED if pipelined
                      else PodStatus.ALLOCATED)
            req = task.res_req.to_vec(node.gpu_memory_per_device,
                                      mig_as_gpu=False)
            op = _Op("pipeline" if pipelined else "allocate", task,
                     node.name, prev_status=task.status,
                     prev_node=task.node_name,
                     prev_gpu_group=task.gpu_group,
                     native_req=req, node_idx=node.idx)
            task.node_name = node.name
            task.gpu_group = ""
            job = ssn.cluster.podgroups.get(task.job_id)
            if job is not None:
                job.update_task_status(task, status)
            else:
                task.status = status
            node.pod_infos[task.uid] = task
            ssn.fire_allocate_handlers(task)
            ops.append(op)
            idx[i] = node.idx
            reqs[i] = req
            statuses[i] = 2 if pipelined else 0
        table.add_tasks(idx, reqs, statuses)
        ssn.cluster.invalidate_aggregates()
        ssn.mutation_count += 1
        ssn._dirty_rows.update(int(i) for i in idx)
        self.ops.extend(ops)
        return True

    # -- mutations ---------------------------------------------------------
    def allocate(self, task: PodInfo, node_name: str,
                 gpu_group: str = "") -> None:
        """Assign the task to a node on idle resources (statement.go:297)."""
        self._place(task, node_name, PodStatus.ALLOCATED, gpu_group,
                    "allocate")

    def pipeline(self, task: PodInfo, node_name: str,
                 gpu_group: str = "") -> None:
        """Assign the task onto releasing resources (statement.go:197)."""
        self._place(task, node_name, PodStatus.PIPELINED, gpu_group,
                    "pipeline")

    def _place(self, task: PodInfo, node_name: str, status: PodStatus,
               gpu_group: str, kind: str) -> None:
        node = self.session.cluster.nodes[node_name]
        job = self.session.cluster.podgroups.get(task.job_id)
        op = _Op(kind, task, node_name, prev_status=task.status,
                 prev_node=task.node_name, prev_gpu_group=task.gpu_group,
                 gpu_group=gpu_group)
        task.node_name = node_name
        task.gpu_group = gpu_group
        if job is not None:
            job.update_task_status(task, status)
        else:
            task.status = status
        self.session.cluster.invalidate_aggregates()
        node.add_task(task)
        self._sync(node)
        self.session.fire_allocate_handlers(task)
        self.ops.append(op)

    def evict(self, task: PodInfo) -> None:
        """Mark the task as releasing its resources (statement.go:63)."""
        node = self.session.cluster.nodes.get(task.node_name)
        job = self.session.cluster.podgroups.get(task.job_id)
        op = _Op("evict", task, task.node_name, prev_status=task.status,
                 prev_node=task.node_name, prev_gpu_group=task.gpu_group)
        if node is not None:
            node.remove_task(task)
        if job is not None:
            job.update_task_status(task, PodStatus.RELEASING)
        else:
            task.status = PodStatus.RELEASING
        self.session.cluster.invalidate_aggregates()
        if node is not None:
            node.add_task(task)
            self._sync(node)
        self.session.fire_deallocate_handlers(task, op.prev_status)
        self.ops.append(op)

    # -- undo --------------------------------------------------------------
    def checkpoint(self) -> int:
        return len(self.ops)

    def rollback(self, checkpoint: int = 0) -> None:
        while len(self.ops) > checkpoint:
            self._undo(self.ops.pop())

    _STATUS_CODE = {PodStatus.ALLOCATED: 0, PodStatus.RELEASING: 1,
                    PodStatus.PIPELINED: 2}

    def _undo(self, op: _Op) -> None:
        task = op.task
        node = self.session.cluster.nodes.get(op.node_name)
        job = self.session.cluster.podgroups.get(task.job_id)
        self.session.cluster.invalidate_aggregates()
        if op.native_req is not None and op.kind in ("allocate",
                                                     "pipeline"):
            # Native-applied op: reverse through the table (views keep
            # the NodeInfo graph consistent).
            if node is not None:
                node.pod_infos.pop(task.uid, None)
                self.session._native.remove_task(
                    op.node_idx, op.native_req,
                    self._STATUS_CODE.get(task.status, 0))
            self.session.fire_deallocate_handlers(task, task.status)
            if job is not None:
                job.update_task_status(task, op.prev_status)
            else:
                task.status = op.prev_status
            task.node_name = op.prev_node
            task.gpu_group = op.prev_gpu_group
            self.session.mutation_count += 1
            self.session._dirty_rows.add(op.node_idx)
            return
        if op.kind in ("allocate", "pipeline"):
            if node is not None:
                node.remove_task(task)
            self.session.fire_deallocate_handlers(task, task.status)
            if job is not None:
                job.update_task_status(task, op.prev_status)
            else:
                task.status = op.prev_status
            task.node_name = op.prev_node
            task.gpu_group = op.prev_gpu_group
            if node is not None:
                self.session.sync_node(node)
        elif op.kind == "evict":
            if node is not None:
                node.remove_task(task)
            if job is not None:
                job.update_task_status(task, op.prev_status)
            else:
                task.status = op.prev_status
            task.node_name = op.prev_node
            task.gpu_group = op.prev_gpu_group
            if node is not None:
                node.add_task(task)
                self.session.sync_node(node)
            self.session.fire_allocate_handlers(task)

    # -- pipelining conversion (statement.go:483) --------------------------
    def convert_all_allocated_to_pipelined(self, job_id: str) -> None:
        """Once any gang member pipelines, the whole gang must wait for the
        releasing resources: demote this statement's Allocated ops."""
        for op in self.ops:
            if (op.kind == "allocate" and op.task.job_id == job_id
                    and op.task.status == PodStatus.ALLOCATED):
                node = self.session.cluster.nodes[op.task.node_name]
                job = self.session.cluster.podgroups.get(job_id)
                if op.native_req is not None:
                    self.session._native.remove_task(
                        op.node_idx, op.native_req, 0)
                    if job is not None:
                        job.update_task_status(op.task,
                                               PodStatus.PIPELINED)
                    else:
                        op.task.status = PodStatus.PIPELINED
                    self.session._native.add_task(
                        op.node_idx, op.native_req, 2)
                    self.session.mutation_count += 1
                    self.session._dirty_rows.add(op.node_idx)
                    op.kind = "pipeline"
                    continue
                node.remove_task(op.task)
                if job is not None:
                    job.update_task_status(op.task, PodStatus.PIPELINED)
                else:
                    op.task.status = PodStatus.PIPELINED
                node.add_task(op.task)
                self.session.sync_node(node)
                op.kind = "pipeline"

    # -- commit (statement.go:536) -----------------------------------------
    def commit(self) -> list[BindRequest]:
        """Apply durable side effects: BindRequests for allocations,
        evictions via the cache/evictor.  Pipelined tasks stay in-memory —
        they bind in a later cycle once resources actually free.

        When the cache carries a commit journal (utils/commitlog.py), the
        commit follows WAL discipline: every durable side effect's intent
        is journaled and fsync'd as ONE batch before the first API write
        (a gang's intents are all-or-nothing durable), then each
        completed write appends a buffered ``done`` marker.  A crash
        anywhere in between leaves a journal the restart reconcile pass
        (``ClusterCache.startup_reconcile``) resolves against live API
        state — no phantom reservations, no half-trusted history."""
        from ..utils import commitlog as cl
        from ..utils.deviceguard import control_fault
        from ..utils.tracing import TRACER

        log = getattr(self.session.cache, "commitlog", None)
        epoch_provider = getattr(self.session.cache, "epoch_provider", None)
        epoch = epoch_provider() if epoch_provider is not None else None
        trace_id = getattr(self.session, "trace_id", None)

        # Pre-pass: build every BindRequest (running the plugin mutators,
        # dynamicresources.go:252) and collect the intent records in op
        # order, so the whole gang's intents hit the journal in one
        # fsync'd batch before any API write.
        binds: list[BindRequest] = []
        by_op: dict[int, BindRequest] = {}
        intents: list[dict] = []
        for i, op in enumerate(self.ops):
            if op.kind == "allocate":
                br = BindRequest(
                    pod_uid=op.task.uid, pod_name=op.task.name,
                    namespace=op.task.namespace, node_name=op.node_name,
                    gpu_groups=(op.gpu_group.split(",") if op.gpu_group
                                else []),
                    trace_id=trace_id)
                for mutator in getattr(self.session,
                                       "bind_request_mutators", []):
                    mutator(op.task, br)
                binds.append(br)
                by_op[i] = br
                if log is not None:
                    intents.append(cl.bind_intent(
                        op.task.uid, op.task.name, op.task.namespace,
                        op.node_name, br.gpu_groups, epoch))
            elif op.kind == "evict" and log is not None:
                intents.append(cl.evict_intent(
                    op.task.uid, op.task.name, op.task.namespace, epoch))
        if log is not None and intents:
            # The journal append is the commit's one fsync: a span of its
            # own so a slow disk is distinguishable from slow API writes.
            with TRACER.span("journal", kind="commit",
                             intents=len(intents), epoch=epoch):
                txids = iter(log.append_intents(intents))
        else:
            txids = iter(())
        if log is not None and intents \
                and control_fault("crash-after-journal") is not None:
            # Chaos: die at the worst instant — intents durable, nothing
            # committed.  The restart reconcile pass must make this
            # indistinguishable from "never decided".
            raise cl.SimulatedCrash(
                "crash-after-journal: intents journaled, API commit "
                "not started")
        from ..utils.lifecycle import LIFECYCLE
        for i, op in enumerate(self.ops):
            if op.kind == "allocate":
                # Lifecycle: the cycle committed a placement decision for
                # this pod (stamped before the bind write so the phase
                # order is scheduled <= bind_requested; an aborted commit
                # leaves a scheduled-but-unbound attempt a later cycle
                # completes — monotone either way).
                LIFECYCLE.note(op.task.uid, "scheduled",
                               podgroup=op.task.job_id,
                               node=op.node_name, trace_id=trace_id)
                self.session.cache.bind(op.task, op.node_name, by_op[i])
                if log is not None:
                    log.mark_done(next(txids))
            elif op.kind == "pipeline":
                # Lifecycle: a pipelined decision is still a committed
                # scheduling verdict — the bind follows once resources
                # free, on this same attempt.
                LIFECYCLE.note(op.task.uid, "scheduled",
                               podgroup=op.task.job_id,
                               node=op.node_name, trace_id=trace_id)
                # Pipelined assignments persist in the cache across cycles
                # (Cache.TaskPipelined, cache/interface.go:36-50) so the
                # next snapshot rebuilds them.
                task_pipelined = getattr(self.session.cache,
                                         "task_pipelined", None)
                if task_pipelined is not None:
                    task_pipelined(op.task, op.node_name, op.gpu_group)
            elif op.kind == "evict":
                self.session.cache.evict(op.task)
                if log is not None:
                    log.mark_done(next(txids))
        if log is not None and intents:
            log.flush_buffered()
        self.committed = True
        self.session.cluster.bind_requests.extend(binds)
        return binds

    def discard(self) -> None:
        """Roll everything back (an action abandoning its statement)."""
        self.rollback(0)
