"""Transactional statement: the in-session operation log.

Mirrors pkg/scheduler/framework/statement.go: every mutation an action makes
(Allocate/Pipeline/Evict) goes through here so preemption scenarios can
checkpoint (:44), roll back (:48), convert allocations to pipelines (:483),
and finally commit side effects (:536 — bind requests and evictions).

The statement is also the single writer of the session's dense node-state
mirrors: each op updates both the host object graph (NodeInfo/PodGroupInfo)
and the packed numpy arrays the device kernels consume, keeping the two
views exactly in sync.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import TYPE_CHECKING

from ..api.cluster_info import BindRequest
from ..api.pod_info import PodInfo
from ..api.pod_status import PodStatus

if TYPE_CHECKING:
    from .session import Session


@dataclass
class _Op:
    kind: str                      # allocate | pipeline | evict
    task: PodInfo
    node_name: str = ""
    prev_status: PodStatus = PodStatus.PENDING
    prev_node: str = ""
    prev_gpu_group: str = ""
    gpu_group: str = ""
    # Fast-path ops: accounting went through the native table directly
    # (one batched call), so undo must route there too.
    native_req: object = None      # np.ndarray when native-applied
    node_idx: int = -1


class Statement:
    def __init__(self, session: "Session"):
        self.session = session
        # Op recording and commit both run on the scheduler thread; the
        # commit executor only ever executes the already-frozen closures
        # (DESIGN §10) — it never touches the op list.
        # kairace: single-writer=main
        self.ops: list[_Op] = []
        # kairace: single-writer=main
        self.committed = False
        # Deferred-sync mode for bulk application: node-state mirror
        # pushes collapse to one sync per touched node instead of one per
        # task (the dominant host cost at 100k-node scale).
        self._defer: "set | None" = None

    def _sync(self, node) -> None:
        if self._defer is not None:
            self._defer.add(node.name)
        else:
            self.session.sync_node(node)

    def apply_bulk(self, placements) -> None:
        """Apply [(task, node_name, pipelined)] with one mirror sync per
        touched node.  Semantically identical to per-task allocate()/
        pipeline() — the op log and handlers still fire per task, so
        checkpoint/rollback and queue accounting are unchanged.

        Plain tasks (no fractional GPU, no MIG, no storage claims) take
        the NATIVE batch path: per-task Python does only the object-graph
        bookkeeping (op log, job status, handlers, pod_infos) while the
        resource accounting for the whole batch lands in ONE
        statestore.cpp call, with NodeInfo.used/releasing views updated
        for free (framework/session.py row binding)."""
        # Callers pass generators; materialize once so the native attempt
        # and the generic fallback iterate the same complete list (a
        # partially-consumed generator would silently drop placements and
        # break gang atomicity).
        placements = list(placements)
        if self._apply_bulk_native(placements):
            return
        self._defer = set()
        try:
            for task, node_name, pipelined in placements:
                if pipelined:
                    self.pipeline(task, node_name)
                else:
                    self.allocate(task, node_name)
        finally:
            touched, self._defer = self._defer, None
            for name in touched:
                self.session.sync_node(self.session.cluster.nodes[name])

    def _apply_bulk_native(self, placements) -> bool:
        """Try the batched native path; False -> caller uses the generic
        per-task path (non-plain task, no native table, unbound views)."""
        import numpy as np
        ssn = self.session
        table = getattr(ssn, "_native", None)
        if table is None or not placements:
            return False
        nodes = ssn.cluster.nodes
        rows = []
        for task, node_name, pipelined in placements:
            node = nodes[node_name]
            if (task.is_fractional or task.res_req.mig_resources
                    or task.needs_storage_scheduling() or node.idx < 0
                    or node.idx >= table.n_nodes
                    or node.used.base is None):  # view not bound
                return False
            rows.append((task, node, pipelined))
        n = len(rows)
        idx = np.empty(n, np.int64)
        reqs = np.empty((n, table.n_res), np.float64)
        statuses = np.empty(n, np.int32)
        ops = []
        for i, (task, node, pipelined) in enumerate(rows):
            status = (PodStatus.PIPELINED if pipelined
                      else PodStatus.ALLOCATED)
            req = task.res_req.to_vec(node.gpu_memory_per_device,
                                      mig_as_gpu=False)
            op = _Op("pipeline" if pipelined else "allocate", task,
                     node.name, prev_status=task.status,
                     prev_node=task.node_name,
                     prev_gpu_group=task.gpu_group,
                     native_req=req, node_idx=node.idx)
            task.node_name = node.name
            task.gpu_group = ""
            job = ssn.cluster.podgroups.get(task.job_id)
            if job is not None:
                job.update_task_status(task, status)
            else:
                task.status = status
            node.pod_infos[task.uid] = task
            ssn.fire_allocate_handlers(task)
            ops.append(op)
            idx[i] = node.idx
            reqs[i] = req
            statuses[i] = 2 if pipelined else 0
        table.add_tasks(idx, reqs, statuses)
        ssn.cluster.invalidate_aggregates()
        ssn.mutation_count += 1
        ssn._dirty_rows.update(int(i) for i in idx)
        self.ops.extend(ops)
        return True

    # -- mutations ---------------------------------------------------------
    def allocate(self, task: PodInfo, node_name: str,
                 gpu_group: str = "") -> None:
        """Assign the task to a node on idle resources (statement.go:297)."""
        self._place(task, node_name, PodStatus.ALLOCATED, gpu_group,
                    "allocate")

    def pipeline(self, task: PodInfo, node_name: str,
                 gpu_group: str = "") -> None:
        """Assign the task onto releasing resources (statement.go:197)."""
        self._place(task, node_name, PodStatus.PIPELINED, gpu_group,
                    "pipeline")

    def _place(self, task: PodInfo, node_name: str, status: PodStatus,
               gpu_group: str, kind: str) -> None:
        node = self.session.cluster.nodes[node_name]
        job = self.session.cluster.podgroups.get(task.job_id)
        op = _Op(kind, task, node_name, prev_status=task.status,
                 prev_node=task.node_name, prev_gpu_group=task.gpu_group,
                 gpu_group=gpu_group)
        task.node_name = node_name
        task.gpu_group = gpu_group
        if job is not None:
            job.update_task_status(task, status)
        else:
            task.status = status
        self.session.cluster.invalidate_aggregates()
        node.add_task(task)
        self._sync(node)
        self.session.fire_allocate_handlers(task)
        self.ops.append(op)

    def evict(self, task: PodInfo) -> None:
        """Mark the task as releasing its resources (statement.go:63)."""
        node = self.session.cluster.nodes.get(task.node_name)
        job = self.session.cluster.podgroups.get(task.job_id)
        op = _Op("evict", task, task.node_name, prev_status=task.status,
                 prev_node=task.node_name, prev_gpu_group=task.gpu_group)
        if node is not None:
            node.remove_task(task)
        if job is not None:
            job.update_task_status(task, PodStatus.RELEASING)
        else:
            task.status = PodStatus.RELEASING
        self.session.cluster.invalidate_aggregates()
        if node is not None:
            node.add_task(task)
            self._sync(node)
        self.session.fire_deallocate_handlers(task, op.prev_status)
        self.ops.append(op)

    # -- undo --------------------------------------------------------------
    def checkpoint(self) -> int:
        return len(self.ops)

    def rollback(self, checkpoint: int = 0) -> None:
        while len(self.ops) > checkpoint:
            self._undo(self.ops.pop())

    _STATUS_CODE = {PodStatus.ALLOCATED: 0, PodStatus.RELEASING: 1,
                    PodStatus.PIPELINED: 2}

    def _undo(self, op: _Op) -> None:
        task = op.task
        node = self.session.cluster.nodes.get(op.node_name)
        job = self.session.cluster.podgroups.get(task.job_id)
        self.session.cluster.invalidate_aggregates()
        if op.native_req is not None and op.kind in ("allocate",
                                                     "pipeline"):
            # Native-applied op: reverse through the table (views keep
            # the NodeInfo graph consistent).
            if node is not None:
                node.pod_infos.pop(task.uid, None)
                self.session._native.remove_task(
                    op.node_idx, op.native_req,
                    self._STATUS_CODE.get(task.status, 0))
            self.session.fire_deallocate_handlers(task, task.status)
            if job is not None:
                job.update_task_status(task, op.prev_status)
            else:
                task.status = op.prev_status
            task.node_name = op.prev_node
            task.gpu_group = op.prev_gpu_group
            self.session.mutation_count += 1
            self.session._dirty_rows.add(op.node_idx)
            return
        if op.kind in ("allocate", "pipeline"):
            if node is not None:
                node.remove_task(task)
            self.session.fire_deallocate_handlers(task, task.status)
            if job is not None:
                job.update_task_status(task, op.prev_status)
            else:
                task.status = op.prev_status
            task.node_name = op.prev_node
            task.gpu_group = op.prev_gpu_group
            if node is not None:
                self.session.sync_node(node)
        elif op.kind == "evict":
            if node is not None:
                node.remove_task(task)
            if job is not None:
                job.update_task_status(task, op.prev_status)
            else:
                task.status = op.prev_status
            task.node_name = op.prev_node
            task.gpu_group = op.prev_gpu_group
            if node is not None:
                node.add_task(task)
                self.session.sync_node(node)
            self.session.fire_allocate_handlers(task)

    # -- pipelining conversion (statement.go:483) --------------------------
    def convert_all_allocated_to_pipelined(self, job_id: str) -> None:
        """Once any gang member pipelines, the whole gang must wait for the
        releasing resources: demote this statement's Allocated ops."""
        for op in self.ops:
            if (op.kind == "allocate" and op.task.job_id == job_id
                    and op.task.status == PodStatus.ALLOCATED):
                node = self.session.cluster.nodes[op.task.node_name]
                job = self.session.cluster.podgroups.get(job_id)
                if op.native_req is not None:
                    self.session._native.remove_task(
                        op.node_idx, op.native_req, 0)
                    if job is not None:
                        job.update_task_status(op.task,
                                               PodStatus.PIPELINED)
                    else:
                        op.task.status = PodStatus.PIPELINED
                    self.session._native.add_task(
                        op.node_idx, op.native_req, 2)
                    self.session.mutation_count += 1
                    self.session._dirty_rows.add(op.node_idx)
                    op.kind = "pipeline"
                    continue
                node.remove_task(op.task)
                if job is not None:
                    job.update_task_status(op.task, PodStatus.PIPELINED)
                else:
                    op.task.status = PodStatus.PIPELINED
                node.add_task(op.task)
                self.session.sync_node(node)
                op.kind = "pipeline"

    # -- commit (statement.go:536) -----------------------------------------
    def commit(self) -> list[BindRequest]:
        """Apply durable side effects: BindRequests for allocations,
        evictions via the cache/evictor.  Pipelined tasks stay in-memory —
        they bind in a later cycle once resources actually free.

        When the cache carries a commit journal (utils/commitlog.py), the
        commit follows WAL discipline: every durable side effect's intent
        is journaled and fsync'd as ONE batch before the first API write
        (a gang's intents are all-or-nothing durable), then each
        completed write appends a buffered ``done`` marker.  A crash
        anywhere in between leaves a journal the restart reconcile pass
        (``ClusterCache.startup_reconcile``) resolves against live API
        state — no phantom reservations, no half-trusted history.

        OVERLAPPED mode (DESIGN §10): when the session carries a commit
        executor (``Session.commit_executor``, armed by the pipelined
        operator cycle) and the cache supports the speculative view, the
        decision is registered speculatively on THIS thread — the next
        snapshot already sees it — and the whole durable write batch
        (journal fsync + API writes) is enqueued to the commit-executor
        thread, overlapping the next cycle's host prep and device work.
        Write order, journal discipline, and fencing are preserved: the
        executor is single-threaded FIFO and every write still carries
        the leadership epoch read at write time."""
        from ..utils.lifecycle import LIFECYCLE

        cache = self.session.cache
        log = getattr(cache, "commitlog", None)
        epoch_provider = getattr(cache, "epoch_provider", None)
        epoch = epoch_provider() if epoch_provider is not None else None
        trace_id = getattr(self.session, "trace_id", None)

        binds, by_op, intents, intent_ops = self._build_commit_batch(
            log, epoch, trace_id)

        # Lifecycle 'scheduled' stamps happen at DECISION time on the
        # cycle thread, for allocate and pipeline ops alike (stamped
        # before any bind write so the phase order stays monotone:
        # scheduled <= bind_requested, whichever thread writes).
        for op in self.ops:
            if op.kind in ("allocate", "pipeline"):
                LIFECYCLE.note(op.task.uid, "scheduled",
                               podgroup=op.task.job_id,
                               node=op.node_name, trace_id=trace_id)
            if op.kind == "pipeline":
                # Pipelined assignments persist in the cache across
                # cycles (Cache.TaskPipelined, cache/interface.go:36-50)
                # so the next snapshot rebuilds them.  In-memory: always
                # on the decision thread.
                task_pipelined = getattr(cache, "task_pipelined", None)
                if task_pipelined is not None:
                    task_pipelined(op.task, op.node_name, op.gpu_group)

        executor = getattr(self.session, "commit_executor", None)
        if executor is not None and hasattr(cache, "speculate"):
            self._commit_overlapped(executor, cache, log, binds, by_op,
                                    intents, intent_ops, epoch)
        else:
            self._commit_serial(cache, log, binds, by_op, intents,
                                intent_ops, epoch)
        self.committed = True
        self.session.cluster.bind_requests.extend(binds)
        return binds

    def _build_commit_batch(self, log, epoch, trace_id):
        """Pre-pass: build every BindRequest (running the plugin
        mutators, dynamicresources.go:252) and collect the intent
        records in op order, so the whole gang's intents hit the journal
        in one fsync'd batch before any API write.  ``intent_ops`` maps
        each intent to its op index — done markers stay correct however
        the writes are batched downstream."""
        from ..utils import commitlog as cl

        binds: list[BindRequest] = []
        by_op: dict[int, BindRequest] = {}
        intents: list[dict] = []
        intent_ops: list[int] = []
        for i, op in enumerate(self.ops):
            if op.kind == "allocate":
                br = BindRequest(
                    pod_uid=op.task.uid, pod_name=op.task.name,
                    namespace=op.task.namespace, node_name=op.node_name,
                    gpu_groups=(op.gpu_group.split(",") if op.gpu_group
                                else []),
                    trace_id=trace_id)
                for mutator in getattr(self.session,
                                       "bind_request_mutators", []):
                    mutator(op.task, br)
                binds.append(br)
                by_op[i] = br
                if log is not None:
                    intents.append(cl.bind_intent(
                        op.task.uid, op.task.name, op.task.namespace,
                        op.node_name, br.gpu_groups, epoch))
                    intent_ops.append(i)
            elif op.kind == "evict" and log is not None:
                intents.append(cl.evict_intent(
                    op.task.uid, op.task.name, op.task.namespace, epoch))
                intent_ops.append(i)
        return binds, by_op, intents, intent_ops

    def _journal_batch(self, log, intents, intent_ops, epoch) -> dict:
        """Append + fsync the intent batch; returns op index -> txid.
        Raises the chaos ``SimulatedCrash`` AFTER the fsync — intents
        durable, nothing committed — on whichever thread runs the batch
        (the restart reconcile pass must cope either way)."""
        from ..utils import commitlog as cl
        from ..utils.deviceguard import control_fault
        from ..utils.tracing import TRACER

        if log is None or not intents:
            return {}
        # The journal append is the commit's one fsync: a span of its
        # own so a slow disk is distinguishable from slow API writes.
        with TRACER.span("journal", kind="commit",
                         intents=len(intents), epoch=epoch):
            txids = log.append_intents(intents)
        txid_of = dict(zip(intent_ops, txids))
        if control_fault("crash-after-journal") is not None:
            # Chaos: die at the worst instant — intents durable, nothing
            # committed.  The restart reconcile pass must make this
            # indistinguishable from "never decided".
            raise cl.SimulatedCrash(
                "crash-after-journal: intents journaled, API commit "
                "not started")
        return txid_of

    def _apply_writes(self, cache, log, by_op, txid_of, ops, intents,
                      landed=None) -> None:
        """The ONE durable-write loop both commit paths share: apply
        every side effect in op order — evictions batch through
        ``cache.evict_many`` and binds through ``cache.bind_many`` (one
        flush per gang batch each) when the cache supports them; a bind
        wave flushes the pending evict batch first and an evict flushes
        the pending bind wave, so writes land in op order ACROSS kinds
        (a crash between them must never leave a bind durable against
        capacity whose victim was not evicted).  ``landed`` (overlapped
        mode) collects the uid of every write that reached the store —
        the fenced-rollback path rolls back exactly the rest.  Per-item
        bulk outcomes: a failed item fails that item only — the rest of
        the wave lands, its journal entries mark done — and the first
        failure (Fenced first) re-raises after the wave settles, exactly
        like ``evict_many``."""
        from ..controllers.kubeapi import Fenced

        evict_batch: list[tuple[int, object]] = []
        bind_batch: list[tuple[int, object]] = []
        evict_many = getattr(cache, "evict_many", None)
        bind_many = getattr(cache, "bind_many", None)

        def note_landed(uid) -> None:
            if landed is not None:
                landed.add(uid)

        def flush_evicts() -> None:
            if not evict_batch:
                return
            evict_many([task for _i, task in evict_batch])
            for i, task in evict_batch:
                note_landed(task.uid)
                if i in txid_of:
                    log.mark_done(txid_of[i])
            evict_batch.clear()

        def flush_binds() -> None:
            if not bind_batch:
                return
            outcomes = bind_many([(op.task, op.node_name, by_op[i])
                                  for i, op in bind_batch])
            failures: list = []
            for (i, op), out in zip(bind_batch, outcomes):
                if out.get("ok"):
                    note_landed(op.task.uid)
                    if i in txid_of:
                        log.mark_done(txid_of[i])
                else:
                    failures.append(out.get("error"))
            bind_batch.clear()
            for exc in failures:
                if isinstance(exc, Fenced):
                    raise exc
            if failures:
                raise failures[0]

        for i, op in enumerate(ops):
            if op.kind == "allocate":
                flush_evicts()
                if bind_many is not None:
                    bind_batch.append((i, op))
                else:
                    cache.bind(op.task, op.node_name, by_op[i])
                    note_landed(op.task.uid)
                    if i in txid_of:
                        log.mark_done(txid_of[i])
            elif op.kind == "evict":
                flush_binds()
                if evict_many is not None:
                    evict_batch.append((i, op.task))
                else:
                    cache.evict(op.task)
                    note_landed(op.task.uid)
                    if i in txid_of:
                        log.mark_done(txid_of[i])
        flush_binds()
        flush_evicts()
        if log is not None and intents:
            log.flush_buffered()

    def _commit_serial(self, cache, log, binds, by_op, intents,
                       intent_ops, epoch) -> None:
        """The synchronous write path (no executor): journal, then the
        shared write loop."""
        txid_of = self._journal_batch(log, intents, intent_ops, epoch)
        self._apply_writes(cache, log, by_op, txid_of, self.ops, intents)

    def _commit_overlapped(self, executor, cache, log, binds, by_op,
                           intents, intent_ops, epoch) -> None:
        """Register the decision speculatively and hand the durable
        writes to the commit executor.  On a fencing rejection mid-batch
        the UN-LANDED decisions' speculative view rolls back and the
        executor poisons (the operator then drains the pipeline to the
        serial path); landed writes stand, exactly like a serial
        mid-commit depose."""
        import time as _time

        from ..utils.tracing import TRACER

        trace_id = getattr(self.session, "trace_id", None)
        spec_entries = []
        for i, op in enumerate(self.ops):
            if op.kind == "allocate":
                spec_entries.append((op.task.uid, "bind", op.node_name))
            elif op.kind == "evict":
                spec_entries.append((op.task.uid, "evict", ""))
        handle = cache.speculate(spec_entries)
        ops = list(self.ops)

        def run_batch() -> None:
            t_batch = _time.perf_counter()
            # Ambient wire context: the batch's bulk bind/status waves
            # run on the executor thread after the cycle trace was
            # finalized — arm the trace id so every wave's request
            # still stamps X-Kai-Trace and its client span attaches to
            # the owning cycle (the wire observatory's commit leg).
            TRACER.set_wire_context(trace_id)
            try:
                self._run_overlapped_batch(executor, cache, log, by_op,
                                           intents, intent_ops, epoch,
                                           handle, ops)
            finally:
                TRACER.clear_wire_context()
                # The commit stage finishes after its cycle's trace was
                # finalized: attach the span post-hoc so /debug/trace
                # still shows where cycle N's commit budget went.
                TRACER.attach_async_span(
                    trace_id, "stage:commit", "commit_async",
                    _time.perf_counter() - t_batch,
                    ops=len(ops), binds=len(binds))

        executor.submit(
            run_batch, label="commit-batch",
            # Dropped by poisoning (an earlier batch hit the fence or a
            # crash): these decisions will never be durable — roll back
            # their speculative view at fault time.
            on_skip=lambda: cache.rollback_speculation(
                handle, "commit skipped: pipeline poisoned"))

    def _run_overlapped_batch(self, executor, cache, log, by_op, intents,
                              intent_ops, epoch, handle, ops) -> None:
        from ..controllers.kubeapi import Fenced
        from ..utils import commitlog as cl
        from ..utils.metrics import METRICS

        txid_of = {}
        try:
            txid_of = self._journal_batch(log, intents, intent_ops,
                                          epoch)
        except cl.SimulatedCrash:
            # Crash semantics: this scheduler is dead — nothing else it
            # queued may commit.  The speculation stays (a real crash
            # takes the whole process); the test/restart path reconciles
            # from the journal.
            executor.poison("crash-after-journal")
            raise
        landed: set = set()
        try:
            self._apply_writes(cache, log, by_op, txid_of, ops, intents,
                               landed=landed)
        except Fenced as exc:
            # Deposed mid-overlap: the store rejected the write.
            # Decisions whose writes never landed roll back their
            # speculative view — the rightful leader re-schedules those
            # pods; landed writes stand (they carried a then-valid
            # epoch).
            remaining = {uid: seq for uid, seq in handle.items()
                         if uid not in landed}
            cache.rollback_speculation(remaining, f"fenced: {exc}")
            METRICS.inc("pipeline_fenced_commits_total")
            executor.poison(f"fenced commit: {exc}")

    def discard(self) -> None:
        """Roll everything back (an action abandoning its statement)."""
        self.rollback(0)
