"""Persistent device arena: cross-cycle snapshot and device-state residency.

The reference scheduler rebuilds its world every cycle; our port inherited
that at the host<->device seam — every new ``Session`` re-uploaded the
whole packed snapshot (including the immutable ``allocatable``/``labels``/
``taints`` tensors) and any single touched node row re-shipped all of
``idle``+``releasing``+``room``.  On the tunneled-TPU deployment every one
of those transfers pays the ~70-100ms RTT floor, which makes re-shipping
unchanged state the dominant steady-state cost (BENCH_r05 host_pipeline).

The arena keeps cluster state resident across cycles and updates it by
deltas instead of rebuilding:

- **incremental snapshot pack** — the previous cycle's packed numpy arrays
  persist here (``pack``); ``ClusterCache.snapshot`` feeds the arena the
  dirty set it derives from the watch-event stream (resourceVersion
  diffing of the watched store, resync boundaries invalidating wholesale),
  and ``api/snapshot.pack_incremental`` patches only the changed node rows
  — bit-identical to a from-scratch ``pack()`` (tests/test_snapshot_delta.py
  proves it property-style);
- **static device residency** — ``allocatable``/``labels``/``taints``
  upload once per arena *generation* (bumped only on a full rebuild) and
  are reused across Session objects;
- **scatter-based state updates** — ``idle``/``releasing``/``room`` stay
  resident on device; dirty rows (tracked by ``Session.sync_node`` and the
  cross-cycle snapshot diff) are applied by the jitted
  ``ops/arena.apply_deltas_kernel`` scatter (``[K]`` rows + ``[K,R]``
  values) instead of a full ``[N,R]`` re-upload.

Degraded-mode contract: every device-touching step dispatches through the
device guard (``Session.dispatch_kernel`` — watchdog, breaker, CPU
fallback), and the arena drops its device caches on breaker/CPU-fallback
transitions so degraded mode never reads a stale TPU buffer
(docs/DEGRADATION.md).  The arena is single-writer: only the scheduler
thread that runs the cycle touches it, like the Session mirrors it backs.

Observability: ``snapshot_delta``/``arena_scatter`` tracing spans,
``snapshot_delta_ratio`` gauge, ``arena_full_rebuild_total`` /
``arena_scatter_rows`` / ``arena_device_invalidation_total`` counters, and
pack stats on ``GET /debug/cycles`` (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import time

import numpy as np

from ..api.snapshot import SnapshotTensors, pack, pack_incremental
from ..utils.logging import LOG
from ..utils.metrics import METRICS
from ..utils.tracing import TRACER
from .session import _next_pow2

# Above this fraction of dirty rows a scatter loses to one contiguous
# upload (scatter pays gather+kernel overhead per row; a bulk transfer
# streams).  Conservative midpoint; the bench's steady_state config
# measures the real crossover per deployment.
SCATTER_MAX_FRACTION = 0.5


class GuardWatch:
    """Detects device-guard transitions the arena must invalidate on.

    A breaker state change (device died, or recovered via a half-open
    probe) means cached device buffers may live on the wrong/dead side of
    the fallback boundary; a CPU-fallback call while the breaker is still
    CLOSED (threshold not yet hit) is the same hazard one call earlier.
    ``resync`` re-reads the counters after the arena's own guarded
    uploads so the arena's fallbacks don't count as fresh transitions
    (that would re-invalidate every call while degraded)."""

    def __init__(self):
        self._mark = None

    def _read(self, guard) -> tuple:
        return (guard.breaker.state, guard.fallback_calls)

    def transitioned(self, guard) -> bool:
        mark = self._read(guard)
        if self._mark is None:
            self._mark = mark
            return False
        prev_state, prev_fallbacks = self._mark
        state, fallbacks = mark
        self._mark = mark
        if state != prev_state:
            return True
        # Fallbacks with the breaker still closed: transient degradation
        # that already ran host-side against possibly-device buffers.
        from ..utils.deviceguard import CLOSED
        return state == CLOSED and fallbacks != prev_fallbacks

    def resync(self, guard) -> None:
        self._mark = self._read(guard)


class DeviceStateCache:
    """Device-resident mutable node state (idle/releasing/room) updated by
    row scatters.

    ``_host`` mirrors exactly what the device arrays hold, so a new
    Session adopting the cache can diff its (snapshot-fresh) mirrors
    against it and scatter only the rows that actually moved — whether
    they moved because the watch stream delivered cluster changes or
    because the previous cycle's statements committed placements."""

    def __init__(self):
        # Device residency is scheduler-thread-owned (single-writer):
        # dispatch/scatter happen on the cycle path only.
        # kairace: single-writer=main
        self._dev: tuple | None = None    # (idle, rel, room) device arrays
        # kairace: single-writer=main
        self._host: tuple | None = None   # matching host copies
        self._owner = None                # session the cache is synced to

    @property
    def resident(self) -> bool:
        return self._dev is not None

    def invalidate(self) -> None:
        self._dev = None
        self._host = None
        self._owner = None

    def _upload(self, session, idle, rel, room) -> tuple:
        import jax.numpy as jnp

        def thunk():
            return (jnp.asarray(idle), jnp.asarray(rel), jnp.asarray(room))

        self._dev = session.dispatch_kernel(thunk, label="arena_state_upload")
        self._host = (np.array(idle, np.float64),
                      np.array(rel, np.float64),
                      np.array(room, np.float64))
        return self._dev

    def _changed_rows(self, session) -> np.ndarray:
        """Rows whose host mirrors differ from what the device holds."""
        if self._owner is session:
            # In-session mutations are tracked at the source
            # (Session.sync_node / the native bulk path).
            rows = np.fromiter(session._dirty_rows, np.int64,
                               count=len(session._dirty_rows))
            rows.sort()
            return rows
        # Cross-cycle adoption: one vectorized diff is exact whatever
        # happened in between (binds the scheduler committed, watch
        # deltas, statement mutations of the previous session).
        h_idle, h_rel, h_room = self._host
        diff = (h_idle != session.node_idle).any(axis=1)
        diff |= (h_rel != session.node_releasing).any(axis=1)
        diff |= h_room != session.node_room
        return np.nonzero(diff)[0]

    def arrays(self, session) -> tuple:
        import jax.numpy as jnp

        idle, rel, room = (session.node_idle, session.node_releasing,
                           session.node_room)
        n = idle.shape[0]
        if self._host is not None and self._host[0].shape != idle.shape:
            self.invalidate()  # node bucket grew: shapes no longer match
        if self._dev is None:
            dev = self._upload(session, idle, rel, room)
            self._owner = session
            session._dirty_rows.clear()
            return dev
        rows = self._changed_rows(session)
        self._owner = session
        session._dirty_rows.clear()
        if rows.size == 0:
            return self._dev
        if rows.size > n * SCATTER_MAX_FRACTION:
            METRICS.inc("arena_state_full_upload_total")
            return self._upload(session, idle, rel, room)
        # Pad the row axis to a pow2 bucket so the scatter kernel compiles
        # a handful of shapes, not one per K; padding repeats the first
        # real row with its own value (an idempotent write).
        k = int(rows.size)
        k_pad = _next_pow2(k)
        rows_pad = np.full(k_pad, rows[0], np.int64)
        rows_pad[:k] = rows
        # Slice values in the RESIDENT dtype: the host mirrors are f64
        # (exact diffing) but the device arrays follow the backend's
        # default width — converting here is one fused host pass, where
        # an f64 np array handed to jnp.asarray under 32-bit mode pays a
        # separate conversion copy per scatter.
        dt = np.dtype(self._dev[0].dtype)
        idle_v = np.ascontiguousarray(idle[rows_pad], dt)
        rel_v = np.ascontiguousarray(rel[rows_pad], dt)
        room_v = np.ascontiguousarray(room[rows_pad], dt)
        dev = self._dev
        from ..ops.arena import apply_deltas_kernel
        with TRACER.span("arena_scatter", kind="arena_scatter",
                         rows=k, padded=k_pad):
            self._dev = session.dispatch_kernel(
                lambda: apply_deltas_kernel(
                    dev[0], dev[1], dev[2], jnp.asarray(rows_pad),
                    jnp.asarray(idle_v), jnp.asarray(rel_v),
                    jnp.asarray(room_v)),
                label="arena_scatter",
                validate=lambda r: (getattr(r[0], "shape", None)
                                    == dev[0].shape))
        METRICS.inc("arena_scatter_rows", k)
        h_idle, h_rel, h_room = self._host
        h_idle[rows] = idle[rows]
        h_rel[rows] = rel[rows]
        h_room[rows] = room[rows]
        return self._dev


class ClusterArena:
    """Cross-cycle pack + device residency cache, one per ClusterCache.

    Producer side (``ClusterCache.snapshot`` on the scheduler thread):
    ``note_nodes``/``note_tasks``/``note_vocab``/``note_full`` accumulate
    the dirty set derived from the watch-updated store since the last
    pack; ``stamp`` marks the ClusterInfo as this arena's latest view.

    Consumer side (``Session.__init__`` / ``Session._device_arrays``, same
    thread): ``pack`` turns the accumulated delta into a SnapshotTensors
    (incremental when safe, full rebuild otherwise), ``device_arrays``
    serves the resident device tensors."""

    def __init__(self):
        # Single-writer structure (DESIGN §9): the scheduler thread owns
        # every arena mutation — watch hooks mark dirt through the
        # cache's queued changes, never here.  The annotations are
        # machine-checked by kairace KRC003 (docs/STATIC_ANALYSIS.md).
        # kairace: single-writer=main
        self.generation = 0
        # kairace: single-writer=main
        self._prev: SnapshotTensors | None = None
        # kairace: single-writer=main
        self._prev_pad: int | None = None
        # kairace: single-writer=main
        self._prev_usage: dict | None = None
        self._prev_node_order: list | None = None
        # Accumulated dirty state since the last pack.
        # kairace: single-writer=main
        self._dirty_nodes: set[str] = set()
        # kairace: single-writer=main
        self._tasks_dirty = True
        # kairace: single-writer=main
        self._vocab_dirty = False
        self._full_reason: str | None = "first-snapshot"
        # Stamp: only the owning cache's LATEST snapshot may take the
        # delta path (an older/foreign ClusterInfo packs from scratch).
        # kairace: single-writer=main
        self._stamp = 0
        self._latest_stamp: int | None = None
        # Device residency.
        self.state = DeviceStateCache()
        self._static_dev: tuple | None = None
        self._static_gen = -1
        self.guard_watch = GuardWatch()
        self.last_pack: dict = {}

    # -- producer side (ClusterCache.snapshot) -----------------------------
    def note_nodes(self, names) -> None:
        self._dirty_nodes.update(names)

    def note_tasks(self) -> None:
        self._tasks_dirty = True

    def note_vocab(self) -> None:
        """A selector/toleration-bearing pod changed: the label codec (and
        the task-array widths derived from it) may shift — delta packs
        must not trust the previous vocabulary."""
        self._vocab_dirty = True
        self._tasks_dirty = True

    def note_full(self, reason: str) -> None:
        if self._full_reason is None:
            self._full_reason = reason

    def stamp(self, cluster) -> None:
        self._stamp += 1
        self._latest_stamp = self._stamp
        cluster.arena_stamp = self._stamp

    def invalidate(self, reason: str) -> None:
        """Wholesale invalidation (watch resync, explicit operator
        action): the next pack rebuilds from scratch and the device side
        re-uploads."""
        self.note_full(reason)
        self.drop_device(reason)

    def drop_device(self, reason: str) -> None:
        if self._static_dev is not None or self.state.resident:
            METRICS.inc("arena_device_invalidation_total")
            LOG.v(1).info("arena: device caches dropped (%s)", reason)
        self._static_dev = None
        self._static_gen = -1
        self.state.invalidate()

    # -- pack --------------------------------------------------------------
    def _full_rebuild_reason(self, cluster, pad_nodes_to,
                             queue_usage) -> str | None:
        if self._full_reason is not None:
            return self._full_reason
        if self._prev is None:
            return "no-previous-pack"
        if getattr(cluster, "arena_stamp", None) != self._latest_stamp:
            return "unstamped-cluster"
        if pad_nodes_to != self._prev_pad:
            return "node-bucket-growth"
        if self._vocab_dirty:
            return "vocab-change"
        if cluster.node_order != self._prev.node_names:
            return "topology-change"
        return None

    @staticmethod
    def _usage_equal(a, b) -> bool:
        if a is None and b is None:
            return True
        if a is None or b is None or set(a) != set(b):
            return False
        return all(np.array_equal(a[k], b[k]) for k in a)

    def pack(self, cluster, queue_usage=None,
             pad_nodes_to: int | None = None
             ) -> tuple[SnapshotTensors, dict]:
        """Pack ``cluster`` for one Session, reusing the previous cycle's
        arrays where the accumulated delta proves them unchanged.  Always
        bit-identical to ``api.snapshot.pack`` on the same cluster."""
        with TRACER.span("snapshot_delta", kind="snapshot_delta") as sp:
            t0 = time.perf_counter()
            reason = self._full_rebuild_reason(cluster, pad_nodes_to,
                                               queue_usage)
            snap = None
            rows = None
            if reason is None:
                reuse_tasks = (not self._tasks_dirty
                               and self._usage_equal(queue_usage,
                                                     self._prev_usage))
                try:
                    snap, rows = pack_incremental(
                        cluster, self._prev, self._dirty_nodes,
                        queue_usage=queue_usage, pad_nodes_to=pad_nodes_to,
                        reuse_tasks=reuse_tasks)
                except Exception as exc:
                    # A delta that cannot be applied must degrade to a
                    # rebuild, never crash the cycle; the property suite
                    # keeps this branch honest (it asserts delta packs DO
                    # happen, so a silent always-fallback would fail).
                    LOG.warning("arena: incremental pack failed (%r); "
                                "falling back to full rebuild", exc)
                    reason = "delta-error"
                    snap = None
            if snap is None:
                snap = pack(cluster, queue_usage=queue_usage,
                            pad_nodes_to=pad_nodes_to)
                self.generation += 1
                METRICS.inc("arena_full_rebuild_total")
            self._prev = snap
            self._prev_pad = pad_nodes_to
            self._prev_usage = queue_usage
            stamp = getattr(cluster, "arena_stamp", None)
            if stamp is not None and stamp == self._latest_stamp:
                # The baseline now matches the latest snapshot: the dirty
                # accumulation restarts from here.
                self._dirty_nodes = set()
                self._tasks_dirty = False
                self._vocab_dirty = False
                self._full_reason = None
            else:
                # A stale/foreign cluster became the baseline: the dirty
                # set no longer describes "changes since the baseline",
                # so the next pack must rebuild regardless.
                self._full_reason = "stale-baseline"
            n = max(1, len(cluster.node_order))
            ratio = 1.0 if rows is None else len(rows) / n
            METRICS.set_gauge("snapshot_delta_ratio", ratio)
            stats = {
                "full_rebuild": rows is None,
                "reason": reason or "",
                "changed_rows": (n if rows is None else int(len(rows))),
                "total_rows": n,
                "delta_ratio": round(ratio, 6),
                "generation": self.generation,
                "pack_s": round(time.perf_counter() - t0, 6),
            }
            self.last_pack = stats
            sp.set(**stats)
        return snap, stats

    # -- device residency --------------------------------------------------
    def device_static(self, snap: SnapshotTensors, session) -> tuple:
        """(allocatable, labels, taints) device arrays, uploaded once per
        arena generation and reused across Sessions (the static tensors
        are shared by reference across delta packs, so a generation match
        proves the device copies current)."""
        import jax.numpy as jnp

        s = self._static_dev
        if s is not None and self._static_gen == self.generation \
                and s[0].shape == snap.node_allocatable.shape:
            return s

        def thunk():
            return (jnp.asarray(snap.node_allocatable),
                    jnp.asarray(snap.node_labels),
                    jnp.asarray(snap.node_taints))

        self._static_dev = session.dispatch_kernel(
            thunk, label="arena_static_upload")
        self._static_gen = self.generation
        return self._static_dev

    def device_arrays(self, snap: SnapshotTensors, session) -> tuple:
        """The kernel-input tuple (alloc, idle, rel, labels, taints, room)
        served from the resident caches; called on the cycle thread, every
        device touch routed through ``session.dispatch_kernel``."""
        from ..utils.deviceguard import device_guard
        guard = device_guard()
        if self.guard_watch.transitioned(guard):
            # Breaker flipped or a CPU fallback ran: device buffers may
            # sit on the dead/wrong side of the fallback boundary.
            self.drop_device("device-guard transition "
                             f"({guard.breaker.state})")
        t0 = time.perf_counter()
        alloc, labels, taints = self.device_static(snap, session)
        idle, rel, room = self.state.arrays(session)
        # The arena's own guarded uploads may themselves have fallen
        # back; absorbing them here keeps a degraded steady state from
        # re-invalidating (and re-uploading) on every call.
        self.guard_watch.resync(guard)
        dt = time.perf_counter() - t0
        session.phase_timings["arena_upload"] = \
            session.phase_timings.get("arena_upload", 0.0) + dt
        return (alloc, idle, rel, labels, taints, room)

    # -- observability -----------------------------------------------------
    def stats(self) -> dict:
        """Pack/residency stats for GET /debug/cycles."""
        return {
            "generation": self.generation,
            "last_pack": dict(self.last_pack),
            "device": {
                "static_resident": self._static_dev is not None,
                "state_resident": self.state.resident,
            },
            "full_rebuild_total": METRICS.counters.get(
                "arena_full_rebuild_total", 0),
            "scatter_rows_total": METRICS.counters.get(
                "arena_scatter_rows", 0),
        }
